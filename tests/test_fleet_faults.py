"""Fleet fault tolerance (ISSUE 14): chaos injection, failure
detection, circuit breakers, and exactly-once request redrive.

The battery pins the ISSUE acceptance: with a ChaosReplica killed
mid-burst, every accepted request completes or sheds with a structured
reason (0 silently lost), redriven greedy outputs are byte-identical
to a failure-free run, the breaker visibly opens → half-opens →
closes, and the steady state compiles nothing with detection +
breakers armed."""

import time

import numpy as np
import jax
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.serving import fleet
from paddle_tpu.serving.fleet.faults import BREAKER_GAUGE
from paddle_tpu.models.gpt import GPT, GPTConfig

VOCAB = 64


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_params, tracer=None, **kw):
    model, params = model_params
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_tokens_per_slot", 32)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("decode_block", 2)
    return serving.ServingEngine(model, params, attn_impl="lax",
                                 registry=obs.MetricsRegistry(),
                                 tracer=tracer, **kw)


def _fleet(model_params, n, tracer=None, faults=None, seed=0, clock=None,
           wrap=None, **kw):
    """n warmed LocalReplicas behind a FleetRouter; ``wrap`` maps
    replica index -> ChaosSpec kwargs for replicas to chaos-wrap."""
    tracer = tracer or obs.Tracer(enabled=False)
    reps = []
    for i in range(n):
        rep = fleet.LocalReplica(_engine(model_params, tracer=tracer,
                                         **kw), name=f"r{i}").warmup()
        if wrap and i in wrap:
            rep = fleet.ChaosReplica(rep, **wrap[i])
        reps.append(rep)
    router = fleet.FleetRouter(
        reps, registry=obs.MetricsRegistry(), tracer=tracer, seed=seed,
        faults=faults or fleet.FaultPolicy(max_consecutive_failures=1,
                                           probe_timeout_s=30.0),
        **({"clock": clock} if clock else {}))
    return router, reps


def _prompts(n, rng=None, lo=3, hi=9):
    rng = rng or np.random.default_rng(0)
    return [rng.integers(1, VOCAB, int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


_REF_ENGINE = {}


def _reference(model_params, prompts, max_new):
    """Failure-free reference: one clean engine, greedy decode. The
    engine is warmed once per module and reused (generate_many leaves
    it idle) — each warmup compiles every bucket and would otherwise
    dominate the battery's runtime."""
    eng = _REF_ENGINE.get(id(model_params))
    if eng is None:
        eng = _engine(model_params, num_slots=2)
        eng.warmup()
        _REF_ENGINE[id(model_params)] = eng
    return eng.generate_many(prompts, max_new, max_steps=100_000)


def _drain_fleet(router, frids, max_steps=5000):
    """Run to idle; every accepted request must end with a result or a
    structured reject (the no-silent-loss contract)."""
    steps = 0
    while not router.idle():
        router.step()
        steps += 1
        assert steps < max_steps, "fleet did not converge"
    outs, rejects = {}, {}
    for f in frids:
        r = router.result(f)
        if r is not None:
            outs[f] = r
        else:
            rejects[f] = router.reject_reason(f)
            assert rejects[f] is not None, \
                f"request {f} silently lost (no result, no reject)"
    return outs, rejects


# ---------------------------------------------------------------------------
# unit: chaos wrapper


class _InnerFake(fleet.ReplicaHandle):
    name = "inner"

    def __init__(self):
        self.steps = 0
        self.submits = 0

    def step(self):
        self.steps += 1
        return {}

    def submit(self, *a, **k):
        self.submits += 1
        return self.submits

    def health(self):
        return {"queue_depth": 1, "requests_in_flight": 0,
                "heartbeat_age_s": 0.0}

    def idle(self):
        return False


class TestChaosReplica:
    def test_crash_on_step_then_dead_host(self):
        c = fleet.ChaosReplica(_InnerFake(), crash_on_step=3)
        assert c.step() == {} and c.step() == {}
        with pytest.raises(fleet.ReplicaCrashed):
            c.step()
        # dead-host semantics: EVERY later op raises, inner untouched
        for op in (c.step, c.health, c.idle, lambda: c.submit([1], 4)):
            with pytest.raises(fleet.ReplicaCrashed):
                op()
        assert c.inner.steps == 2

    def test_submit_failures_then_heal(self):
        c = fleet.ChaosReplica(_InnerFake(), submit_failures=2)
        for _ in range(2):
            with pytest.raises(fleet.ReplicaUnavailable):
                c.submit([1], 4)
        assert c.submit([1], 4) == 1         # healed

    def test_hang_reports_stale_heartbeat_no_progress(self):
        c = fleet.ChaosReplica(_InnerFake(), hang_after_step=1)
        assert c.step() == {}
        assert c.hung and c.inner.steps == 0     # never reached inner
        assert c.health()["heartbeat_age_s"] == float("inf")
        assert c.idle() is False                 # work never finishes

    def test_corrupt_health_then_heal(self):
        c = fleet.ChaosReplica(_InnerFake(), health_failures=1)
        with pytest.raises(fleet.ReplicaUnavailable):
            c.health()
        assert c.health()["queue_depth"] == 1

    def test_seeded_schedule_deterministic(self):
        a = fleet.chaos_schedule(7, 8)
        b = fleet.chaos_schedule(7, 8)
        assert a == b and len(a) == 8
        assert fleet.chaos_schedule(8, 8) != a


# ---------------------------------------------------------------------------
# unit: circuit breaker + detector


class TestCircuitBreaker:
    def test_full_cycle_closed_open_halfopen_closed(self):
        clk = FakeClock()
        b = fleet.CircuitBreaker(threshold=2, cooldown_s=5.0, clock=clk)
        assert b.allow() and b.state == "closed"
        b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open" and not b.allow()
        clk.advance(4.9)
        assert not b.allow()                 # still cooling down
        clk.advance(0.2)
        assert b.allow() and b.state == "half_open"
        b.note_probe()
        assert not b.allow()                 # one probe at a time
        b.record_success()
        assert b.state == "closed" and b.allow()
        assert b.transitions == [("closed", "open"),
                                 ("open", "half_open"),
                                 ("half_open", "closed")]

    def test_probe_failure_reopens(self):
        clk = FakeClock()
        b = fleet.CircuitBreaker(threshold=1, cooldown_s=1.0, clock=clk)
        b.record_failure()
        clk.advance(1.1)
        assert b.allow()
        b.note_probe()
        b.record_failure()
        assert b.state == "open" and not b.allow()
        clk.advance(1.1)                     # cooldown restarted
        assert b.allow() and b.state == "half_open"

    def test_gauge_encoding_covers_states(self):
        assert set(BREAKER_GAUGE) == {"closed", "open", "half_open"}


class TestFailureDetector:
    def test_crash_is_immediately_terminal(self):
        d = fleet.FailureDetector(max_consecutive_failures=99)
        assert d.observe_failure("r", fleet.ReplicaCrashed("x")) == "crashed"

    def test_consecutive_threshold_with_reset(self):
        d = fleet.FailureDetector(max_consecutive_failures=3)
        e = fleet.ReplicaUnavailable("flake")
        assert d.observe_failure("r", e) is None
        assert d.observe_failure("r", e) is None
        d.observe_success("r")               # healed: count resets
        assert d.observe_failure("r", e) is None
        assert d.observe_failure("r", e) is None
        assert d.observe_failure("r", e) is not None

    def test_health_verdicts(self):
        d = fleet.FailureDetector(probe_timeout_s=5.0)
        assert d.check_health("r", {"failed": True,
                                    "last_error": "boom"}) is not None
        # stale heartbeat only matters while work is pending
        idle = {"heartbeat_age_s": 99.0, "queue_depth": 0,
                "requests_in_flight": 0}
        assert d.check_health("r", idle) is None
        busy = {"heartbeat_age_s": 99.0, "queue_depth": 1,
                "requests_in_flight": 0}
        assert d.check_health("r", busy) is not None


# ---------------------------------------------------------------------------
# integration: eject + exactly-once redrive


class TestEjectRedrive:
    def test_crash_mid_burst_zero_lost_bit_identical(self, model_params):
        """The acceptance battery: kill a replica mid-burst; nothing is
        lost and every redriven output is byte-identical to a
        failure-free run — with zero steady-state recompiles while
        detection + breakers are armed."""
        cap = 10
        prompts = _prompts(6)
        ref = _reference(model_params, prompts, cap)
        tracer = obs.Tracer()
        router, reps = _fleet(model_params, 3, tracer=tracer,
                              wrap={1: {}})
        chaos = reps[1]
        det = obs.RecompileDetector("fleet_chaos", warmup=0,
                                    registry=obs.MetricsRegistry())
        frids = [router.submit(p, cap) for p in prompts]
        # run until the chaos replica holds mid-decode work, then kill
        for _ in range(500):
            router.step()
            eng = chaos.inner.engine
            if any(0 < len(eng.scheduler.slots[i].generated) < cap
                   for i in eng.scheduler.decode_slots()):
                break
        else:
            pytest.skip("chaos replica never held mid-decode work")
        chaos.dead = True
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects, f"unexpected sheds: {rejects}"
        for f, want in zip(frids, ref):
            np.testing.assert_array_equal(outs[f], want)
        assert chaos not in router.replicas
        assert router.ejected_total == 1 and router.redrives_total >= 1
        det.check()
        assert det.recompiles == 0
        names = {s.name for s in tracer.spans()}
        assert "router.eject" in names and "router.redrive" in names

    def test_redrive_shares_original_trace_id(self, model_params):
        tracer = obs.Tracer()
        router, reps = _fleet(model_params, 2, tracer=tracer,
                              wrap={0: {}})
        frids = [router.submit(p, 6) for p in _prompts(3, lo=3, hi=5)]
        router.step()
        reps[0].dead = True
        _drain_fleet(router, frids)
        redrives = [s for s in tracer.spans()
                    if s.name == "router.redrive"]
        assert redrives
        req_tids = {s.trace_id for s in tracer.spans()
                    if s.name == "router.route"}
        assert all(s.trace_id in req_tids for s in redrives), \
            "redrive spans must ride the request's original trace"

    def test_queued_requests_reroute_on_eject(self, model_params):
        # more requests than the chaos replica can admit: its queue
        # must re-route (observed empty -> plain resubmit)
        router, reps = _fleet(model_params, 2, wrap={0: {}})
        prompts = _prompts(8, lo=3, hi=5)
        ref = _reference(model_params, prompts, 6)
        frids = [router.submit(p, 6) for p in prompts]
        reps[0].dead = True                  # dies before a single step
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects
        for f, want in zip(frids, ref):
            np.testing.assert_array_equal(outs[f], want)

    def test_redrive_budget_exhausted_sheds_structured(self,
                                                       model_params):
        router, reps = _fleet(
            model_params, 2,
            faults=fleet.FaultPolicy(max_consecutive_failures=1,
                                     max_redrives=0))
        frids = [router.submit(p, 6) for p in _prompts(2, lo=3, hi=5)]
        router.step()
        router.eject_replica(reps[0], reason="crashed")
        outs, rejects = _drain_fleet(router, frids)
        assert rejects, "budget 0 must shed the ejected replica's work"
        for rej in rejects.values():
            assert rej.reason == "redrive_budget"
        # reject is pop-on-read
        assert all(router.reject_reason(f) is None for f in rejects)

    def test_expired_deadline_redrive_sheds_structured(self,
                                                       model_params):
        clk = FakeClock()
        router, reps = _fleet(model_params, 2, clock=clk)
        # a queued-only request (no token observed) with a TTFT deadline
        frid = router.submit(_prompts(1)[0], 6, ttft_deadline_s=0.5)
        rep = router._where[frid][0]
        clk.advance(1.0)                     # deadline long gone
        router.eject_replica(rep, reason="crashed")
        rej = router.reject_reason(frid)
        assert rej is not None and rej.reason == "deadline_expired"
        reg = router._reg
        assert reg.counter("fleet_redrive_shed_total").value(
            reason="deadline_expired") == 1

    def test_engine_side_shed_surfaces_at_router(self, model_params):
        """A replica's OWN engine shedding a queued request (TTFT
        deadline expired before admission) must surface as a fleet
        reject — result XOR reject, never silence — and clean the
        replay record."""
        router, reps = _fleet(model_params, 1)
        # fill both slots so the probe request has to queue
        busy = [router.submit(p, 16) for p in _prompts(2, lo=3, hi=5)]
        router.step()
        doomed = router.submit(_prompts(1)[0], 8, ttft_deadline_s=0.01)
        time.sleep(0.05)                 # deadline passes while queued
        for _ in range(50):
            router.step()
            if doomed not in router._reqs:
                break
        rej = router.reject_reason(doomed)
        assert rej is not None and rej.reason == "deadline_expired"
        assert router.result(doomed) is None
        assert doomed not in router._reqs and doomed not in router._where
        assert router._reg.counter("fleet_replica_shed_total").value(
            reason="deadline_expired") == 1
        outs, rejects = _drain_fleet(router, busy)
        assert not rejects and len(outs) == 2

    def test_live_deadline_survives_redrive(self, model_params):
        clk = FakeClock()
        router, reps = _fleet(model_params, 2, clock=clk)
        prompts = _prompts(1)
        ref = _reference(model_params, prompts, 6)
        frid = router.submit(prompts[0], 6, ttft_deadline_s=60.0)
        rep = router._where[frid][0]
        router.eject_replica(rep, reason="crashed")
        outs, rejects = _drain_fleet(router, [frid])
        assert not rejects
        np.testing.assert_array_equal(outs[frid], ref[0])


class TestWarmRedrive:
    def test_micro_checkpoint_restores_on_peer(self, model_params):
        """With snapshot_every_blocks on, a crash redrives WARM: the
        newest checkpoint restores into a peer (bounded re-decode) and
        outputs stay byte-identical."""
        cap = 12
        prompts = _prompts(2, lo=3, hi=5)
        ref = _reference(model_params, prompts, cap)
        tracer = obs.Tracer()
        router, reps = _fleet(model_params, 2, tracer=tracer,
                              wrap={0: {}}, snapshot_every_blocks=1)
        chaos = reps[0]
        frids = [router.submit(p, cap) for p in prompts]
        for _ in range(500):
            router.step()
            if any(rec.checkpoint is not None
                   for rec in router._reqs.values()):
                break
        else:
            pytest.fail("no micro-checkpoint ever reached the router")
        chaos.dead = True
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects
        for f, want in zip(frids, ref):
            np.testing.assert_array_equal(outs[f], want)
        warm = router._reg.counter("fleet_redrive_total").value(
            mode="warm")
        assert warm >= 1, "warm restore path never used"
        modes = {s.attrs.get("mode") for s in tracer.spans()
                 if s.name == "router.redrive"}
        assert "warm" in modes

    def test_engine_refuses_speculative_checkpoints(self, model_params):
        model, params = model_params
        with pytest.raises(ValueError):
            serving.ServingEngine(model, params, num_slots=2,
                                  page_size=4, max_tokens_per_slot=32,
                                  draft_model=model, draft_params=params,
                                  spec_k=2, snapshot_every_blocks=1,
                                  registry=obs.MetricsRegistry())


class TestHangDetection:
    def test_hung_replica_ejected_work_redriven(self, model_params):
        prompts = _prompts(4, lo=3, hi=5)
        ref = _reference(model_params, prompts, 6)
        router, reps = _fleet(
            model_params, 2, wrap={1: {"hang_after_step": 2}},
            faults=fleet.FaultPolicy(max_consecutive_failures=1,
                                     probe_timeout_s=5.0))
        frids = [router.submit(p, 6) for p in prompts]
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects
        assert router.ejected_total == 1
        assert reps[1] not in router.replicas
        for f, want in zip(frids, ref):
            np.testing.assert_array_equal(outs[f], want)


class TestThreadDeathSurfaced:
    def test_background_loop_crash_marks_replica_failed(self,
                                                        model_params):
        """Satellite regression: a raising step() in the background
        loop must not die silently — last_error recorded, failed set,
        health()/running() see it."""
        rep = fleet.LocalReplica(_engine(model_params), name="t0")
        rep.warmup()
        orig_step = rep.engine.step

        def boom():
            raise RuntimeError("kaboom in step")

        rep.engine.step = boom
        rep.start(idle_sleep_s=0.001)
        rep.submit(_prompts(1)[0], 4)
        for _ in range(200):
            if rep.failed:
                break
            time.sleep(0.01)
        assert rep.failed and "kaboom" in rep.last_error
        assert rep.running() is False
        h = rep.health()
        assert h["failed"] and "kaboom" in h["last_error"]
        rep.stop()
        rep.engine.step = orig_step
        with pytest.raises(RuntimeError):
            rep.start()                      # no zombie restarts

    def test_router_ejects_failed_thread_replica(self, model_params):
        prompts = _prompts(2, lo=3, hi=5)
        ref = _reference(model_params, prompts, 6)
        router, reps = _fleet(model_params, 2)
        bad = reps[0]
        frids = [router.submit(p, 6) for p in prompts]
        # simulate what the background loop records on a step crash
        bad.failed = True
        bad.last_error = "RuntimeError: kaboom in step"
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects
        assert bad not in router.replicas
        assert router._reg.counter("fleet_ejected_total").value(
            reason="replica_failed") == 1
        for f, want in zip(frids, ref):
            np.testing.assert_array_equal(outs[f], want)


class TestDrainVsCrashRace:
    def test_crash_mid_drain_falls_through_to_redrive(self,
                                                      model_params):
        """A replica that dies after drain_queue but before migration
        completes must not lose its in-flight requests — they fall
        through to the redrive path."""
        cap = 10
        prompts = _prompts(4)
        ref = _reference(model_params, prompts, cap)
        router, reps = _fleet(model_params, 2,
                              wrap={1: {"crash_on_snapshot": True}})
        chaos = reps[1]
        frids = [router.submit(p, cap) for p in prompts]
        for _ in range(500):
            router.step()
            eng = chaos.inner.engine
            if any(0 < len(eng.scheduler.slots[i].generated) < cap
                   for i in eng.scheduler.decode_slots()):
                break
        else:
            pytest.skip("no mid-decode window on the chaos replica")
        router.drain_replica(chaos)          # dies at snapshot time
        assert chaos not in router.replicas
        assert router._reg.counter("fleet_drain_crash_total").value() == 1
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects
        for f, want in zip(frids, ref):
            np.testing.assert_array_equal(outs[f], want)


# ---------------------------------------------------------------------------
# integration: circuit breaker through the router


class TestBreakerThroughRouter:
    def test_open_halfopen_closed_visible(self, model_params):
        clk = FakeClock()
        tracer = obs.Tracer()
        router, reps = _fleet(
            model_params, 2, tracer=tracer, clock=clk,
            wrap={1: {"submit_failures": 2}},
            faults=fleet.FaultPolicy(max_consecutive_failures=10,
                                     breaker_threshold=2,
                                     breaker_cooldown_s=5.0))
        # enough submits that p2c hits the flaky replica twice: its
        # breaker opens; the caller never sees a failure (peer retry)
        frids = [router.submit(p, 4) for p in _prompts(6, lo=3, hi=5)]
        name = reps[1].name
        assert (name, "closed", "open") in router.breaker_transitions
        assert not router.is_routable(reps[1])
        assert router.routable_count() == 1
        h = router.health()
        assert h["degraded"] and h["breakers"][name]["state"] == "open"
        outs, rejects = _drain_fleet(router, frids)
        assert not rejects and len(outs) == 6
        # cooldown passes; the next submit is routed as the deliberate
        # half-open probe; the chaos replica has healed -> closed
        clk.advance(6.0)
        probe = router.submit(_prompts(1)[0], 4)
        assert (name, "open", "half_open") in router.breaker_transitions
        assert (name, "half_open", "closed") in router.breaker_transitions
        assert router._where[probe][0] is reps[1], \
            "half-open probe must be routed to the recovering replica"
        outs, rejects = _drain_fleet(router, [probe])
        assert not rejects
        states = [s.attrs["to"] for s in tracer.spans()
                  if s.name == "fleet.breaker"]
        assert states == ["open", "half_open", "closed"]
        g = router._reg.gauge("fleet_breaker_state")
        assert g.value(replica=name) == BREAKER_GAUGE["closed"]

    def test_transient_health_flap_quarantines_not_ejects(self,
                                                          model_params):
        """A transiently flaky health endpoint must trip the breaker
        (quarantine, which also stops the probing) BEFORE the
        consecutive-failure count reaches the death verdict — the
        replica stays in the fleet and recovers through the half-open
        probe."""
        router, reps = _fleet(
            model_params, 2, wrap={0: {"health_failures": 3}},
            faults=fleet.FaultPolicy(max_consecutive_failures=5,
                                     breaker_threshold=3,
                                     breaker_cooldown_s=0.0))
        name = reps[0].name
        for _ in range(6):               # idle fleet: probes flake
            router.step()
        assert reps[0] in router.replicas, "flake must not eject"
        assert router.ejected_total == 0
        assert (name, "closed", "open") in router.breaker_transitions
        # endpoint healed: the next submit probes the breaker shut and
        # the replica serves again
        frid = router.submit(_prompts(1)[0], 4)
        outs, rejects = _drain_fleet(router, [frid])
        assert not rejects
        assert (name, "half_open", "closed") in router.breaker_transitions

    def test_disabled_policy_restores_pr9_behavior(self, model_params):
        router, reps = _fleet(model_params, 2,
                              faults=fleet.FaultPolicy(enabled=False),
                              wrap={0: {"crash_on_step": 1}})
        # p2c balances, so a few submits guarantee the chaos replica
        # holds work and gets stepped (a lone request may land on the
        # healthy peer and never touch it)
        for p in _prompts(4, lo=3, hi=5):
            router.submit(p, 4)
        assert not reps[0].inner.engine.scheduler.idle()
        with pytest.raises(fleet.ReplicaCrashed):
            router.run_until_idle(max_steps=50)
        # PR 9 contract: with faults disabled, health errors surface
        # instead of degrading to error-dicts / infinite load
        with pytest.raises(fleet.ReplicaCrashed):
            router.health()
        with pytest.raises(fleet.ReplicaCrashed):
            router._load(reps[0])


# ---------------------------------------------------------------------------
# autoscaler: lost capacity -> replacement


class _HealthFake(fleet.ReplicaHandle):
    def __init__(self, name, occupancy=0.5):
        self.name = name
        self.draining = False
        self.warmed = 0
        self.occupancy = occupancy

    def health(self):
        return {"queue_depth": 0, "requests_in_flight": 0,
                "slot_occupancy": self.occupancy, "slo": {}}

    def idle(self):
        return True

    def warmup(self):
        self.warmed += 1
        return self


class TestAutoscalerReplace:
    def _make(self, clk, n=2, min_replicas=2, max_replicas=4,
              occupancy=0.5, **asc_kw):
        spawned = []

        def spawn(i):
            r = _HealthFake(f"spawn{i}", occupancy=occupancy)
            spawned.append(r)
            return r

        asc = fleet.FleetAutoscaler(spawn, min_replicas=min_replicas,
                                    max_replicas=max_replicas,
                                    cooldown_s=10.0,
                                    registry=obs.MetricsRegistry(),
                                    clock=clk, **asc_kw)
        router = fleet.FleetRouter(
            [_HealthFake(f"f{i}", occupancy=occupancy)
             for i in range(n)],
            registry=obs.MetricsRegistry(),
            tracer=obs.Tracer(enabled=False), autoscaler=asc, clock=clk)
        return router, asc, spawned

    def test_ejection_below_floor_spawns_warmed_replacement(self):
        clk = FakeClock()
        router, asc, spawned = self._make(clk)
        router.eject_replica(router.replicas[0], reason="crashed")
        assert asc.tick() == "replace"
        assert len(spawned) == 1 and spawned[0].warmed == 1
        assert spawned[0] in router.replicas
        assert asc.events[-1]["action"] == "replace"
        # cooldown: an immediate second loss does not flap-spawn
        router.eject_replica(router.replicas[0], reason="crashed")
        assert asc.tick() is None
        clk.advance(11.0)
        assert asc.tick() == "replace"

    def test_open_breaker_counts_as_lost_capacity(self):
        clk = FakeClock()
        router, asc, spawned = self._make(clk)
        b = router._breaker(router.replicas[0])
        for _ in range(b.threshold):
            b.record_failure()
        assert router.routable_count() == 1
        assert asc.tick() == "replace"
        assert len(spawned) == 1

    def test_scale_in_with_no_routable_victim_is_a_noop(self):
        """Fleet-wide breaker flap at max_replicas: _scale_in must find
        no victim and return None — never crash the serve loop with
        min() over an empty sequence."""
        clk = FakeClock()
        router, asc, spawned = self._make(clk, n=2, min_replicas=1,
                                          max_replicas=2, occupancy=0.0,
                                          idle_s=1.0)
        for rep in router.replicas:
            b = router._breaker(rep)
            for _ in range(b.threshold):
                b.record_failure()
        assert router.routable_count() == 0
        assert asc.tick() is None        # starts the idle clock
        clk.advance(2.0)
        assert asc.tick() is None        # idle long enough: no victim
        assert not spawned and len(router.replicas) == 2

    def test_drain_never_replaced(self):
        clk = FakeClock()
        router, asc, spawned = self._make(clk, n=3, min_replicas=1)
        # voluntary shrink: replicas drop to 2, routable 2 >= min 1
        router.replicas[0].draining = True
        router.replicas.remove(router.replicas[0])
        assert asc.tick() is None
        assert not spawned


# ---------------------------------------------------------------------------
# exposition: the fleet-breaker /healthz section


class TestHealthzFleetBreakers:
    def test_degraded_503_while_breaker_open(self, model_params):
        router, reps = _fleet(model_params, 2)
        monitor = fleet.FleetMonitor(router,
                                     registry=obs.MetricsRegistry())
        srv = obs.ExpositionServer(registry=monitor.reg,
                                   tracer=router.tracer)
        srv.add_health("fleet", monitor.collect)
        status, payload = srv.healthz()
        assert status == "ok"
        b = router._breaker(reps[0])
        for _ in range(b.threshold):
            b.record_failure()
        status, payload = srv.healthz()
        assert status == "degraded"
        sect = payload["providers"]["fleet"]
        assert sect["breakers"][reps[0].name]["state"] == "open"
        assert sect["routable"] == 1
        assert monitor.reg.gauge("fleet_routable_replicas").value() == 1
