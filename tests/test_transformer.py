"""Transformer stack tests: flash kernel vs XLA reference, MHA, BERT.

Modeled on the reference's OpTest parity pattern (op_test.py:135 — compare
kernel output against a python-computed expectation) applied to the fused
attention path, plus book-style end-to-end model smoke tests.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow  # excluded from the quick CI gate


from paddle_tpu.models.bert import BertConfig, BertForPretraining, BertModel
from paddle_tpu.nn.transformer import (MultiHeadAttention,
                                       TransformerDecoderLayer,
                                       TransformerEncoderLayer)
from paddle_tpu.ops import attention as A


def _qkv(key, b=2, h=2, sq=128, sk=128, d=32, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, h, sq, d), dtype),
            jax.random.normal(kk, (b, h, sk, d), dtype),
            jax.random.normal(kv, (b, h, sk, d), dtype))


class TestFlashAttention:
    def test_matches_xla_plain(self):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        ref = A.scaled_dot_product_attention(q, k, v)
        out = A.flash_attention(q, k, v, None, False, None, 64, 64, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_matches_xla_causal(self):
        q, k, v = _qkv(jax.random.PRNGKey(1))
        ref = A.scaled_dot_product_attention(q, k, v, causal=True)
        out = A.flash_attention(q, k, v, None, True, None, 64, 64, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_matches_xla_padding_bias(self):
        q, k, v = _qkv(jax.random.PRNGKey(2), b=2, sq=64, sk=64)
        mask = jnp.arange(64)[None, :] < jnp.array([40, 64])[:, None]
        bias = A.make_padding_bias(mask)
        ref = A.scaled_dot_product_attention(q, k, v, bias=bias)
        out = A.flash_attention(q, k, v, bias, False, None, 32, 32, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_full_bias(self):
        q, k, v = _qkv(jax.random.PRNGKey(3), b=1, h=1, sq=64, sk=64)
        bias = jax.random.normal(jax.random.PRNGKey(4), (1, 1, 64, 64))
        ref = A.scaled_dot_product_attention(q, k, v, bias=bias)
        out = A.flash_attention(q, k, v, bias, False, None, 32, 32, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_uneven_seq_blocks(self):
        # seq not a multiple of the block size exercises the tail masking
        q, k, v = _qkv(jax.random.PRNGKey(5), sq=96, sk=96)
        ref = A.scaled_dot_product_attention(q, k, v)
        out = A.flash_attention(q, k, v, None, False, None, 64, 64, True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)

    def test_grads_match_xla(self):
        q, k, v = _qkv(jax.random.PRNGKey(6), sq=64, sk=64)

        def f_ref(q, k, v):
            return A.scaled_dot_product_attention(q, k, v,
                                                  causal=True).sum()

        def f_flash(q, k, v):
            return A.flash_attention(q, k, v, None, True, None,
                                     32, 32, True).sum()

        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)


class TestMHA:
    def test_self_attention_shapes(self):
        mha = MultiHeadAttention(32, 4, attn_impl="xla")
        params = mha.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 16, 32))
        out = mha(params, x)
        assert out.shape == (2, 16, 32)

    def test_cross_attention(self):
        mha = MultiHeadAttention(32, 4, self_attention=False, attn_impl="xla")
        params = mha.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 32))
        mem = jax.random.normal(jax.random.PRNGKey(2), (2, 20, 32))
        out = mha(params, x, mem)
        assert out.shape == (2, 10, 32)

    def test_causal_is_causal(self):
        """Changing a future token must not change earlier outputs."""
        mha = MultiHeadAttention(16, 2, causal=True, attn_impl="xla")
        params = mha.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 16))
        x2 = x.at[0, 7].set(99.0)
        o1, o2 = mha(params, x), mha(params, x2)
        np.testing.assert_allclose(np.asarray(o1[0, :7]),
                                   np.asarray(o2[0, :7]), atol=1e-5)


class TestEncoderDecoder:
    def test_encoder_layer(self):
        layer = TransformerEncoderLayer(32, 4, 64, dropout=0.0,
                                        attn_impl="xla")
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 12, 32))
        out = layer(params, x)
        assert out.shape == x.shape
        assert not np.isnan(np.asarray(out)).any()

    def test_decoder_layer(self):
        layer = TransformerDecoderLayer(32, 4, 64, dropout=0.0,
                                        attn_impl="xla")
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 10, 32))
        mem = jax.random.normal(jax.random.PRNGKey(2), (2, 14, 32))
        out = layer(params, x, mem)
        assert out.shape == x.shape

    @pytest.mark.parametrize("pre_ln", [False, True])
    def test_pre_post_ln(self, pre_ln):
        layer = TransformerEncoderLayer(32, 4, 64, dropout=0.0,
                                        pre_ln=pre_ln, attn_impl="xla")
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (1, 8, 32))
        assert layer(params, x).shape == x.shape


class TestBert:
    def test_forward_shapes(self):
        cfg = BertConfig.tiny(attn_impl="xla")
        model = BertModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ids = jnp.zeros((2, 16), jnp.int32)
        seq, pooled = model(params, ids)
        assert seq.shape == (2, 16, cfg.hidden_size)
        assert pooled.shape == (2, cfg.hidden_size)

    def test_pretraining_loss_and_train_step(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.train import build_train_step, make_train_state

        cfg = BertConfig.tiny(attn_impl="xla", dropout=0.0, attn_dropout=0.0)
        model = BertForPretraining(cfg)
        optimizer = opt.AdamW(learning_rate=1e-3)
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

        b, s = 2, 16
        batch = dict(
            input_ids=jnp.ones((b, s), jnp.int32),
            token_type_ids=jnp.zeros((b, s), jnp.int32),
            attention_mask=jnp.ones((b, s), bool),
            mlm_labels=jnp.ones((b, s), jnp.int32),
            mlm_mask=jnp.ones((b, s), jnp.float32),
            nsp_labels=jnp.zeros((b,), jnp.int32),
        )

        def loss_fn(params, **batch):
            return model.loss(params, training=False, **batch)

        step = jax.jit(build_train_step(loss_fn, optimizer))
        losses = []
        for _ in range(4):
            state, metrics = step(state, **batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]  # it learns the constant batch
        assert not np.isnan(losses).any()

    def test_padding_mask_effective(self):
        cfg = BertConfig.tiny(attn_impl="xla", dropout=0.0, attn_dropout=0.0)
        model = BertModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ids = jnp.ones((1, 16), jnp.int32)
        mask = jnp.arange(16)[None, :] < 8
        ids2 = ids.at[0, 12].set(7)  # change a PADDED position
        seq1, _ = model(params, ids, attention_mask=mask)
        seq2, _ = model(params, ids2, attention_mask=mask)
        np.testing.assert_allclose(np.asarray(seq1[0, :8]),
                                   np.asarray(seq2[0, :8]), atol=1e-5)


class TestFlashBackward:
    """Pallas bwd kernels vs XLA-autodiff grads (OpTest grad-check analog)."""

    def _grads(self, f, *args):
        return jax.grad(lambda *a: f(*a).sum(), argnums=(0, 1, 2))(*args)

    def test_plain_uneven_blocks(self):
        q, k, v = _qkv(jax.random.PRNGKey(10), sq=96, sk=96)
        g_ref = self._grads(
            lambda q, k, v: A.scaled_dot_product_attention(q, k, v), q, k, v)
        g_fl = self._grads(
            lambda q, k, v: A.flash_attention(q, k, v, None, False, None,
                                              64, 64, True), q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_padding_bias_grads(self):
        q, k, v = _qkv(jax.random.PRNGKey(11), sq=64, sk=64)
        mask = jnp.arange(64)[None, :] < jnp.array([40, 64])[:, None]
        bias = A.make_padding_bias(mask)
        g_ref = self._grads(
            lambda q, k, v: A.scaled_dot_product_attention(q, k, v,
                                                           bias=bias),
            q, k, v)
        g_fl = self._grads(
            lambda q, k, v: A.flash_attention(q, k, v, bias, False, None,
                                              32, 32, True), q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_causal_rectangular(self):
        # decoder-style Sq < Sk (cached prefix)
        q, k, v = _qkv(jax.random.PRNGKey(12), sq=32, sk=64)
        g_ref = self._grads(
            lambda q, k, v: A.scaled_dot_product_attention(q, k, v,
                                                           causal=True),
            q, k, v)
        g_fl = self._grads(
            lambda q, k, v: A.flash_attention(q, k, v, None, True, None,
                                              32, 32, True), q, k, v)
        for a, b in zip(g_fl, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=2e-4)

    def test_full_bias_gets_bias_grad(self):
        # full-bias path must fall back to XLA and return a bias cotangent
        q, k, v = _qkv(jax.random.PRNGKey(13), b=1, h=1, sq=32, sk=32)
        bias = jax.random.normal(jax.random.PRNGKey(14), (1, 1, 32, 32))

        def f(bias):
            return A.flash_attention(q, k, v, bias, False, None,
                                     16, 16, True).sum()

        def f_ref(bias):
            return A.scaled_dot_product_attention(q, k, v, bias=bias).sum()

        g = jax.grad(f)(bias)
        g_ref = jax.grad(f_ref)(bias)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=2e-4, rtol=2e-4)
        assert float(jnp.abs(g).sum()) > 0

    def test_bias_cotangent_matches_caller_shape(self):
        # sub-4D biases must get grads in their ORIGINAL shape
        q, k, v = _qkv(jax.random.PRNGKey(15), b=1, h=1, sq=16, sk=16)
        for shape in [(16, 16), (16,), (1, 1, 16, 16)]:
            bias = jnp.zeros(shape)
            g = jax.grad(lambda b: A.flash_attention(
                q, k, v, b, False, None, 16, 16, True).sum())(bias)
            assert g.shape == shape, (g.shape, shape)

    def test_empty_row_grads_not_inflated(self):
        # a fully-masked query row must not pollute dk/dv with seq_k-scaled
        # garbage (lse degenerates to NEG_INF for such rows)
        q, k, v = _qkv(jax.random.PRNGKey(16), b=2, h=1, sq=8, sk=8)
        mask = jnp.stack([jnp.zeros(8, bool), jnp.ones(8, bool)])  # row0 empty
        bias = A.make_padding_bias(mask)

        def f(v):
            return A.flash_attention(q, k, v, bias, False, None,
                                     8, 8, True)[1].sum()  # loss on batch 1

        dv = jax.grad(f)(v)
        # batch 0 (the empty-mask batch) contributes nothing to this loss
        np.testing.assert_allclose(np.asarray(dv[0]), 0.0, atol=1e-6)
        assert float(jnp.abs(dv[1]).sum()) > 0
