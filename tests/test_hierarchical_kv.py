"""Hierarchical KV (ISSUE 20): HBM->host spill of published prefix
pages with byte-identical restore (fp + int8), the randomized
two-tier allocator property battery, fleet-global prefix fetch over
hash-chained migration shards, fetch-under-churn degradation (drain /
crash / scale-in / corruption — never a lost or wrong request), and
the stale-affinity generation fix."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.serving import fleet
from paddle_tpu.serving.engine import (PREFIX_BUNDLE_FORMAT,
                                       SlotMigrationError)
from paddle_tpu.serving.fleet.faults import (ChaosReplica, ChaosSpec,
                                             FaultPolicy,
                                             ReplicaUnavailable)
from paddle_tpu.serving.paged_cache import (HostPagePool, SpilledPage,
                                            payload_digest,
                                            prompt_prefix_digests)
from paddle_tpu.models.gpt import GPT, GPTConfig

VOCAB = 64


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_params, **kw):
    model, params = model_params
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_tokens_per_slot", 44)
    kw.setdefault("prefill_chunk", 4)
    return serving.ServingEngine(model, params, attn_impl="lax",
                                 registry=obs.MetricsRegistry(), **kw)


def _prefix(seed=1, n=16):
    return np.random.default_rng(seed).integers(1, VOCAB, n).astype(
        np.int32)


def _spill_schedule(eng, prefix, rng):
    """Publish ``prefix``, evict it to the host pool with filler
    pressure, then hit it again. Returns the three generated outputs."""
    outs = []
    p1 = np.concatenate([prefix, rng.integers(1, VOCAB, 3).astype(np.int32)])
    outs.append(eng.generate_many([p1], 6, max_steps=10_000)[0])
    filler = np.arange(1, 33, dtype=np.int32) % (VOCAB - 1) + 1
    outs.append(eng.generate_many([filler], 6, max_steps=10_000)[0])
    p2 = np.concatenate([prefix, rng.integers(1, VOCAB, 2).astype(np.int32)])
    outs.append(eng.generate_many([p2], 6, max_steps=10_000)[0])
    return outs


class TestHostPagePool:
    def _entry(self, key, fill):
        payload = (np.full((2, 1, 4, 2, 4), fill, np.int8),)
        return SpilledPage(key=key, tokens=np.arange(4, dtype=np.int32),
                           payload=payload,
                           sha256=payload_digest(payload),
                           nbytes=payload[0].nbytes)

    def test_capacity_drops_lru(self):
        pool = HostPagePool(2)
        for k in (1, 2, 3):
            pool.put(self._entry(k, k))
        assert pool.keys() == frozenset({2, 3})
        assert pool.dropped_total == 1
        assert pool.spilled_total == 3
        assert len(pool) == 2 <= pool.capacity

    def test_get_refreshes_lru_and_gen_tracks_drops(self):
        pool = HostPagePool(2)
        pool.put(self._entry(1, 1))
        pool.put(self._entry(2, 2))
        g = pool.gen
        assert pool.get(1) is not None      # 1 becomes hot
        pool.put(self._entry(3, 3))         # 2 is the LRU victim
        assert pool.keys() == frozenset({1, 3})
        assert pool.gen > g, "a dropped entry must bump the generation"

    def test_rejects_useless_capacity(self):
        with pytest.raises(ValueError):
            HostPagePool(0)


class TestSpillRestore:
    def test_restore_is_byte_identical(self, model_params):
        eng = _engine(model_params, num_pages=12, host_spill_pages=8)
        eng.warmup()
        rng = np.random.default_rng(0)
        prefix = _prefix()
        p1 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 3).astype(np.int32)])
        eng.generate_many([p1], 6, max_steps=10_000)
        # golden bytes of every published full prefix page, pre-spill
        golden = {}
        for key, pid in eng.cache._full_index.items():
            if eng.cache._page_pub.get(pid, (None,))[0] == "full":
                golden[key] = tuple(np.asarray(a).copy()
                                    for a in eng._spill_read(pid))
        filler = np.arange(1, 33, dtype=np.int32) % (VOCAB - 1) + 1
        eng.generate_many([filler], 6, max_steps=10_000)
        pool = eng.cache.spill_pool
        assert len(pool) > 0, "pressure did not spill any published page"
        for ent in pool.entries():
            assert payload_digest(ent.payload) == ent.sha256
        p2 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 2).astype(np.int32)])
        eng.generate_many([p2], 6, max_steps=10_000)
        assert pool.restored_total > 0, "prefix hit restored nothing"
        # restored device content must equal the pre-spill bytes
        checked = 0
        for key, want in golden.items():
            pid = eng.cache._full_index.get(key)
            if pid is None:
                continue
            got = eng._spill_read(pid)
            for w, g in zip(want, got):
                np.testing.assert_array_equal(np.asarray(w),
                                              np.asarray(g))
            checked += 1
        assert checked > 0
        eng.cache.check_invariants()

    @pytest.mark.slow
    @pytest.mark.parametrize("cache_dtype", [None, jnp.int8])
    def test_greedy_bit_identical_and_zero_recompiles(self, model_params,
                                                      cache_dtype):
        prefix = _prefix()
        base = _engine(model_params, num_pages=12, host_spill_pages=0,
                       cache_dtype=cache_dtype)
        base.warmup()
        outs_base = _spill_schedule(base, prefix, np.random.default_rng(0))
        eng = _engine(model_params, num_pages=12, host_spill_pages=8,
                      cache_dtype=cache_dtype)
        eng.warmup()
        outs = _spill_schedule(eng, prefix, np.random.default_rng(0))
        pool = eng.cache.spill_pool
        assert pool.spilled_total > 0 and pool.restored_total > 0
        if cache_dtype is jnp.int8:
            # int8 scale rows travel WITH their pages: two host arrays
            for ent in pool.entries():
                assert len(ent.payload) == 2
        for a, b in zip(outs_base, outs):
            np.testing.assert_array_equal(a, b)
        assert eng.health()["recompiles"] == 0, \
            "spill/restore must ride the warmed page_read/page_write"
        eng.cache.check_invariants()

    @pytest.mark.slow
    def test_spill_headroom_and_gauges(self, model_params):
        eng = _engine(model_params, num_pages=12, host_spill_pages=8)
        eng.warmup()
        assert eng.health()["headroom"]["spill"] == 1.0
        _spill_schedule(eng, _prefix(), np.random.default_rng(0))
        h = eng.health()
        assert 0.0 <= h["headroom"]["spill"] < 1.0
        assert h["headroom"]["spill_pages"] == len(eng.cache.spill_pool)
        assert eng._reg.gauge("serving_spill_pages").value() == \
            len(eng.cache.spill_pool)
        assert eng._reg.counter(
            "serving_spill_restored_pages_total").value() > 0

    def test_disabled_tier_has_no_pool(self, model_params):
        eng = _engine(model_params, num_pages=12)
        assert eng.cache.spill_pool is None
        assert eng.health()["headroom"]["spill"] == 1.0


class TestHierarchyProperty:
    pytestmark = pytest.mark.slow  # excluded from the quick CI gate

    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("cache_dtype", [None, jnp.int8])
    def test_randomized_two_tier_schedule(self, model_params, seed,
                                          cache_dtype):
        """Interleaved admit/publish/spill/restore/CoW/free under a
        tiny page pool hold the allocator invariants (which now include
        host-pool sha verification and device/host disjointness) and
        produce exactly the no-spill engine's greedy tokens."""
        rng = np.random.default_rng(seed)
        prefixes = [rng.integers(1, VOCAB, n).astype(np.int32)
                    for n in (8, 12, 16)]
        prompts = []
        for _ in range(12):
            roll = rng.random()
            if roll < 0.7:      # shared-prefix traffic (publish + CoW)
                pre = prefixes[rng.integers(len(prefixes))]
                tail = rng.integers(1, VOCAB,
                                    rng.integers(1, 5)).astype(np.int32)
                prompts.append(np.concatenate([pre, tail]))
            else:               # unique filler (eviction pressure)
                prompts.append(rng.integers(1, VOCAB, 24).astype(np.int32))
        want, got = [], []
        for spill in (0, 6):
            eng = _engine(model_params, num_pages=14,
                          host_spill_pages=spill, cache_dtype=cache_dtype)
            eng.warmup()
            outs = want if spill == 0 else got
            for i in range(0, len(prompts), 3):
                outs.extend(eng.generate_many(prompts[i:i + 3], 5,
                                              max_steps=10_000))
                eng.cache.check_invariants()
            if spill:
                assert eng.cache.spill_pool.spilled_total > 0
                assert eng.health()["recompiles"] == 0
        for a, b in zip(want, got):
            np.testing.assert_array_equal(a, b)


def _fleet(model_params, n, prefix_fetch=True, faults=None, chaos=None,
           **kw):
    kw.setdefault("num_pages", 24)
    kw.setdefault("host_spill_pages", 8)
    reps = []
    for i in range(n):
        r = fleet.LocalReplica(_engine(model_params, **kw),
                               name=f"r{i}").warmup()
        if chaos and i in chaos:
            r = ChaosReplica(r, chaos[i])
        reps.append(r)
    router = fleet.FleetRouter(reps, registry=obs.MetricsRegistry(),
                               tracer=obs.Tracer(enabled=False),
                               prefix_fetch=prefix_fetch, faults=faults)
    return router, reps


def _publish_and_drain(router, reps, prefix, rng):
    """Run one shared-prefix request, then drain whichever replica
    published the pages — the next same-prefix submit MUST route
    elsewhere. Returns (first_output, holder, miss_target)."""
    p1 = np.concatenate([prefix, rng.integers(1, VOCAB, 3).astype(np.int32)])
    f1 = router.submit(p1, 6)
    out1 = router.run_until_idle(2_000)[f1]
    holder = next(r for r in reps if r.prefix_digests())
    holder.draining = True
    other = next(r for r in reps if r is not holder)
    return out1, holder, other


class TestFleetPrefixFetch:
    @pytest.mark.slow
    def test_miss_fetches_from_holder_bit_identical(self, model_params):
        prefix = _prefix()
        outs = {}
        for pf in (False, True):
            rng = np.random.default_rng(0)
            router, reps = _fleet(model_params, 2, prefix_fetch=pf)
            out1, holder, other = _publish_and_drain(router, reps,
                                                     prefix, rng)
            p2 = np.concatenate([prefix,
                                 rng.integers(1, VOCAB, 2).astype(np.int32)])
            f2 = router.submit(p2, 6)
            out2 = router.run_until_idle(2_000)[f2]
            outs[pf] = (out1, out2)
            reg = router._reg
            fetched = reg.counter("fleet_prefix_fetch_pages_total").value()
            shared = other.engine._reg.counter(
                "serving_prefix_shared_tokens_total").value()
            if pf:
                assert fetched > 0, "miss did not fetch from the holder"
                assert shared >= 4 * fetched
                assert reg.counter("fleet_prefix_fetch_total").value(
                    src=holder.name, dst=other.name) == 1
                assert reg.counter(
                    "fleet_prefix_fetch_bytes_total").value() > 0
            else:
                assert fetched == 0 and shared == 0
            for r in reps:
                r.engine.cache.check_invariants()
                assert r.engine.health()["recompiles"] == 0
        for a, b in zip(outs[False], outs[True]):
            np.testing.assert_array_equal(a, b)

    def test_engine_export_import_roundtrip(self, model_params):
        src = _engine(model_params, num_pages=24, host_spill_pages=8)
        dst = _engine(model_params, num_pages=24, host_spill_pages=8)
        src.warmup(), dst.warmup()
        prefix = _prefix()
        rng = np.random.default_rng(0)
        p1 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 3).astype(np.int32)])
        src.generate_many([p1], 6, max_steps=10_000)
        digests = prompt_prefix_digests(p1, 4)
        bundle = src.export_prefix_pages(digests)
        assert bundle["format"] == PREFIX_BUNDLE_FORMAT
        assert len(bundle["pages"]) == len(digests)
        assert dst.import_prefix_pages(bundle) == len(digests)
        # idempotent: everything already held installs nothing
        assert dst.import_prefix_pages(bundle) == 0
        assert set(digests) <= dst.cache.advertised_digests()
        dst.cache.check_invariants()
        out_dst = dst.generate_many([p1], 6, max_steps=10_000)[0]
        out_src = src.generate_many([p1], 6, max_steps=10_000)[0]
        np.testing.assert_array_equal(out_src, out_dst)
        assert dst._reg.counter(
            "serving_prefix_shared_tokens_total").value() >= 16

    @pytest.mark.slow
    def test_export_covers_spilled_pages(self, model_params):
        """A host-spilled page is still exportable — the whole point of
        advertising the spill tier fleet-wide."""
        src = _engine(model_params, num_pages=12, host_spill_pages=8)
        src.warmup()
        prefix = _prefix()
        _spill_schedule(src, prefix, np.random.default_rng(0))
        digests = prompt_prefix_digests(prefix, 4)
        spilled = src.cache.spill_pool.keys()
        assert spilled, "schedule did not leave spilled pages"
        bundle = src.export_prefix_pages(digests)
        assert bundle is not None
        assert {int(p["key"]) for p in bundle["pages"]} >= set(
            d for d in digests if d in spilled)

    @pytest.mark.slow
    def test_holder_crash_mid_fetch_degrades(self, model_params):
        prefix = _prefix()
        rng = np.random.default_rng(0)
        chaos = None
        router, reps = _fleet(model_params, 2,
                              faults=FaultPolicy())
        out1, holder, other = _publish_and_drain(router, reps, prefix, rng)
        # the holder dies exactly when the fetch reaches for its pages
        idx = reps.index(holder)
        reps[idx] = ChaosReplica(holder, ChaosSpec(crash_on_export=True))
        reps[idx].draining = True   # the wrapper must stay draining too
        router.replicas[router.replicas.index(holder)] = reps[idx]
        p2 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 2).astype(np.int32)])
        f2 = router.submit(p2, 6)
        out2 = router.run_until_idle(2_000).get(f2)
        assert out2 is not None, "request lost to a mid-fetch crash"
        reg = router._reg
        assert reg.counter("fleet_prefix_fetch_failed_total").value(
            reason="transport") >= 1
        assert reg.counter(
            "fleet_prefix_fetch_degraded_total").value() >= 1
        assert reg.counter("fleet_prefix_fetch_pages_total").value() == 0
        # degraded = local re-prefill: bit-identical to a no-fetch fleet
        rng = np.random.default_rng(0)
        router2, reps2 = _fleet(model_params, 2, prefix_fetch=False)
        ref1, _h, _o = _publish_and_drain(router2, reps2, prefix, rng)
        p2r = np.concatenate([prefix,
                              rng.integers(1, VOCAB, 2).astype(np.int32)])
        fr2 = router2.submit(p2r, 6)
        ref2 = router2.run_until_idle(2_000)[fr2]
        np.testing.assert_array_equal(out1, ref1)
        np.testing.assert_array_equal(out2, ref2)

    @pytest.mark.slow
    def test_holder_scaled_in_mid_fetch_degrades(self, model_params):
        """The holder advertises, then vanishes (autoscaler scale-in)
        before the export lands: the fetch degrades with a structured
        marker and the request re-prefills locally."""
        prefix = _prefix()
        rng = np.random.default_rng(0)
        router, reps = _fleet(model_params, 2)
        out1, holder, other = _publish_and_drain(router, reps, prefix, rng)

        real_export = holder.export_prefix_pages

        def vanished(digests):
            raise ReplicaUnavailable("chaos: scaled in mid-fetch")

        holder.export_prefix_pages = vanished
        p2 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 2).astype(np.int32)])
        f2 = router.submit(p2, 6)
        out2 = router.run_until_idle(2_000).get(f2)
        assert out2 is not None
        reg = router._reg
        assert reg.counter(
            "fleet_prefix_fetch_degraded_total").value() >= 1
        assert reg.counter("fleet_prefix_fetch_pages_total").value() == 0
        holder.export_prefix_pages = real_export
        for r in reps:
            r.engine.cache.check_invariants()

    @pytest.mark.slow
    def test_corrupt_bundle_refused_not_installed(self, model_params):
        prefix = _prefix()
        rng = np.random.default_rng(0)
        router, reps = _fleet(model_params, 2)
        out1, holder, other = _publish_and_drain(router, reps, prefix, rng)

        real_export = holder.export_prefix_pages

        def tampered(digests):
            bundle = real_export(digests)
            shard = bundle["pages"][0]["shards"][0]
            kv = np.asarray(shard[0] if isinstance(shard, tuple)
                            else shard).copy()
            kv.view(np.uint8).flat[0] ^= 1  # one bit of KV rot
            if isinstance(shard, tuple):
                bundle["pages"][0]["shards"][0] = (kv, shard[1])
            else:
                bundle["pages"][0]["shards"][0] = kv
            return bundle

        holder.export_prefix_pages = tampered
        p2 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 2).astype(np.int32)])
        f2 = router.submit(p2, 6)
        out2 = router.run_until_idle(2_000).get(f2)
        assert out2 is not None, "request lost to a corrupt bundle"
        reg = router._reg
        assert reg.counter(
            "fleet_prefix_fetch_refused_total").value() == 1
        assert reg.counter(
            "fleet_prefix_fetch_degraded_total").value() >= 1
        # a refused bundle installs NOTHING via the fetch path (the
        # pages now advertised were published by serving p2 locally)
        assert reg.counter("fleet_prefix_fetch_pages_total").value() == 0
        other.engine.cache.check_invariants()
        holder.export_prefix_pages = real_export

    @pytest.mark.slow
    def test_unprovable_chain_refused(self, model_params):
        """A bundle whose keys do not hash-chain over its own token
        content is refused outright — shard hashes alone do not make
        pages trustworthy as PUBLISHED prefix state."""
        src = _engine(model_params, num_pages=24, host_spill_pages=8)
        dst = _engine(model_params, num_pages=24, host_spill_pages=8)
        src.warmup(), dst.warmup()
        prefix = _prefix()
        src.generate_many([np.concatenate([prefix, prefix[:3]])], 6,
                          max_steps=10_000)
        bundle = src.export_prefix_pages(prompt_prefix_digests(prefix, 4))
        bundle["pages"][0]["key"] = int(bundle["pages"][0]["key"]) ^ 1
        with pytest.raises(SlotMigrationError):
            dst.import_prefix_pages(bundle)
        dst.cache.check_invariants()

    @pytest.mark.slow
    def test_import_never_evicts_published_pages(self, model_params):
        """All-or-nothing capacity: a bundle larger than the idle free
        pool is refused instead of evicting local published pages."""
        src = _engine(model_params, num_pages=24, host_spill_pages=8)
        dst = _engine(model_params, num_pages=6, host_spill_pages=8)
        src.warmup(), dst.warmup()
        prefix = _prefix()
        src.generate_many([np.concatenate([prefix, prefix[:3]])], 6,
                          max_steps=10_000)
        bundle = src.export_prefix_pages(prompt_prefix_digests(prefix, 4))
        need = len(bundle["pages"])
        assert need > dst.cache.idle_free_pages or need > 0
        if need > dst.cache.idle_free_pages:
            with pytest.raises(SlotMigrationError):
                dst.import_prefix_pages(bundle)
            dst.cache.check_invariants()


class TestStaleAffinity:
    pytestmark = pytest.mark.slow  # excluded from the quick CI gate

    def test_prefix_gen_bumps_through_health(self, model_params):
        eng = _engine(model_params, num_pages=12, host_spill_pages=2)
        eng.warmup()
        rng = np.random.default_rng(0)
        prefix = _prefix()
        p1 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 3).astype(np.int32)])
        eng.generate_many([p1], 6, max_steps=10_000)
        rep = fleet.LocalReplica(eng, name="r0")
        g0 = rep.health()["prefix_gen"]
        adv0 = rep.prefix_digests()
        # pressure: published pages spill (pool holds 2, rest DROP)
        filler = np.arange(1, 33, dtype=np.int32) % (VOCAB - 1) + 1
        eng.generate_many([filler], 6, max_steps=10_000)
        g1 = rep.health()["prefix_gen"]
        assert g1 > g0, \
            "eviction/spill of a published page must bump prefix_gen"
        dropped = eng.cache.spill_pool.dropped_total
        assert dropped > 0, "tiny pool should have dropped spilled pages"
        # the filler published pages of its own, so compare what LEFT:
        # at least one of p1's advertised pages must be gone for good
        assert adv0 - rep.prefix_digests(), \
            "dropped pages must leave the advertisement"

    def test_affinity_miss_counter_on_stale_view(self, model_params):
        """A replica advertising pages it no longer holds gets the
        affinity route AND the miss counted — the regression signal the
        generation plumbing keeps at zero."""
        rng = np.random.default_rng(0)
        router, reps = _fleet(model_params, 2, prefix_fetch=False,
                              host_spill_pages=0, num_pages=12)
        prefix = _prefix()
        out1, holder, other = _publish_and_drain(router, reps, prefix, rng)
        holder.draining = False
        stale = holder.prefix_digests()
        assert stale
        # silently destroy the holder's pages, then freeze its
        # advertisement at the pre-eviction view
        filler = np.arange(1, 33, dtype=np.int32) % (VOCAB - 1) + 1
        holder.engine.generate_many([filler], 6, max_steps=10_000)
        assert not (set(stale) & holder.engine.cache.advertised_digests())
        holder.prefix_digests = lambda: stale
        p2 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 2).astype(np.int32)])
        f2 = router.submit(p2, 6)
        assert router.run_until_idle(2_000).get(f2) is not None
        assert router._reg.counter(
            "fleet_affinity_miss_total").value() == 1

    def test_no_miss_when_generation_propagates(self, model_params):
        """With live advertisements (the fix), the same eviction story
        routes by balance instead and the miss counter stays zero."""
        rng = np.random.default_rng(0)
        router, reps = _fleet(model_params, 2, prefix_fetch=False,
                              host_spill_pages=0, num_pages=12)
        prefix = _prefix()
        out1, holder, other = _publish_and_drain(router, reps, prefix, rng)
        holder.draining = False
        filler = np.arange(1, 33, dtype=np.int32) % (VOCAB - 1) + 1
        holder.engine.generate_many([filler], 6, max_steps=10_000)
        p2 = np.concatenate([prefix,
                             rng.integers(1, VOCAB, 2).astype(np.int32)])
        f2 = router.submit(p2, 6)
        assert router.run_until_idle(2_000).get(f2) is not None
        assert router._reg.counter(
            "fleet_affinity_miss_total").value() == 0
