"""Int8 paged KV cache (ISSUE 13): dequant-attend kernel parity,
quantized-engine greedy parity vs the bf16/fp32 cache, prefix-sharing /
CoW scale consistency, fleet migration of int8 slots (hash-verified
shards include scales), zero steady-state recompiles, and the static
bytes-reduction gate (cost-diff demonstrably fails at bf16-level
bytes)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import kernels
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving.paged_cache import (PagedCacheConfig, PagedKVCache,
                                            quantize_kv)


def _model(seed=0, **kw):
    cfg = GPTConfig.tiny(vocab_size=64, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla", **kw)
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompts(rng, lens):
    return [rng.integers(1, 64, n).astype(np.int32) for n in lens]


def _dense_reference(model, params, prompt, max_new):
    out = model.generate(params, jnp.asarray(prompt)[None],
                         max_new_tokens=max_new, use_cache=True)
    return np.asarray(out)[0, len(prompt):]


class TestQuantizeKV:
    def test_roundtrip_error_bounded(self):
        """Per-token abs-max int8: dequant error <= scale/2 per element
        (half an LSB), i.e. <= amax/254 — the quality budget the greedy
        parity rides on."""
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((5, 3, 8)), jnp.float32)
        q, scale = quantize_kv(x, (1, 2))
        assert q.dtype == jnp.int8 and scale.shape == (5,)
        deq = np.asarray(q, np.float32) * np.asarray(scale)[:, None, None]
        amax = np.max(np.abs(np.asarray(x)), axis=(1, 2))
        err = np.max(np.abs(deq - np.asarray(x)), axis=(1, 2))
        assert (err <= amax / 254.0 + 1e-7).all()

    def test_zero_row_harmless(self):
        q, scale = quantize_kv(jnp.zeros((2, 4)), (1,))
        assert (np.asarray(q) == 0).all()
        assert (np.asarray(scale) > 0).all()    # floored, no div-by-zero

    def test_quantized_pool_layout(self):
        c = PagedKVCache(PagedCacheConfig(
            num_layers=2, num_heads=2, head_dim=4, num_slots=2,
            page_size=4, num_pages=6, max_pages_per_slot=3,
            dtype=jnp.int8))
        assert c.config.quantized
        kp, vp, ks, vs = c.pages[0]
        assert kp.dtype == jnp.int8 and vp.dtype == jnp.int8
        assert ks.shape == (6, 4) and ks.dtype == jnp.float32
        # allocator state is dtype-agnostic: invariants hold untouched
        c.reserve(0, 9)
        c.check_invariants()
        c.free_slot(0)
        c.check_invariants()


class TestDequantAttendKernels:
    """The registered int8 kernels through the shared harness."""

    @pytest.mark.parametrize("name", ["ragged_paged_decode_int8",
                                      "ragged_paged_prefill_int8"])
    def test_parity_battery(self, name):
        for seed in (0, 1, 2):
            kernels.parity_check(name, seed)

    @pytest.mark.parametrize("name", ["ragged_paged_decode_int8",
                                      "ragged_paged_prefill_int8"])
    def test_pages_per_block_bit_equal(self, name):
        """The tunable streams N pages per grid step with an identical
        per-page accumulation order, so every setting is BIT-equal —
        tuning can never flip a greedy argmax (same contract as the fp
        kernels)."""
        spec = kernels.get(name)
        args, kwargs = spec.sample_inputs(1)
        ref = np.asarray(kernels.dispatch(
            name, *args, impl="pallas_interpret",
            block_sizes={"pages_per_block": 1}, **kwargs))
        for pb in (2, 4):
            out = np.asarray(kernels.dispatch(
                name, *args, impl="pallas_interpret",
                block_sizes={"pages_per_block": pb}, **kwargs))
            np.testing.assert_array_equal(out, ref)

    def test_stale_page_contents_ignored(self):
        """Poisoning pages (and scales) beyond the live extent must not
        change the int8 decode output."""
        spec = kernels.get("ragged_paged_decode_int8")
        (q, kp, vp, ks, vs, bt, _lens), _ = spec.sample_inputs(0)
        lens = jnp.asarray([3] + [0] * (q.shape[0] - 1), jnp.int32)
        ref = np.asarray(kernels.dispatch(
            "ragged_paged_decode_int8", q, kp, vp, ks, vs, bt, lens,
            impl="lax"))
        owned = int(bt[0, 0])
        pk, pv = np.asarray(kp).copy(), np.asarray(vp).copy()
        pks, pvs = np.asarray(ks).copy(), np.asarray(vs).copy()
        for pg in range(pk.shape[0]):
            if pg != owned:
                pk[pg] = 127
                pv[pg] = 127
                pks[pg] = 1e6
                pvs[pg] = 1e6
        pk[owned, 3:] = 127                   # dead tail of the live page
        pks[owned, 3:] = 1e6
        out = np.asarray(kernels.dispatch(
            "ragged_paged_decode_int8", q, jnp.asarray(pk),
            jnp.asarray(pv), jnp.asarray(pks), jnp.asarray(pvs), bt, lens,
            impl="lax"))
        np.testing.assert_allclose(out, ref, atol=1e-6, rtol=1e-6)


class TestInt8EngineParity:
    """ISSUE 13 quality gate: greedy tokens through the int8 cache
    match the bf16/fp32 cache on the serving parity battery. The pinned
    tolerance is EXACT token equality on this battery — per-token-row
    scales keep the dequant error around 0.4% of each row's abs-max,
    far inside the greedy argmax margins of these models."""

    def test_int8_matches_fp32_and_dense(self):
        model, params = _model()
        rng = np.random.default_rng(3)
        prompts = _prompts(rng, [5, 9, 3, 12, 7])

        def run(dtype):
            eng = serving.ServingEngine(
                model, params, num_slots=3, page_size=4, prefill_chunk=8,
                attn_impl="lax", cache_dtype=dtype)
            outs = eng.generate_many(prompts, max_new_tokens=6,
                                     max_steps=200)
            eng.cache.check_invariants()
            assert eng.cache.pages_in_use == 0
            return outs

        outs_fp = run(None)
        outs_bf = run(jnp.bfloat16)
        outs_q = run(jnp.int8)
        for p, fp, bf, q in zip(prompts, outs_fp, outs_bf, outs_q):
            ref = _dense_reference(model, params, p, 6)
            np.testing.assert_array_equal(fp, ref)
            np.testing.assert_array_equal(q, bf)
            np.testing.assert_array_equal(q, ref)

    def test_int8_through_interpret_kernels(self):
        """End-to-end through the REAL dequant-attend kernel bodies."""
        model, params = _model(seed=1)
        rng = np.random.default_rng(4)
        prompts = _prompts(rng, [4, 10])
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, prefill_chunk=8,
                                    attn_impl="pallas_interpret",
                                    cache_dtype=jnp.int8)
        outs = eng.generate_many(prompts, max_new_tokens=5, max_steps=100)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(
                o, _dense_reference(model, params, p, 5))

    def test_zero_steady_state_recompiles(self):
        model, params = _model()
        rng = np.random.default_rng(8)
        reg = obs.MetricsRegistry()
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, attn_impl="lax",
                                    cache_dtype=jnp.int8, registry=reg)
        eng.warmup()
        det = obs.RecompileDetector("int8_steady", warmup=0, registry=reg)
        eng.generate_many(_prompts(rng, [9, 4, 6]), max_new_tokens=4,
                          max_steps=100)
        det.check()
        assert det.recompiles == 0, "int8 steady state recompiled"

    def test_same_pool_hosts_twice_the_tokens(self):
        """The HBM claim: per-token page bytes roughly halve (int8 + a
        small scale overhead vs bf16)."""
        c8 = PagedKVCache(PagedCacheConfig(
            num_layers=1, num_heads=4, head_dim=32, num_slots=2,
            page_size=16, num_pages=8, max_pages_per_slot=4,
            dtype=jnp.int8))
        cb = PagedKVCache(PagedCacheConfig(
            num_layers=1, num_heads=4, head_dim=32, num_slots=2,
            page_size=16, num_pages=8, max_pages_per_slot=4,
            dtype=jnp.bfloat16))
        bytes8 = sum(a.size * a.dtype.itemsize for ent in c8.pages
                     for a in ent)
        bytesb = sum(a.size * a.dtype.itemsize for ent in cb.pages
                     for a in ent)
        assert bytes8 < 0.6 * bytesb


class TestInt8PrefixSharing:
    """Scales never diverge from their pages: sharing, CoW, and the
    cached pool all move (page, scale-rows) as one unit."""

    def test_identical_prompts_tail_cow_parity_int8(self):
        """The tail-CoW battery on an int8 engine: tokens stay exactly
        equal to the dense reference, the published source page AND its
        scale rows are never mutated by borrowers, and the CoW copy
        duplicates the scales with the page."""
        model, params = _model(seed=4)
        rng = np.random.default_rng(21)
        prompt = rng.integers(1, 64, 10).astype(np.int32)
        ref = _dense_reference(model, params, prompt, 6)
        eng = serving.ServingEngine(model, params, num_slots=1,
                                    page_size=4, prefill_chunk=8,
                                    attn_impl="lax", cache_dtype=jnp.int8)
        out0 = eng.generate_many([prompt.copy()], max_new_tokens=6,
                                 max_steps=100)[0]
        np.testing.assert_array_equal(out0, ref)
        shared_pages = np.asarray(sorted(eng.cache._page_pub))
        snap = {}
        for layer, (kp, vp, ks, vs) in enumerate(eng.cache.pages):
            snap[layer] = tuple(np.asarray(a[shared_pages])
                                for a in (kp, vp, ks, vs))
        tail_pid = next(iter(eng.cache._tail_index.values()))
        tail_tokens = len(eng.cache._page_tokens[tail_pid])
        for _ in range(2):
            out = eng.generate_many([prompt.copy()], max_new_tokens=6,
                                    max_steps=100)[0]
            np.testing.assert_array_equal(out, ref)
        assert eng.cache.cow_copies_total == 2
        for layer, (kp, vp, ks, vs) in enumerate(eng.cache.pages):
            now = tuple(np.asarray(a[shared_pages])
                        for a in (kp, vp, ks, vs))
            for j, pid in enumerate(shared_pages):
                t = tail_tokens if pid == tail_pid else None
                for a_now, a_snap in zip(now, snap[layer]):
                    np.testing.assert_array_equal(a_now[j][:t],
                                                  a_snap[j][:t])
        eng.cache.check_invariants()

    def test_randomized_refcount_invariants_int8(self):
        """The allocator property test on a quantized pool — refcounts,
        publication, and the free/cached/live partition are storage-
        dtype independent and must hold identically."""
        rng = np.random.default_rng(22)
        c = PagedKVCache(PagedCacheConfig(
            num_layers=1, num_heads=2, head_dim=4, num_slots=4,
            page_size=4, num_pages=14, max_pages_per_slot=4,
            dtype=jnp.int8))
        pool = [rng.integers(1, 9, n).astype(np.int32)
                for n in (6, 9, 10, 13, 10)]
        pool.append(pool[2].copy())
        live = {}
        for _step in range(300):
            op = rng.random()
            free_slots = [s for s in range(4) if s not in live]
            if op < 0.5 and free_slots:
                slot = int(rng.choice(free_slots))
                prompt = pool[int(rng.integers(len(pool)))]
                total = len(prompt) + int(rng.integers(1, 4))
                try:
                    shared = c.reserve(slot, total, prompt=prompt)
                except serving.PageOverflowError:
                    c.check_invariants()
                    continue
                assert 0 <= shared < len(prompt)
                live[slot] = (prompt, shared)
            elif op < 0.7 and live:
                slot = int(rng.choice(list(live)))
                if c.pending_copy(slot) is not None:
                    c.copy_done(slot)
                prompt, shared = live[slot]
                upto = int(rng.integers(shared, len(prompt) + 1))
                if c.pending_copy(slot) is None:
                    c.publish_prefix(slot, prompt, upto)
            elif live:
                slot = int(rng.choice(list(live)))
                c.free_slot(slot)
                del live[slot]
            c.check_invariants()
        for slot in list(live):
            c.free_slot(slot)
        c.check_invariants()
        assert c.pages_in_use == 0


class TestInt8Migration:
    """Fleet drain of an int8 slot: shards carry scales, hashes cover
    both, restore is byte-identical."""

    def _engine(self, model_params, **kw):
        model, params = model_params
        kw.setdefault("num_slots", 2)
        kw.setdefault("page_size", 4)
        kw.setdefault("max_tokens_per_slot", 48)
        kw.setdefault("attn_impl", "lax")
        kw.setdefault("cache_dtype", jnp.int8)
        kw.setdefault("decode_block", 2)
        return serving.ServingEngine(model, params, **kw)

    def _step_to_mid_decode(self, eng, cap, max_steps=50):
        for _ in range(max_steps):
            eng.step()
            mid = [i for i in eng.scheduler.decode_slots()
                   if 0 < len(eng.scheduler.slots[i].generated) < cap]
            if mid:
                return mid[0]
        raise AssertionError("no mid-decode window reached")

    @pytest.fixture(scope="class")
    def model_params(self):
        return _model(seed=5)

    def test_mid_decode_migration_byte_identical(self, model_params):
        model, params = model_params
        prompt = np.arange(1, 8, dtype=np.int32)
        ref = _dense_reference(model, params, prompt, 16)

        src = self._engine(model_params)
        src.warmup()
        src.submit(prompt, 16)
        slot = self._step_to_mid_decode(src, 16)
        snap = src.snapshot_slot(slot)
        # quantized shards are (kv int8, scales f32) pairs, hashed as one
        kv, sc = snap["shards"][0]
        assert kv.dtype == np.int8 and sc.dtype == np.float32
        assert snap["geometry"]["dtype"] == "int8"

        dst = self._engine(model_params)
        dst.warmup()
        rid = dst.restore_slot(snap)
        src.release_slot(slot)
        out = {}
        for _ in range(200):
            out.update(dst.step())
            if dst.scheduler.idle():
                break
        np.testing.assert_array_equal(out[rid], ref)
        # the restored pages + scales must be byte-identical: re-snapshot
        dst_slot_gone = dst.scheduler.active_slots() == []
        assert dst_slot_gone
        src.cache.check_invariants()
        dst.cache.check_invariants()

    def test_corrupt_scale_shard_refused(self, model_params):
        """A bit-flip in the SCALES (not the int8 KV) must be refused:
        the digest covers both halves of the shard."""
        src = self._engine(model_params)
        src.warmup()
        src.submit(np.arange(1, 8, dtype=np.int32), 24)
        snap = src.snapshot_slot(self._step_to_mid_decode(src, 24))
        kv, sc = snap["shards"][0]
        sc = sc.copy()
        sc.reshape(-1)[0] += 0.25
        snap["shards"][0] = (kv, sc)
        dst = self._engine(model_params)
        dst.warmup()
        with pytest.raises(serving.SlotMigrationError,
                           match="sha256 mismatch"):
            dst.restore_slot(snap)
        assert dst.scheduler.active_slots() == []
        dst.cache.check_invariants()

    def test_cross_dtype_restore_refused(self, model_params):
        """An int8 snapshot cannot restore into a bf16 engine (geometry
        pins the dtype)."""
        src = self._engine(model_params)
        src.warmup()
        src.submit(np.arange(1, 8, dtype=np.int32), 24)
        snap = src.snapshot_slot(self._step_to_mid_decode(src, 24))
        dst = self._engine(model_params, cache_dtype=jnp.bfloat16)
        with pytest.raises(serving.SlotMigrationError,
                           match="geometry mismatch"):
            dst.restore_slot(snap)


class TestInt8StaticBytes:
    """The PR 7 cost model proves the bytes-per-decode-step reduction
    statically, and the committed budget gate demonstrably FAILS if the
    int8 path regresses to bf16-level bytes."""

    def _lower(self, dtype):
        from paddle_tpu import analysis
        model, params = _model()
        eng = serving.ServingEngine(
            model, params, num_slots=4, page_size=8,
            max_tokens_per_slot=64, num_pages=513, attn_impl="lax",
            cache_dtype=dtype)
        c = eng.cache.config
        args = (analysis.abstractify(eng.params),
                analysis.abstractify(eng.cache.pages),
                jax.ShapeDtypeStruct((c.num_slots, c.max_pages_per_slot),
                                     jnp.int32),
                jax.ShapeDtypeStruct((c.num_slots,), jnp.int32),
                jax.ShapeDtypeStruct((c.num_slots,), jnp.int32),
                jax.ShapeDtypeStruct((c.num_slots,), jnp.int32))
        return analysis.estimate_cost(eng.decode_step, *args,
                                      name=f"decode_{dtype}")

    def _cost_diff(self):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "graph_lint", os.path.join(os.path.dirname(__file__),
                                       "..", "tools", "graph_lint.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.cost_diff

    def test_cost_diff_fails_at_bf16_bytes(self):
        cost_diff = self._cost_diff()
        cost8 = self._lower(jnp.int8)
        costb = self._lower(jnp.bfloat16)
        # the real claim: on a KV-dominated pool the int8 step moves
        # meaningfully fewer static bytes than the bf16 step
        assert costb.traffic_bytes > 1.1 * cost8.traffic_bytes
        budgets = {"tolerance": 0.10,
                   "surfaces": {"serving_decode_int8": cost8.summary()}}
        ok = cost_diff({"serving_decode_int8": cost8.summary()}, budgets,
                       out=lambda *_a: None)
        assert ok == 0
        regressed = cost_diff({"serving_decode_int8": costb.summary()},
                              budgets, out=lambda *_a: None)
        assert regressed == 1, ("bf16-level bytes did not trip the "
                                "int8 budget gate")
