"""GPipe pipeline-parallel tests: schedule parity vs sequential stack.

Reference test analog: fluid pipeline tests run SectionWorkers over scope
queues; here the whole schedule is traced, so parity with the plain
sequential stack is exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.parallel.pipeline import (gpipe, microbatch,
                                          stack_layer_params, unmicrobatch)


@pytest.fixture(scope="module")
def pp_mesh():
    return make_mesh(MeshConfig(pp=4, dp=2))


def _block(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def _make_layers(key, n_layers, dim):
    out = []
    for i in range(n_layers):
        k1, k2, key = jax.random.split(key, 3)
        out.append({"w": jax.random.normal(k1, (dim, dim)) * 0.3,
                    "b": jax.random.normal(k2, (dim,)) * 0.1})
    return out


class TestGPipe:
    def test_matches_sequential(self, pp_mesh):
        layers = _make_layers(jax.random.PRNGKey(0), 8, 16)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(1), (12, 4, 16))  # M=12 mbs

        ref = x
        for p in layers:
            ref = _block(p, ref)

        with mesh_context(pp_mesh):
            out = jax.jit(lambda sp, x: gpipe(
                _block, sp, x, mesh=pp_mesh))(stacked, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grads_match_sequential(self, pp_mesh):
        layers = _make_layers(jax.random.PRNGKey(2), 4, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 2, 8))

        def loss_pipe(sp):
            return gpipe(_block, sp, x, mesh=pp_mesh).sum()

        def loss_seq(sp):
            def body(h, lp):
                return _block(lp, h), None
            h, _ = jax.lax.scan(body, x, sp)
            return h.sum()

        with mesh_context(pp_mesh):
            g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_microbatch_roundtrip(self):
        batch = {"x": jnp.arange(24.0).reshape(12, 2)}
        mb = microbatch(batch, 4)
        assert mb["x"].shape == (4, 3, 2)
        back = unmicrobatch(mb)
        np.testing.assert_allclose(np.asarray(back["x"]),
                                   np.asarray(batch["x"]))

    def test_train_step_through_pipeline(self, pp_mesh):
        """End-to-end: pipelined MLP regression learns under jit."""
        layers = _make_layers(jax.random.PRNGKey(4), 4, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(5), (8, 4, 8))
        y = jax.random.normal(jax.random.PRNGKey(6), (8, 4, 8))

        def loss_fn(sp):
            out = gpipe(_block, sp, x, mesh=pp_mesh)
            return ((out - y) ** 2).mean()

        with mesh_context(pp_mesh):
            step = jax.jit(jax.value_and_grad(loss_fn))
            params = stacked
            losses = []
            for _ in range(10):
                loss, g = step(params)
                params = jax.tree_util.tree_map(
                    lambda p, gr: p - 0.1 * gr, params, g)
                losses.append(float(loss))
        assert losses[-1] < losses[0]
