"""GPipe pipeline-parallel tests: schedule parity vs sequential stack.

Reference test analog: fluid pipeline tests run SectionWorkers over scope
queues; here the whole schedule is traced, so parity with the plain
sequential stack is exact.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow  # excluded from the quick CI gate


from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.parallel.pipeline import (circular_pipeline, gpipe,
                                          microbatch,
                                          pipeline_bubble_fraction,
                                          stack_layer_params, unmicrobatch)


@pytest.fixture(scope="module")
def pp_mesh():
    return make_mesh(MeshConfig(pp=4, dp=2))


def _block(params, h, extra=None, mb_idx=None):
    h = jnp.tanh(h @ params["w"] + params["b"])
    if extra is not None:
        h = h + extra
    return h


def _make_layers(key, n_layers, dim):
    out = []
    for i in range(n_layers):
        k1, k2, key = jax.random.split(key, 3)
        out.append({"w": jax.random.normal(k1, (dim, dim)) * 0.3,
                    "b": jax.random.normal(k2, (dim,)) * 0.1})
    return out


class TestGPipe:
    def test_matches_sequential(self, pp_mesh):
        layers = _make_layers(jax.random.PRNGKey(0), 8, 16)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(1), (12, 4, 16))  # M=12 mbs

        ref = x
        for p in layers:
            ref = _block(p, ref)

        with mesh_context(pp_mesh):
            out = jax.jit(lambda sp, x: gpipe(
                _block, sp, x, mesh=pp_mesh))(stacked, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grads_match_sequential(self, pp_mesh):
        layers = _make_layers(jax.random.PRNGKey(2), 4, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(3), (8, 2, 8))

        def loss_pipe(sp):
            return gpipe(_block, sp, x, mesh=pp_mesh).sum()

        def loss_seq(sp):
            def body(h, lp):
                return _block(lp, h), None
            h, _ = jax.lax.scan(body, x, sp)
            return h.sum()

        with mesh_context(pp_mesh):
            g_pipe = jax.jit(jax.grad(loss_pipe))(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_extras_ride_the_ring(self, pp_mesh):
        """Per-microbatch side inputs (attention-bias analog) must follow
        their microbatch through every stage."""
        layers = _make_layers(jax.random.PRNGKey(7), 4, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(8), (6, 2, 8))
        extra = jax.random.normal(jax.random.PRNGKey(9), (6, 2, 8))

        ref = x
        for p in layers:
            ref = _block(p, ref, extra)

        with mesh_context(pp_mesh):
            out = jax.jit(lambda sp, x, e: gpipe(
                _block, sp, x, extras=e, mesh=pp_mesh))(stacked, x, extra)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_pp_sharded_extras_rejected(self, pp_mesh):
        """Extras are indexed locally and must be pp-replicated; a spec
        sharding them over the pp axis is a contract violation."""
        layers = _make_layers(jax.random.PRNGKey(7), 4, 8)
        stacked = stack_layer_params(layers)
        x = jnp.zeros((4, 2, 8))
        extra = jnp.zeros((4, 2, 8))
        from jax.sharding import PartitionSpec as P
        with mesh_context(pp_mesh):
            with pytest.raises(ValueError, match="pp-replicated"):
                gpipe(_block, stacked, x, extras=extra,
                      extras_spec=P("pp"), mesh=pp_mesh)

    def test_mb_idx_tracks_microbatch(self, pp_mesh):
        """The microbatch index delivered to the block must equal the true
        index of the microbatch being computed (dropout-PRNG contract)."""
        layers = _make_layers(jax.random.PRNGKey(0), 4, 4)
        stacked = stack_layer_params(layers)
        M = 6
        x = jnp.zeros((M, 1, 4))

        def block(p, h, extra, mb_idx):
            # write the index into the activation; every stage adds it, so
            # output = 4 * mb_idx if indices are delivered correctly
            return h + mb_idx.astype(h.dtype)

        with mesh_context(pp_mesh):
            out = jax.jit(lambda sp, x: gpipe(
                block, sp, x, mesh=pp_mesh))(stacked, x)
        expect = 4.0 * jnp.arange(M).reshape(M, 1, 1) * jnp.ones((M, 1, 4))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect))

    def test_circular_matches_gpipe_pp4(self, pp_mesh):
        """Interleaved 1F1B-circular schedule computes the same function
        as GPipe (pp=4, v=2, L=8, M=8)."""
        layers = _make_layers(jax.random.PRNGKey(10), 8, 16)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(11), (8, 4, 16))

        with mesh_context(pp_mesh):
            ref = jax.jit(lambda sp, x: gpipe(
                _block, sp, x, mesh=pp_mesh))(stacked, x)
            out = jax.jit(lambda sp, x: circular_pipeline(
                _block, sp, x, num_circuits=2, mesh=pp_mesh))(stacked, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_circular_matches_gpipe_pp2(self):
        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        layers = _make_layers(jax.random.PRNGKey(12), 8, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(13), (8, 2, 8))
        with mesh_context(mesh):
            ref = jax.jit(lambda sp, x: gpipe(
                _block, sp, x, mesh=mesh))(stacked, x)
            out = jax.jit(lambda sp, x: circular_pipeline(
                _block, sp, x, num_circuits=4, mesh=mesh))(stacked, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_circular_grads_match_sequential(self, pp_mesh):
        layers = _make_layers(jax.random.PRNGKey(14), 8, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(15), (8, 2, 8))

        def loss_circ(sp):
            return circular_pipeline(_block, sp, x, num_circuits=2,
                                     mesh=pp_mesh).sum()

        def loss_seq(sp):
            def body(h, lp):
                return _block(lp, h), None
            h, _ = jax.lax.scan(body, x, sp)
            return h.sum()

        with mesh_context(pp_mesh):
            g_circ = jax.jit(jax.grad(loss_circ))(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        for a, b in zip(jax.tree_util.tree_leaves(g_circ),
                        jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_circular_extras_and_mb_idx(self, pp_mesh):
        """Extras and microbatch indices stay glued to their microbatch
        across all circuits of the ring."""
        layers = _make_layers(jax.random.PRNGKey(16), 8, 4)
        stacked = stack_layer_params(layers)
        M = 8
        x = jnp.zeros((M, 1, 4))
        extras = 100.0 * jnp.arange(M, dtype=jnp.float32)

        def block(p, h, extra, mb_idx):
            # every chunk-layer adds extra + mb; 8 layers total
            return h + extra + mb_idx.astype(h.dtype)

        with mesh_context(pp_mesh):
            out = jax.jit(lambda sp, x, e: circular_pipeline(
                block, sp, x, num_circuits=2, extras=e,
                mesh=pp_mesh))(stacked, x, extras)
        expect = (8.0 * (100.0 * jnp.arange(M) + jnp.arange(M))
                  ).reshape(M, 1, 1) * jnp.ones((M, 1, 4))
        np.testing.assert_allclose(np.asarray(out), np.asarray(expect))

    def test_interleave_roundtrip_and_pre_interleaved(self, pp_mesh):
        """interleave_stack/uninterleave_stack invert each other, and a
        pre-interleaved layout (the recommended no-reshuffle path) gives
        the same result as arranging inside the step."""
        from paddle_tpu.parallel.pipeline import (interleave_stack,
                                                  uninterleave_stack)
        layers = _make_layers(jax.random.PRNGKey(20), 8, 8)
        stacked = stack_layer_params(layers)
        arranged = interleave_stack(stacked, 4, 2)
        back = uninterleave_stack(arranged, 4, 2)
        for a, b in zip(jax.tree_util.tree_leaves(back),
                        jax.tree_util.tree_leaves(stacked)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        x = jax.random.normal(jax.random.PRNGKey(21), (8, 2, 8))
        with mesh_context(pp_mesh):
            out1 = jax.jit(lambda sp, x: circular_pipeline(
                _block, sp, x, num_circuits=2, mesh=pp_mesh))(stacked, x)
            out2 = jax.jit(lambda sp, x: circular_pipeline(
                _block, sp, x, num_circuits=2, mesh=pp_mesh,
                pre_interleaved=True))(arranged, x)
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2),
                                   atol=1e-6, rtol=1e-6)

    def test_circular_rejects_short_streams(self, pp_mesh):
        layers = _make_layers(jax.random.PRNGKey(17), 8, 4)
        stacked = stack_layer_params(layers)
        with mesh_context(pp_mesh):
            with pytest.raises(ValueError, match="microbatches"):
                circular_pipeline(_block, stacked, jnp.zeros((2, 1, 4)),
                                  num_circuits=2, mesh=pp_mesh)

    def test_bubble_fraction_beats_gpipe(self):
        """The interleaved schedule's structural bubble is strictly below
        GPipe's for every v > 1 (VERDICT round-3 item 4)."""
        for n, M in [(2, 8), (4, 8), (4, 16)]:
            g = pipeline_bubble_fraction(n, M, 1)
            for v in (2, 4):
                c = pipeline_bubble_fraction(n, M, v)
                assert c < g, (n, M, v, c, g)
        # exact values: pp=4, M=8 -> GPipe 3/11, circular v=2 -> 3/19
        assert abs(pipeline_bubble_fraction(4, 8, 1) - 3 / 11) < 1e-12
        assert abs(pipeline_bubble_fraction(4, 8, 2) - 3 / 19) < 1e-12

    def test_circular_ticks_are_cheaper_than_gpipe_ticks(self, pp_mesh):
        """Wall-clock check of the schedules (tools/PIPELINE_TIMING.md):
        circular ticks apply 1/v of a GPipe stage's layers, so measured
        per-tick time must be strictly lower — the robust wall-clock
        property on any backend (full circ-beats-gpipe step time needs
        per-tick overhead << chunk compute, true on ICI, not on the CPU
        thread-rendezvous backend; the model + measurements live in
        tools/pipeline_bench.py)."""
        import time
        n, v, L, M, dim, mb = 4, 2, 8, 8, 768, 8
        key = jax.random.PRNGKey(0)
        layers = _make_layers(key, L, dim)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(1), (M, mb, dim))
        y = jax.random.normal(jax.random.PRNGKey(2), (M, mb, dim))

        def step_time(fn, params):
            def loss(sp, x, y):
                return jnp.mean((fn(sp, x) - y) ** 2)

            @jax.jit
            def step(sp, x, y):
                l, g = jax.value_and_grad(loss)(sp, x, y)
                return jax.tree_util.tree_map(
                    lambda p, gg: p - 1e-3 * gg, sp, g), l

            with mesh_context(pp_mesh):
                params, l = step(params, x, y)
                jax.block_until_ready(l)
                ts = []
                for _ in range(7):
                    t0 = time.perf_counter()
                    params, l = step(params, x, y)
                    jax.block_until_ready(l)
                    ts.append(time.perf_counter() - t0)
            return sorted(ts)[len(ts) // 2]

        t_g = step_time(
            lambda sp, x: gpipe(_block, sp, x, mesh=pp_mesh), stacked)
        from paddle_tpu.parallel.pipeline import interleave_stack
        t_c = step_time(
            lambda sp, x: circular_pipeline(
                _block, sp, x, num_circuits=v, mesh=pp_mesh,
                pre_interleaved=True),
            interleave_stack(stacked, n, v))
        ticks_g, ticks_c = M + n - 1, v * M + n - 1
        per_tick_g, per_tick_c = t_g / ticks_g, t_c / ticks_c
        # 5% slack: on heavily contended/low-core runners the per-tick
        # rendezvous overhead can eat most of the halved-compute margin
        assert per_tick_c < per_tick_g * 1.05, (
            f"circular per-tick {per_tick_c * 1e3:.2f}ms not below gpipe "
            f"{per_tick_g * 1e3:.2f}ms (steps: {t_c * 1e3:.1f} / "
            f"{t_g * 1e3:.1f}ms)")
        # and the full step must stay within the overhead-regime bound
        assert t_c < 2.0 * t_g

    def test_microbatch_roundtrip(self):
        batch = {"x": jnp.arange(24.0).reshape(12, 2)}
        mb = microbatch(batch, 4)
        assert mb["x"].shape == (4, 3, 2)
        back = unmicrobatch(mb)
        np.testing.assert_allclose(np.asarray(back["x"]),
                                   np.asarray(batch["x"]))

class TestBertPipelined:
    """BERT with the encoder run through gpipe over "pp", composed with
    dp+fsdp batch sharding — loss/grad parity vs the sequential encoder."""

    CFG = dict(vocab_size=64, hidden_size=16, num_layers=4, num_heads=2,
               ffn_size=32, max_position=32, dropout=0.0, attn_dropout=0.0,
               attn_impl="xla")

    def _models_and_batch(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        m_ref = BertForPretraining(BertConfig.tiny(**self.CFG))
        # stacked_layers=False: these tests isolate the SCHEDULE by
        # feeding the same LayerList-layout params to both models (the
        # stacked layout has its own parity tests below)
        m_pp = BertForPretraining(BertConfig.tiny(
            **self.CFG, pipeline=True, pp_microbatches=4,
            stacked_layers=False))
        params = m_ref.init(jax.random.PRNGKey(0))
        b, s = 16, 16
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        mask = jnp.arange(s)[None, :] < jax.random.randint(
            k2, (b, 1), s // 2, s + 1)           # ragged padding
        batch = dict(
            input_ids=jax.random.randint(k1, (b, s), 0, 64, jnp.int32),
            token_type_ids=jnp.zeros((b, s), jnp.int32),
            attention_mask=mask,
            mlm_labels=jnp.zeros((b, s), jnp.int32),
            mlm_mask=jnp.ones((b, s), jnp.float32),
            nsp_labels=jnp.zeros((b,), jnp.int32),
        )
        return m_ref, m_pp, params, batch

    def test_loss_and_grad_parity_pp_dp_fsdp(self):
        from paddle_tpu.core.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        m_ref, m_pp, params, batch = self._models_and_batch()

        def loss_ref(p):
            return m_ref.loss(p, training=False, **batch)[0]

        def loss_pp(p):
            return m_pp.loss(p, training=False, **batch)[0]

        l_ref, g_ref = jax.value_and_grad(loss_ref)(params)
        with mesh_context(mesh):
            l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(params)
        assert float(l_pp) == pytest.approx(float(l_ref), rel=1e-5)
        for a, b_ in zip(jax.tree_util.tree_leaves(g_pp),
                         jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=1e-3)

    def test_circular_schedule_loss_and_grad_parity(self):
        """BERT encoder through the interleaved 1F1B-circular schedule
        (pp=2, v=2, M=4) matches the sequential reference."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        m_ref, _, params, batch = self._models_and_batch()
        m_circ = BertForPretraining(BertConfig.tiny(
            **self.CFG, pipeline=True, pp_microbatches=4,
            pp_schedule="circular", pp_circuits=2,
            stacked_layers=False))

        def loss_ref(p):
            return m_ref.loss(p, training=False, **batch)[0]

        def loss_circ(p):
            return m_circ.loss(p, training=False, **batch)[0]

        l_ref, g_ref = jax.value_and_grad(loss_ref)(params)
        with mesh_context(mesh):
            l_c, g_c = jax.jit(jax.value_and_grad(loss_circ))(params)
        assert float(l_c) == pytest.approx(float(l_ref), rel=1e-5)
        for a, b_ in zip(jax.tree_util.tree_leaves(g_c),
                         jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=1e-3)

    def test_circular_pre_interleaved_layout(self):
        """Stacked-layers BERT with params converted once via
        interleave_stack + pp_pre_interleaved=True (the no-per-step-
        reshuffle path) computes the same loss/grads as the in-step
        arrangement."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.bert import BertConfig, BertForPretraining
        from paddle_tpu.parallel.pipeline import (interleave_stack,
                                                  uninterleave_stack)

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        base = dict(self.CFG, pipeline=True, pp_microbatches=4,
                    pp_schedule="circular", pp_circuits=2)
        m = BertForPretraining(BertConfig.tiny(**base))
        m_pre = BertForPretraining(BertConfig.tiny(
            **base, pp_pre_interleaved=True))
        params = m.init(jax.random.PRNGKey(0))
        p_pre = dict(params)
        p_pre["bert"] = dict(params["bert"])
        p_pre["bert"]["encoder"] = interleave_stack(
            params["bert"]["encoder"], 2, 2)
        _, _, _, batch = self._models_and_batch()

        with mesh_context(mesh):
            l, g = jax.jit(jax.value_and_grad(
                lambda p: m.loss(p, training=False, **batch)[0]))(params)
            l2, g2 = jax.jit(jax.value_and_grad(
                lambda p: m_pre.loss(p, training=False, **batch)[0]))(p_pre)
        assert float(l2) == pytest.approx(float(l), rel=1e-5)
        g2["bert"]["encoder"] = uninterleave_stack(
            g2["bert"]["encoder"], 2, 2)
        for a, b_ in zip(jax.tree_util.tree_leaves(g2),
                         jax.tree_util.tree_leaves(g)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=1e-3)

    def test_circular_pre_interleaved_dropout_keys(self):
        """training=True exercises the layer-key interleave branch: the
        pre-interleaved layout must sample the SAME dropout masks as the
        canonical layout (layer->key binding is layout-independent)."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.bert import BertConfig, BertForPretraining
        from paddle_tpu.parallel.pipeline import interleave_stack

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        base = dict(self.CFG, dropout=0.3, pipeline=True,
                    pp_microbatches=4, pp_schedule="circular",
                    pp_circuits=2)
        m = BertForPretraining(BertConfig.tiny(**base))
        m_pre = BertForPretraining(BertConfig.tiny(
            **base, pp_pre_interleaved=True))
        params = m.init(jax.random.PRNGKey(0))
        p_pre = dict(params)
        p_pre["bert"] = dict(params["bert"])
        p_pre["bert"]["encoder"] = interleave_stack(
            params["bert"]["encoder"], 2, 2)
        _, _, _, batch = self._models_and_batch()
        with mesh_context(mesh):
            l = jax.jit(lambda p, k: m.loss(
                p, training=True, key=k, **batch)[0])(
                    params, jax.random.PRNGKey(7))
            l2 = jax.jit(lambda p, k: m_pre.loss(
                p, training=True, key=k, **batch)[0])(
                    p_pre, jax.random.PRNGKey(7))
        assert float(l2) == pytest.approx(float(l), rel=1e-5)

    def test_pre_interleaved_rejected_under_gpipe(self):
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.parallel.pipeline import gpipe_layer_stack

        mesh = make_mesh(MeshConfig(pp=2, dp=4))
        layers = _make_layers(jax.random.PRNGKey(30), 4, 4)
        with mesh_context(mesh):
            with pytest.raises(ValueError, match="wrong order"):
                gpipe_layer_stack(
                    lambda lp, h, e, k: _block(lp, h), layers,
                    jnp.zeros((8, 4)), num_microbatches=4,
                    schedule="gpipe", pre_interleaved=True)

    def test_dropout_under_pipeline(self):
        """training=True with dropout>0 exercises the per-layer key ride
        (fold_in of the microbatch index) inside the schedule."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        cfg = dict(self.CFG, dropout=0.3)
        m = BertForPretraining(BertConfig.tiny(
            **cfg, pipeline=True, pp_microbatches=4,
            stacked_layers=False))
        params = m.init(jax.random.PRNGKey(0))
        _, _, _, batch = self._models_and_batch()
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        with mesh_context(mesh):
            f = jax.jit(lambda p, k: m.loss(
                p, training=True, key=k, **batch)[0])
            l1 = float(f(params, jax.random.PRNGKey(1)))
            l2 = float(f(params, jax.random.PRNGKey(2)))
        assert np.isfinite(l1) and np.isfinite(l2)
        assert l1 != l2  # dropout really sampled

    def test_pp_composes_with_tp(self):
        """pp=2 x tp=2: stage params replicated over tp, attention/FFN
        constraints inert inside the shard_map — result must still match
        the sequential reference."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=2, tp=2, pp=2))
        m_ref, m_pp, params, batch = self._models_and_batch()

        def loss_ref(p):
            return m_ref.loss(p, training=False, **batch)[0]

        def loss_pp(p):
            return m_pp.loss(p, training=False, **batch)[0]

        l_ref = float(loss_ref(params))
        with mesh_context(mesh):
            l_pp = float(jax.jit(loss_pp)(params))
        assert l_pp == pytest.approx(l_ref, rel=1e-5)


class TestBertStackedLayers:
    """Scan-over-layers param layout (nn.module.StackedLayers): stacked
    (L, ...) leaves, pp-sharded from init."""

    CFG = dict(vocab_size=64, hidden_size=16, num_layers=4, num_heads=2,
               ffn_size=32, max_position=32, dropout=0.0, attn_dropout=0.0,
               attn_impl="xla")

    def test_stacked_forward_matches_layerlist(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        m_list = BertForPretraining(BertConfig.tiny(**self.CFG))
        m_stk = BertForPretraining(BertConfig.tiny(
            **self.CFG, stacked_layers=True))
        from paddle_tpu.models.bert import stack_encoder_params
        params = m_list.init(jax.random.PRNGKey(0))
        sparams = stack_encoder_params(params, 4)
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64,
                                 jnp.int32)
        a = m_list(params, ids, training=False)
        b = m_stk(sparams, ids, training=False)
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=1e-5, rtol=1e-5)

    def test_stacked_init_shapes_and_shardings(self):
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        m = BertForPretraining(BertConfig.tiny(
            **self.CFG, stacked_layers=True))
        params = m.init(jax.random.PRNGKey(0))
        w = params["bert"]["encoder"]["ffn"]["fc1"]["weight"]
        assert w.shape[0] == 4                   # leading L dim
        specs = m.sharding_specs(params)
        s = specs["bert"]["encoder"]["ffn"]["fc1"]["weight"]
        assert tuple(s)[0] == "pp"               # stage axis from init
        assert "tp" in tuple(s)                  # template hint preserved

    def test_stacked_dropout_exact_parity_with_layerlist(self):
        """training=True with dropout: the scan path consumes keys[i+1]
        at step i exactly like the loop path, so outputs match EXACTLY
        given converted params (pins the key-ordering contract)."""
        from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                            stack_encoder_params)

        cfg = dict(self.CFG, dropout=0.3)
        m_list = BertForPretraining(BertConfig.tiny(**cfg))
        m_stk = BertForPretraining(BertConfig.tiny(
            **cfg, stacked_layers=True))
        params = m_list.init(jax.random.PRNGKey(0))
        sparams = stack_encoder_params(params, 4)
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, 64,
                                 jnp.int32)
        key = jax.random.PRNGKey(7)
        a = m_list(params, ids, key=key, training=True)
        b = m_stk(sparams, ids, key=key, training=True)
        for x, y in zip(a, b):
            np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                       atol=1e-5, rtol=1e-5)
        # and dropout is actually live: different key -> different output
        c = m_stk(sparams, ids, key=jax.random.PRNGKey(8), training=True)
        assert not np.allclose(np.asarray(b[0]), np.asarray(c[0]))

    def test_unstack_roundtrip(self):
        from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                            stack_encoder_params,
                                            unstack_encoder_params)

        m = BertForPretraining(BertConfig.tiny(**self.CFG))
        params = m.init(jax.random.PRNGKey(0))
        back = unstack_encoder_params(
            stack_encoder_params(params, 4), 4)
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_stacked_pipeline_trains_no_reshard(self):
        """Pipeline over natively pp-sharded stacked params: loss/grad
        parity vs the same params run sequentially (scan path)."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.bert import BertConfig, BertForPretraining

        m_pp = BertForPretraining(BertConfig.tiny(
            **self.CFG, pipeline=True, pp_microbatches=4))
        m_seq = BertForPretraining(BertConfig.tiny(
            **self.CFG, stacked_layers=True))
        assert m_pp.cfg.stacked_layers        # defaults on with pipeline
        params = m_pp.init(jax.random.PRNGKey(0))
        b, s = 16, 16
        k1 = jax.random.PRNGKey(1)
        batch = dict(
            input_ids=jax.random.randint(k1, (b, s), 0, 64, jnp.int32),
            token_type_ids=jnp.zeros((b, s), jnp.int32),
            attention_mask=jnp.ones((b, s), bool),
            mlm_labels=jnp.zeros((b, s), jnp.int32),
            mlm_mask=jnp.ones((b, s), jnp.float32),
            nsp_labels=jnp.zeros((b,), jnp.int32),
        )
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        l_seq, g_seq = jax.value_and_grad(
            lambda p: m_seq.loss(p, training=False, **batch)[0])(params)
        with mesh_context(mesh):
            l_pp, g_pp = jax.jit(jax.value_and_grad(
                lambda p: m_pp.loss(p, training=False, **batch)[0]))(params)
        assert float(l_pp) == pytest.approx(float(l_seq), rel=1e-5)
        for a, b_ in zip(jax.tree_util.tree_leaves(g_pp),
                         jax.tree_util.tree_leaves(g_seq)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       atol=2e-4, rtol=1e-3)


class TestGPTPipelined:
    def test_gpt_pp_loss_and_grad_parity(self):
        """GPT with the block stack through gpipe (pp=2 x dp x fsdp) vs
        the sequential stack — loss/grad parity."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.gpt import GPT, GPTConfig

        cfg = dict(vocab_size=64, hidden_size=16, num_layers=4,
                   num_heads=2, ffn_size=32, max_position=32,
                   dropout=0.0, attn_impl="xla")
        m_ref = GPT(GPTConfig.tiny(**cfg))
        m_pp = GPT(GPTConfig.tiny(**cfg, pipeline=True,
                                  pp_microbatches=4,
                                  stacked_layers=False))
        params = m_ref.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 64,
                                 jnp.int32)
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))

        l_ref, g_ref = jax.value_and_grad(
            lambda p: m_ref.loss(p, ids, training=False)[0])(params)
        with mesh_context(mesh):
            l_pp, g_pp = jax.jit(jax.value_and_grad(
                lambda p: m_pp.loss(p, ids, training=False)[0]))(params)
        assert float(l_pp) == pytest.approx(float(l_ref), rel=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=1e-3)

    def test_gpt_stacked_pipeline_parity(self):
        """GPT with natively-stacked blocks through the pipeline vs the
        same stacked params run through the scan path."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.gpt import GPT, GPTConfig

        cfg = dict(vocab_size=64, hidden_size=16, num_layers=4,
                   num_heads=2, ffn_size=32, max_position=32,
                   dropout=0.0, attn_impl="xla")
        m_pp = GPT(GPTConfig.tiny(**cfg, pipeline=True,
                                  pp_microbatches=4))
        m_seq = GPT(GPTConfig.tiny(**cfg, stacked_layers=True))
        assert m_pp.cfg.stacked_layers
        params = m_pp.init(jax.random.PRNGKey(0))
        assert params["blocks"]["attn"]["qkv_proj"]["weight"].shape[0] == 4
        ids = jax.random.randint(jax.random.PRNGKey(1), (16, 17), 0, 64,
                                 jnp.int32)
        l_seq = float(m_seq.loss(params, ids, training=False)[0])
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        with mesh_context(mesh):
            l_pp = float(jax.jit(
                lambda p: m_pp.loss(p, ids, training=False)[0])(params))
        assert l_pp == pytest.approx(l_seq, rel=1e-5)

    def test_gpt_pp_trains_with_dropout(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.gpt import GPT, GPTConfig
        from paddle_tpu.train import build_train_step, make_train_state

        cfg = GPTConfig.tiny(num_layers=4, dropout=0.1, attn_impl="xla",
                             pipeline=True, pp_microbatches=2)
        model = GPT(cfg)
        optimizer = opt.Adam(learning_rate=3e-3)
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        ids = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                 cfg.vocab_size, jnp.int32)
        with mesh_context(mesh):
            state = make_train_state(model, optimizer,
                                     jax.random.PRNGKey(0))
            step = jax.jit(build_train_step(
                lambda p, ids, dropout_key: model.loss(
                    p, ids, key=dropout_key, training=True)[0],
                optimizer))
            losses = []
            for i in range(8):
                state, m = step(state, ids=ids,
                                dropout_key=jax.random.key(i))
                losses.append(float(m["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0]


class TestGPipeTraining:
    def test_train_step_through_pipeline(self, pp_mesh):
        """End-to-end: pipelined MLP regression learns under jit."""
        layers = _make_layers(jax.random.PRNGKey(4), 4, 8)
        stacked = stack_layer_params(layers)
        x = jax.random.normal(jax.random.PRNGKey(5), (8, 4, 8))
        y = jax.random.normal(jax.random.PRNGKey(6), (8, 4, 8))

        def loss_fn(sp):
            out = gpipe(_block, sp, x, mesh=pp_mesh)
            return ((out - y) ** 2).mean()

        with mesh_context(pp_mesh):
            step = jax.jit(jax.value_and_grad(loss_fn))
            params = stacked
            losses = []
            for _ in range(10):
                loss, g = step(params)
                params = jax.tree_util.tree_map(
                    lambda p, gr: p - 0.1 * gr, params, g)
                losses.append(float(loss))
        assert losses[-1] < losses[0]


class TestTransformerPipelined:
    """Seq2seq Transformer with encoder AND decoder stacks pipelined
    over "pp" — loss/grad parity vs the sequential stacks."""

    CFG = dict(dropout=0.0, attn_dropout=0.0, max_len=16,
               attn_impl="xla", label_smoothing=0.1,
               num_encoder_layers=4, num_decoder_layers=4)

    def _setup(self, **pp_kw):
        from paddle_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)
        m_ref = Transformer(TransformerConfig.tiny(**self.CFG))
        m_pp = Transformer(TransformerConfig.tiny(
            **self.CFG, pipeline=True, pp_microbatches=4, **pp_kw))
        params = m_ref.init(jax.random.PRNGKey(0))
        rng = np.random.RandomState(0)
        src = jnp.asarray(rng.randint(3, 64, (16, 12)), jnp.int32)
        tgt_in = jnp.asarray(rng.randint(3, 64, (16, 10)), jnp.int32)
        tgt_out = jnp.asarray(rng.randint(3, 64, (16, 10)), jnp.int32)
        return m_ref, m_pp, params, (src, tgt_in, tgt_out)

    @pytest.mark.parametrize("schedule", ["gpipe", "circular"])
    def test_loss_and_grad_parity(self, schedule):
        from paddle_tpu.core.mesh import MeshConfig, make_mesh

        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        m_ref, m_pp, params, batch = self._setup(
            pp_schedule=schedule,
            pp_circuits=2 if schedule == "circular" else 1)

        def loss_ref(p):
            return m_ref.loss(p, *batch, training=False)[0]

        def loss_pp(p):
            return m_pp.loss(p, *batch, training=False)[0]

        l_ref, g_ref = jax.value_and_grad(loss_ref)(params)
        with mesh_context(mesh):
            l_pp, g_pp = jax.jit(jax.value_and_grad(loss_pp))(params)
        assert float(l_pp) == pytest.approx(float(l_ref), rel=1e-5)
        for a, b in zip(jax.tree_util.tree_leaves(g_pp),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-4, rtol=1e-3)

    def test_dropout_trains_under_pipeline(self):
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        from paddle_tpu.models.transformer import (Transformer,
                                                   TransformerConfig)

        cfg = dict(self.CFG, dropout=0.2)
        m = Transformer(TransformerConfig.tiny(
            **cfg, pipeline=True, pp_microbatches=4))
        params = m.init(jax.random.PRNGKey(1))
        rng = np.random.RandomState(1)
        src = jnp.asarray(rng.randint(3, 64, (16, 8)), jnp.int32)
        tgt_in = jnp.asarray(rng.randint(3, 64, (16, 8)), jnp.int32)
        tgt_out = jnp.asarray(rng.randint(3, 64, (16, 8)), jnp.int32)
        mesh = make_mesh(MeshConfig(dp=2, fsdp=2, pp=2))
        with mesh_context(mesh):
            f = jax.jit(lambda p, k: m.loss(
                p, src, tgt_in, tgt_out, training=True, key=k)[0])
            l1 = float(f(params, jax.random.PRNGKey(2)))
            l2 = float(f(params, jax.random.PRNGKey(3)))
        assert np.isfinite(l1) and np.isfinite(l2)
        assert l1 != l2
