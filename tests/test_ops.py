"""Op library tests: output parity vs NumPy references + numeric grad checks.

Parity with the reference's per-op OpTest files
(python/paddle/fluid/tests/unittests/test_*_op.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import ops
from paddle_tpu.core.registry import all_ops, get_op
from paddle_tpu.ops import activation, elementwise, math as pmath, nn, reduction, tensor
from paddle_tpu.testing import check_grad, check_output

RNG = np.random.RandomState(42)


def randn(*shape):
    return RNG.randn(*shape).astype(np.float32)


# -- auto-generated output parity for every op with a reference impl -------

_UNARY_CASES = {
    "default": (randn(4, 5),),
}


def _sample_args(name):
    """Construct sample args per op name for the auto parity sweep."""
    x = randn(4, 6)
    pos = np.abs(randn(4, 6)) + 0.5
    table = {
        "log": (pos,), "sqrt": (pos,), "rsqrt": (pos,), "reciprocal": (pos,),
        "cholesky": (np.eye(4, dtype=np.float32) * 2 + 0.1 * np.ones((4, 4), np.float32),),
        "matmul": (randn(4, 5), randn(5, 3)),
        "mul": (randn(4, 5), randn(5, 3)),
        "bmm": (randn(2, 3, 4), randn(2, 4, 5)),
        "dot": (randn(4, 6), randn(4, 6)),
        "fc": (randn(4, 6), randn(6, 3), randn(3)),
        "addmm": (randn(4, 3), randn(4, 5), randn(5, 3)),
        "norm": (x,),
        "one_hot": (RNG.randint(0, 5, (7,)), 5),
        "concat": ([randn(2, 3), randn(2, 3)],),
        "stack": ([randn(2, 3), randn(2, 3)],),
        "reshape": (x, (6, 4)),
        "transpose": (x, (1, 0)),
        "gather": (randn(5, 3), RNG.randint(0, 5, (4,))),
        "cast": (x, "float64"),
        "expand": (randn(2, 3), (2, 2)),
        "tile": (randn(2, 3), (2, 2)),
        "where": (x > 0, x, -x),
        "flip": (x, 0),
        "squeeze": (randn(2, 1, 3), (1,)),
        "unsqueeze": (randn(2, 3), (1,)),
        "argsort": (x,), "argmax": (x,), "argmin": (x,),
        "range": (0, 10, 2),
        "clip": (x, -0.5, 0.5),
        "leaky_relu": (x,), "elu": (x,), "relu6": (x,),
        "hard_sigmoid": (x,), "hard_swish": (x,),
        "prelu": (x, np.float32(0.1)),
        "pow": (pos,),
        "cross_entropy": (np.abs(randn(4, 5)) / 5 + 0.1, RNG.randint(0, 5, (4,))),
        "square_error_cost": (x, randn(4, 6)),
        "pad": (randn(2, 3), ((1, 1), (0, 2))),
        "label_smooth": (np.eye(5, dtype=np.float32)[RNG.randint(0, 5, (4,))],),
        "lookup_table": (RNG.randint(0, 5, (4,)), randn(5, 3)),
        "assign": (x,), "zeros_like": (x,), "ones_like": (x,),
        "isfinite": (x,), "isnan": (x,),
        "eye": (4,), "diag": (randn(4),),
        "einsum": ("ij,jk->ik", randn(3, 4), randn(4, 5)),
        "kron": (randn(2, 3), randn(3, 2)),
        "index_select": (randn(5, 3), RNG.randint(0, 5, (4,))),
        "index_sample": (randn(4, 6), RNG.randint(0, 6, (4, 3))),
        "multiplex": (RNG.randint(0, 2, (4,)), randn(4, 3), randn(4, 3)),
        "log_loss": (np.abs(randn(4, 1)) % 0.8 + 0.1,
                     RNG.randint(0, 2, (4, 1)).astype(np.float32)),
        "rank_loss": (RNG.randint(0, 2, (4, 1)).astype(np.float32),
                      randn(4, 1), randn(4, 1)),
        "hinge_loss": (randn(4, 1),
                       RNG.randint(0, 2, (4, 1)).astype(np.float32)),
        "conv_shift": (randn(4, 7), randn(4, 3)),
        "modified_huber_loss": (randn(4, 6),
                                RNG.randint(0, 2, (4, 6)).astype(np.float32)),
    }
    if name in ("equal", "not_equal", "less_than", "less_equal",
                "greater_than", "greater_equal"):
        return (randn(4, 6), randn(4, 6))
    if name in ("logical_and", "logical_or", "logical_xor"):
        return (x > 0, randn(4, 6) > 0)
    if name == "logical_not":
        return (x > 0,)
    if name in ("acos", "asin"):
        return (np.clip(x, -0.99, 0.99),)
    if name.startswith("elementwise_"):
        return (randn(4, 6), randn(4, 6))
    if name.startswith("reduce_") or name in ("logsumexp",):
        if name in ("reduce_all", "reduce_any"):
            return (x > 0,)
        return (x,)
    return table.get(name, (x,))


@pytest.mark.parametrize("name", sorted(
    n for n, info in all_ops().items() if info.reference is not None))
def test_op_output_parity(name):
    info = get_op(name)
    args = _sample_args(name)
    rtol, atol = (2e-4, 2e-5) if name in ("gelu",) else (1e-5, 1e-6)
    check_output(info.fn, info.reference, args, rtol=rtol, atol=atol)


# -- targeted numeric gradient checks (op_test.py check_grad parity) -------

@pytest.mark.parametrize("name,args,wrt", [
    ("matmul", (randn(3, 4), randn(4, 2)), (0, 1)),
    ("softmax", (randn(3, 5),), (0,)),
    ("layer_norm", (randn(3, 5), randn(5), randn(5)), (0, 1, 2)),
    ("tanh", (randn(3, 4),), (0,)),
    ("sigmoid", (randn(3, 4),), (0,)),
    ("gelu", (randn(3, 4),), (0,)),
    ("elementwise_mul", (randn(3, 4), randn(3, 4)), (0, 1)),
    ("elementwise_div", (randn(3, 4), np.abs(randn(3, 4)) + 1.0), (0, 1)),
    ("reduce_mean", (randn(3, 4),), (0,)),
    ("logsumexp", (randn(3, 4),), (0,)),
    ("log_softmax", (randn(3, 5),), (0,)),
    ("fc", (randn(3, 4), randn(4, 2), randn(2)), (0, 1, 2)),
    ("lookup_table", (np.array([0, 2, 1]), randn(4, 3)), (1,)),
])
def test_op_numeric_grad(name, args, wrt):
    info = get_op(name)
    check_grad(info.fn, args, wrt=wrt)


def test_conv2d_grad():
    x, w = randn(2, 5, 5, 3), randn(3, 3, 3, 4)
    check_grad(nn.conv2d, (x, w), wrt=(0, 1), rtol=2e-3, atol=2e-3)


def test_conv2d_matches_reference_convolution():
    # spot-check against scipy-style direct computation with padding
    x, w = randn(1, 4, 4, 1), randn(3, 3, 1, 2)
    out = nn.conv2d(x, w, stride=1, padding=1)
    assert out.shape == (1, 4, 4, 2)
    # center pixel = full 3x3 window dot kernel
    want = np.sum(x[0, 0:3, 0:3, 0] [..., None] * w[:, :, 0, :], axis=(0, 1))
    np.testing.assert_allclose(np.asarray(out[0, 1, 1]), want, rtol=1e-4, atol=1e-4)


def test_pool2d():
    x = randn(1, 4, 4, 2)
    out = nn.pool2d(x, kernel=2, stride=2, pool_type="max")
    assert out.shape == (1, 2, 2, 2)
    np.testing.assert_allclose(np.asarray(out[0, 0, 0]),
                               x[0, 0:2, 0:2].max(axis=(0, 1)))
    avg = nn.pool2d(x, kernel=2, stride=2, pool_type="avg")
    np.testing.assert_allclose(np.asarray(avg[0, 0, 0]),
                               x[0, 0:2, 0:2].mean(axis=(0, 1)), rtol=1e-6)


def test_pool2d_nchw():
    x = randn(1, 2, 4, 4)
    out = nn.pool2d(x, kernel=2, stride=2, pool_type="max", data_format="NCHW")
    assert out.shape == (1, 2, 2, 2)


def test_batch_norm_inference():
    x = randn(4, 3, 3, 2)
    scale, bias = np.ones(2, np.float32), np.zeros(2, np.float32)
    mean, var = np.zeros(2, np.float32), np.ones(2, np.float32)
    out, m2, v2 = nn.batch_norm(x, scale, bias, mean, var, training=False)
    np.testing.assert_allclose(np.asarray(out), x, rtol=1e-5, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(m2), mean)


def test_softmax_with_cross_entropy():
    logits = randn(4, 7)
    labels = RNG.randint(0, 7, (4,))
    loss = nn.softmax_with_cross_entropy(logits, labels)
    # reference: -log softmax picked
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    want = -np.log(p[np.arange(4), labels])[:, None]
    np.testing.assert_allclose(np.asarray(loss), want, rtol=1e-5, atol=1e-6)
    # soft label
    soft = np.abs(randn(4, 7)); soft /= soft.sum(-1, keepdims=True)
    loss2 = nn.softmax_with_cross_entropy(logits, soft, soft_label=True)
    want2 = -np.sum(soft * np.log(p), -1, keepdims=True)
    np.testing.assert_allclose(np.asarray(loss2), want2, rtol=1e-5, atol=1e-6)


def test_softmax_with_cross_entropy_grad():
    logits = randn(3, 5)
    labels = np.array([0, 2, 4])
    check_grad(lambda x: nn.softmax_with_cross_entropy(x, labels), (logits,))


def test_dropout_statistics():
    x = jnp.ones((1000,))
    out = nn.dropout(x, jax.random.PRNGKey(0), rate=0.25)
    kept = np.asarray(out) > 0
    assert 0.68 < kept.mean() < 0.82  # ~75% kept
    # upscale_in_train: expectation preserved
    assert abs(np.asarray(out).mean() - 1.0) < 0.1
    # eval mode = identity
    np.testing.assert_array_equal(
        np.asarray(nn.dropout(x, jax.random.PRNGKey(0), rate=0.5, training=False)),
        np.asarray(x))


def test_top_k():
    x = np.array([[1.0, 5.0, 3.0], [9.0, 2.0, 4.0]], np.float32)
    vals, idx = tensor.top_k(x, 2)
    np.testing.assert_array_equal(np.asarray(idx), [[1, 2], [0, 2]])
    np.testing.assert_array_equal(np.asarray(vals), [[5.0, 3.0], [9.0, 4.0]])


def test_accuracy_op():
    logits = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
    labels = np.array([1, 0, 0])
    acc = tensor.accuracy(logits, labels)
    np.testing.assert_allclose(float(acc), 2 / 3, rtol=1e-6)


def test_elementwise_axis_broadcast():
    x = randn(2, 3, 4, 5)
    y = randn(3, 4)
    out = elementwise.add(x, y, axis=1)
    np.testing.assert_allclose(np.asarray(out), x + y[None, :, :, None],
                               rtol=1e-6)


def test_split_and_concat_roundtrip():
    x = randn(6, 4)
    parts = tensor.split(x, 3, axis=0)
    assert len(parts) == 3 and parts[0].shape == (2, 4)
    back = tensor.concat(parts, axis=0)
    np.testing.assert_array_equal(np.asarray(back), x)
    sizes = tensor.split(x, [1, 2, 3], axis=0)
    assert [s.shape[0] for s in sizes] == [1, 2, 3]


def test_scatter():
    x = np.zeros((4, 2), np.float32)
    out = tensor.scatter(jnp.asarray(x), np.array([1, 3]),
                         np.ones((2, 2), np.float32))
    np.testing.assert_array_equal(np.asarray(out[1]), [1, 1])
    np.testing.assert_array_equal(np.asarray(out[0]), [0, 0])


def test_masked_select_static():
    x = np.arange(6).astype(np.float32)
    mask = x > 2
    out = tensor.masked_select(jnp.asarray(x), jnp.asarray(mask), size=3)
    np.testing.assert_array_equal(np.asarray(out), [3, 4, 5])
