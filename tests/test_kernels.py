"""The shared Pallas kernel layer (ISSUE 12): registry, autotuner,
fallback harness, and registry lint.

The parity battery here is THE acceptance surface for every registered
kernel: pallas-interpret (the real kernel body under the interpreter) vs
the lax fallback vs an independent dense reference, at each contract's
declared tolerances. Plus: byte parity against the pre-refactor call
paths, tuner-cache contracts (deterministic keys, persisted round trip,
stale-entry detection on contract-version bumps, cold-cache
correctness), and the zero-steady-state-recompile invariant with the
autotuner active (tuned blocks resolve at trace time, never mid-step).
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import kernels
from paddle_tpu.kernels import autotune, lint, registry

KERNEL_NAMES = kernels.load_all()


# ---------------------------------------------------------------------------
# parity battery — every registered kernel, one harness
# ---------------------------------------------------------------------------

class TestParityBattery:
    @pytest.mark.parametrize("name", KERNEL_NAMES)
    @pytest.mark.parametrize("seed", [0, 1])
    def test_interpret_vs_lax_vs_reference(self, name, seed):
        errs = kernels.parity_check(name, seed)
        # parity_check asserts tolerances internally; a mesh kernel on a
        # single-device box returns {} (skipped), every other kernel
        # must have produced both comparisons
        if errs:
            assert set(errs) >= {"lax", "pallas_interpret"} or \
                set(errs) >= {"xla", "flash_interpret"}, errs


# ---------------------------------------------------------------------------
# byte parity vs the pre-refactor call paths
# ---------------------------------------------------------------------------

class TestByteParity:
    def test_flash_dispatch_equals_direct_kernel_call(self):
        """dispatch() with the tuner's default prior must reproduce the
        pre-refactor flash_attention(block=512) output BIT-FOR-BIT."""
        from paddle_tpu.ops.attention import flash_attention
        spec = kernels.get("flash_attention")
        (q, k, v), kw = spec.sample_inputs(0)
        via_registry = np.asarray(kernels.dispatch(
            "flash_attention", q, k, v, None, impl="pallas_interpret",
            tuner=kernels.KernelTuner(path=None), **kw))
        direct = np.asarray(flash_attention(
            q, k, v, None, kw["causal"], None, 512, 512, True))
        np.testing.assert_array_equal(via_registry, direct)

    @pytest.mark.parametrize("name", ["ragged_paged_decode",
                                      "ragged_paged_prefill"])
    def test_pages_per_block_bit_exact(self, name):
        """The autotuner's pages_per_block tunable keeps the per-page
        accumulation ORDER identical, so every setting is bit-equal —
        tuning can never change serving outputs (greedy argmax included)."""
        spec = kernels.get(name)
        args, kw = spec.sample_inputs(1)
        outs = [np.asarray(kernels.dispatch(
            name, *args, impl="pallas_interpret",
            block_sizes={"pages_per_block": pb}, **kw))
            for pb in (1, 2, 4)]
        for o in outs[1:]:
            np.testing.assert_array_equal(outs[0], o)

    def test_decode_dispatch_equals_private_lax(self):
        from paddle_tpu.serving.decode_attention import _paged_decode_lax
        spec = kernels.get("ragged_paged_decode")
        (q, kp, vp, bt, lens), _ = spec.sample_inputs(0)
        via_registry = np.asarray(kernels.dispatch(
            "ragged_paged_decode", q, kp, vp, bt, lens, impl="lax"))
        direct = np.asarray(_paged_decode_lax(
            q, kp, vp, bt, lens, 1.0 / np.sqrt(q.shape[-1])))
        np.testing.assert_array_equal(via_registry, direct)

    def test_flash_prior_is_the_historic_default(self):
        """The static prior must resolve to the pre-refactor 512/512 so
        auto-dispatched flash is byte-identical to the old hard-coded
        path on every bucket."""
        spec = kernels.get("flash_attention")
        for seed in (0, 1, 2):
            args, kw = spec.sample_inputs(seed)
            assert autotune.static_prior(spec, args, kw) == \
                {"block_q": 512, "block_k": 512}


# ---------------------------------------------------------------------------
# tuner cache
# ---------------------------------------------------------------------------

class TestTunerCache:
    def test_key_is_deterministic_and_bucketed(self):
        spec = kernels.get("flash_attention")
        args, kw = spec.sample_inputs(0)
        k1 = kernels.tune_key(spec, args, kw)
        k2 = kernels.tune_key(spec, args, kw)
        assert k1 == k2
        # abstract shapes produce the same key as concrete arrays
        # (resolution happens on tracers at trace time)
        abstract = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                         for a in args)
        assert kernels.tune_key(spec, abstract, kw) == k1
        # pow2 bucketing: a 65-token and a 128-token seq share an entry
        (q, k, v), _ = spec.sample_inputs(0)

        def with_seq(s):
            pad = ((0, 0), (0, 0), (0, s - q.shape[2]), (0, 0))
            return tuple(jnp.pad(a, pad) for a in (q, k, v))

        k65 = kernels.tune_key(spec, with_seq(65), kw)
        k128 = kernels.tune_key(spec, with_seq(128), kw)
        assert k65 == k128
        assert kernels.tune_key(spec, args, kw) != k65
        # dtype participates
        bf16 = tuple(a.astype(jnp.bfloat16) for a in args)
        assert kernels.tune_key(spec, bf16, kw) != k1

    def test_persisted_round_trip(self, tmp_path):
        spec = kernels.get("ragged_paged_decode")
        args, kw = spec.sample_inputs(0)
        t1 = kernels.KernelTuner(path=None)
        blocks = t1.get(spec, args, kw)
        assert t1.misses == 1
        path = str(tmp_path / "tune.json")
        t1.save(path)
        t2 = kernels.KernelTuner(path)
        assert t2.get(spec, args, kw) == blocks
        assert t2.hits == 1 and t2.misses == 0

    def test_stale_entry_detected_on_contract_version_bump(self):
        import dataclasses
        spec = kernels.get("ragged_paged_decode")
        args, kw = spec.sample_inputs(0)
        t = kernels.KernelTuner(path=None)
        t.get(spec, args, kw)
        bumped = dataclasses.replace(
            spec, contract=dataclasses.replace(spec.contract, version=99))
        key_old = kernels.tune_key(spec, args, kw)
        key_new = kernels.tune_key(bumped, args, kw)
        assert key_old != key_new        # version is part of the key
        # simulate a manifest written before the bump: entry sits under
        # the NEW key but carries the OLD contract_version
        t.entries[key_new] = dict(t.entries[key_old])
        t.entries[key_new]["contract_version"] = spec.contract.version
        stale_before = t.stale
        blocks = t.get(bumped, args, kw)
        assert t.stale == stale_before + 1
        assert blocks == autotune.static_prior(bumped, args, kw)

    def test_cold_cache_still_correct(self):
        """An empty tuner (no committed manifest) must still produce
        reference-correct outputs — cold is slower, never wrong."""
        prev = kernels.set_default_tuner(kernels.KernelTuner(path=None))
        try:
            kernels.parity_check("ragged_paged_prefill", 0)
        finally:
            kernels.set_default_tuner(prev)

    def test_committed_manifest_fresh_and_cost_seeded(self):
        """tools/kernel_tune.json loads, covers every tunable leaf
        kernel, and carries no stale contract versions."""
        t = kernels.KernelTuner(kernels.DEFAULT_CACHE_PATH)
        assert t.entries, "committed kernel_tune.json missing or empty"
        covered = set()
        for key, ent in t.entries.items():
            name = key.split("|", 1)[0]
            spec = kernels.get(name)
            assert int(ent["contract_version"]) == spec.contract.version, \
                f"stale committed entry {key} — reseed with " \
                "python -m paddle_tpu.kernels.autotune --seed"
            covered.add(name)
        for name in KERNEL_NAMES:
            spec = kernels.get(name)
            if spec.contract.block_candidates and not spec.requires_mesh:
                assert name in covered, f"{name} missing from manifest"

    def test_corrupt_blocks_entry_never_dispatched(self):
        """A hand-edited / corrupt manifest entry whose blocks fall
        outside the contract's candidate set must be refused at
        resolution (re-derived as a prior) and flagged stale — dispatch
        can never run an out-of-contract block config."""
        spec = kernels.get("flash_attention")
        args, kw = spec.sample_inputs(0)
        t = kernels.KernelTuner(path=None)
        t.get(spec, args, kw)
        key = kernels.tune_key(spec, args, kw)
        t.entries[key]["blocks"] = {"block_q": 1024, "block_k": 512}
        assert t.stale_entries() == [key]
        blocks = t.get(spec, args, kw)
        assert t.stale == 1
        assert blocks == autotune.static_prior(spec, args, kw)

    def test_purge_stale_clears_bumped_and_orphaned_entries(self):
        """The documented remediation loop: after a contract-version
        bump, ``--seed`` (via purge_stale) must actually delete the old
        entries — or the CI stale gate could never be cleared."""
        spec = kernels.get("flash_attention")
        args, kw = spec.sample_inputs(0)
        t = kernels.KernelTuner(path=None)
        t.get(spec, args, kw)
        key = kernels.tune_key(spec, args, kw)
        t.entries["gone_kernel|v1|x|float32|cpu"] = dict(t.entries[key])
        t.entries[key + "old"] = {**t.entries[key], "contract_version": 0}
        assert t.purge_stale() == 2
        assert set(t.entries) == {key}

    def test_seed_preserves_current_measured_entries(self):
        """Reseeding must not clobber a fresh measured winner with a
        re-derived prior (a TPU session's tuning would silently vanish
        on the next --seed)."""
        spec = kernels.get("ragged_paged_decode")
        args, kw = spec.sample_inputs(0)
        t = kernels.KernelTuner(path=None)
        res = t.measure(spec, args, kw, impl="pallas_interpret", reps=1)
        key = kernels.seed_entry(t, spec, args, kw)
        assert t.entries[key]["source"] == "measured"
        assert t.entries[key]["blocks"] == res["blocks"]

    def test_seed_entry_stamps_cost_prior(self, tmp_path):
        spec = kernels.get("flash_attention")
        args, kw = spec.sample_inputs(0)
        t = kernels.KernelTuner(path=None)
        key = kernels.seed_entry(t, spec, args, kw)
        ent = t.entries[key]
        assert ent["source"] == "prior"
        assert ent["cost_prior"]["flops"] > 0
        assert ent["cost_prior"]["traffic_bytes"] > 0

    def test_measure_caches_winner_and_hits(self):
        spec = kernels.get("ragged_paged_decode")
        args, kw = spec.sample_inputs(0)
        t = kernels.KernelTuner(path=None)
        res = t.measure(spec, args, kw, impl="pallas_interpret", reps=1)
        assert res["blocks"]["pages_per_block"] in (1, 2, 4)
        assert len(res["timings_s"]) == 3     # every candidate timed
        hits = t.hits
        assert t.get(spec, args, kw) == res["blocks"]
        assert t.hits == hits + 1


# ---------------------------------------------------------------------------
# zero-steady-state-recompile invariant with the autotuner active
# ---------------------------------------------------------------------------

class TestTraceTimeResolution:
    def test_tuner_update_never_retraces_steady_state(self):
        """Blocks resolve during tracing; a tuner-cache mutation between
        steady-state calls must NOT trigger a recompile (the jit cache
        keys on shapes, not on tuner state)."""
        from paddle_tpu import observability as obs
        obs.install_compile_listener()
        spec = kernels.get("ragged_paged_decode")
        (q, kp, vp, bt, lens), _ = spec.sample_inputs(0)
        tuner = kernels.KernelTuner(path=None)
        prev = kernels.set_default_tuner(tuner)
        try:
            step = jax.jit(lambda *a: kernels.dispatch(
                "ragged_paged_decode", *a, impl="pallas_interpret"))
            out1 = np.asarray(step(q, kp, vp, bt, lens))   # traces here
            det = obs.RecompileDetector("kernel_tuner_steady", warmup=0)
            # mid-serving tuning: the cache learns a "better" config
            key = kernels.tune_key(spec, (q, kp, vp, bt, lens), {})
            tuner.entries[key]["blocks"] = {"pages_per_block": 4}
            out2 = np.asarray(step(q, kp, vp, bt, lens))
            assert det.check(step=1) == 0, \
                "tuner mutation recompiled a steady-state step"
            np.testing.assert_array_equal(out1, out2)
        finally:
            kernels.set_default_tuner(prev)

    def test_engine_zero_recompiles_with_tuned_interpret_kernel(self):
        """End-to-end acceptance: the serving engine through the REAL
        decode/prefill kernels (interpret) with the autotuner resolving
        pages_per_block at trace time — greedy tokens match the dense
        reference AND a post-warmup detector stays at zero (the tuner
        can never recompile a steady-state step)."""
        from test_serving import _dense_reference, _model, _prompts
        from paddle_tpu import observability as obs
        from paddle_tpu import serving
        model, params = _model(seed=2)
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, [4, 9])
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, prefill_chunk=8,
                                    attn_impl="pallas_interpret")
        eng.warmup()   # precompiles every decode+prefill bucket
        det = obs.RecompileDetector("kernel_engine_steady", warmup=0)
        outs = eng.generate_many(prompts, max_new_tokens=4, max_steps=100)
        det.check()
        assert det.recompiles == 0, \
            "steady-state serving recompiled with the autotuner active"
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(
                o, _dense_reference(model, params, p, 4))


# ---------------------------------------------------------------------------
# registry + lint
# ---------------------------------------------------------------------------

class TestRegistryLint:
    def test_full_registry_lints_clean(self):
        report = kernels.lint_registry()
        assert report.ok(), report.render_text()

    def test_all_pallas_sites_are_registered(self):
        """The bypass scan over ops/, parallel/, serving/ must come back
        empty against the real registry + committed allowlist."""
        assert lint.bypass_findings() == []

    def test_unregistered_pallas_call_is_a_bypass(self):
        """Deleting a spec turns its (real) pallas_call sites into
        bypass findings — the scan is live, not a fixture."""
        saved = dict(registry._REGISTRY)
        try:
            del registry._REGISTRY["flash_attention"]
            sites = {f.location for f in lint.bypass_findings()}
            assert "paddle_tpu.ops.attention:_flash_fwd" in sites
            assert "paddle_tpu.ops.attention:_flash_bwd" in sites
        finally:
            registry._REGISTRY.clear()
            registry._REGISTRY.update(saved)

    def test_allowlist_suppresses_and_stale_entry_fails(self, tmp_path):
        saved = dict(registry._REGISTRY)
        allow = tmp_path / "allow.txt"
        try:
            del registry._REGISTRY["flash_attention"]
            allow.write_text(
                "# deliberate exception for the test\n"
                "paddle_tpu.ops.attention:_flash_fwd\n"
                "paddle_tpu.ops.attention:_flash_bwd\n")
            assert lint.bypass_findings(allowlist_path=str(allow)) == []
        finally:
            registry._REGISTRY.clear()
            registry._REGISTRY.update(saved)
        # with the kernel registered again, those entries are now STALE
        # -> each one is its own error finding
        findings = lint.bypass_findings(allowlist_path=str(allow))
        assert len(findings) == 2
        assert all(f.rule == "kernel-registry-bypass" and
                   "stale" in f.message for f in findings)

    def test_contract_violation_is_reported(self):
        """A spec whose lax fallback and Pallas body disagree on output
        shape must produce a kernel-contract finding."""
        spec = kernels.get("flash_attention")
        import dataclasses
        broken = dataclasses.replace(
            spec, name="broken_flash",
            lax_fn=lambda q, k, v, bias=None, **kw:
                jnp.zeros((1,), jnp.float32))
        findings = lint.contract_findings(broken)
        assert any(f.rule == "kernel-contract" for f in findings)

    def test_donation_contract_verified_in_lowered_hlo(self):
        """The decode/prefill donation probes really lower with
        tf.aliasing_output on the page buffers."""
        for name in ("ragged_paged_decode", "ragged_paged_prefill"):
            spec = kernels.get(name)
            fn, args, donate = spec.donation_probe()
            txt = jax.jit(fn, donate_argnums=donate).lower(
                *args).as_text()
            assert txt.count("tf.aliasing_output") >= len(donate)

    def test_graph_lint_preset_includes_kernel_registry(self):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "graph_lint.py")
        src = open(path).read()
        assert "lint_kernel_registry" in src

    def test_dispatch_unknown_kernel_and_impl(self):
        with pytest.raises(KeyError):
            kernels.dispatch("no_such_kernel", jnp.zeros(1))
        with pytest.raises(ValueError):
            kernels.resolve_impl("cuda")


# ---------------------------------------------------------------------------
# bench artifact
# ---------------------------------------------------------------------------

class TestBenchArtifact:
    def test_committed_bench_kernels_schema(self):
        path = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "BENCH_KERNELS.json")
        with open(path) as f:
            r = json.load(f)
        for k in ("metric", "value", "kernels", "tuner_cache_hits",
                  "committed_cache_entries", "committed_cache_stale"):
            assert k in r, f"BENCH_KERNELS.json missing {k}"
        assert r["committed_cache_stale"] == 0
        assert set(r["kernels"]) == {"flash_attention",
                                     "ragged_paged_decode",
                                     "ragged_paged_prefill"}
        for buckets in r["kernels"].values():
            assert len(buckets) == 3
