"""Exact speculative decoding (ISSUE 13): a draft model proposes
``spec_k`` tokens per slot, the target verifies them in ONE fixed-shape
batched-prefill-shaped step, accept-prefix/rollback rewinds the write
cursors — and greedy outputs are BIT-EXACT vs non-speculative greedy
(the acceptance gate), under perfect drafts (long accepts), adversarial
drafts (constant rollback), int8 caches, and with zero steady-state
recompiles; the bucket-coverage lint extends to the verify buckets."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.analysis import hlo_lint
from paddle_tpu.models.gpt import GPT, GPTConfig


def _model(seed=0, **kw):
    cfg = GPTConfig.tiny(vocab_size=64, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla", **kw)
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(seed))


def _draft(seed=9):
    """A genuinely smaller draft sharing only the vocabulary — its
    random weights agree with the target almost never, so every round
    exercises the reject/rollback path."""
    cfg = GPTConfig.tiny(vocab_size=64, hidden_size=8, num_layers=1,
                         num_heads=2, ffn_size=16, max_position=64,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(seed))


def _prompts(rng, lens):
    return [rng.integers(1, 64, n).astype(np.int32) for n in lens]


def _dense_reference(model, params, prompt, max_new):
    out = model.generate(params, jnp.asarray(prompt)[None],
                         max_new_tokens=max_new, use_cache=True)
    return np.asarray(out)[0, len(prompt):]


class TestSpeculativeParity:
    """The acceptance gate: speculative greedy == non-speculative
    greedy, bit for bit, on the serving parity battery."""

    def _run(self, model, params, prompts, max_new, eos_id=None, **kw):
        eng = serving.ServingEngine(model, params, num_slots=3,
                                    page_size=4, prefill_chunk=8,
                                    attn_impl="lax", **kw)
        outs = eng.generate_many(prompts, max_new_tokens=max_new,
                                 eos_id=eos_id, max_steps=500)
        eng.cache.check_invariants()
        assert eng.cache.pages_in_use == 0
        if eng.speculative:
            eng.draft_cache.check_invariants()
            assert eng.draft_cache.pages_in_use == 0
        return outs

    def test_self_draft_bit_exact_long_accepts(self):
        """draft == target: every proposal verifies, rounds accept the
        whole chunk — and outputs still exactly match non-speculative
        greedy AND the dense reference."""
        model, params = _model()
        rng = np.random.default_rng(3)
        prompts = _prompts(rng, [5, 9, 3, 12, 7])
        reg = obs.MetricsRegistry()
        base = self._run(model, params, prompts, 7)
        spec = self._run(model, params, prompts, 7, draft_model=model,
                         draft_params=params, spec_k=4, registry=reg)
        for p, b, s in zip(prompts, base, spec):
            np.testing.assert_array_equal(s, b)
            np.testing.assert_array_equal(
                s, _dense_reference(model, params, p, 7))
        prop = reg.counter("serving_spec_proposed_total").value()
        acc = reg.counter("serving_spec_accepted_total").value()
        assert prop > 0 and acc == prop     # perfect draft: all accepted

    def test_weak_draft_bit_exact_constant_rollback(self):
        """A random small draft never matches: every round rolls back
        to the single target token — exactness must survive the rewind
        (stale K/V behind the cursor, overwritten next round)."""
        model, params = _model()
        dmodel, dparams = _draft()
        rng = np.random.default_rng(5)
        prompts = _prompts(rng, [6, 11, 4])
        reg = obs.MetricsRegistry()
        base = self._run(model, params, prompts, 8)
        spec = self._run(model, params, prompts, 8, draft_model=dmodel,
                         draft_params=dparams, spec_k=4, registry=reg)
        for b, s in zip(base, spec):
            np.testing.assert_array_equal(s, b)
        prop = reg.counter("serving_spec_proposed_total").value()
        acc = reg.counter("serving_spec_accepted_total").value()
        assert prop > 0 and acc < prop      # rollback really happened

    def test_early_eos_truncates_accepted_run(self):
        """EOS inside an accepted chunk stops the request exactly where
        sequential decoding would."""
        model, params = _model()
        rng = np.random.default_rng(6)
        prompt = _prompts(rng, [6])[0]
        full = _dense_reference(model, params, prompt, 12)
        eos = int(full[3])
        stop = int(np.argmax(full == eos)) + 1
        out = self._run(model, params, [prompt], 12, eos_id=eos,
                        draft_model=model, draft_params=params,
                        spec_k=4)[0]
        np.testing.assert_array_equal(out, full[:stop])

    def test_int8_cache_speculative_matches_int8_plain(self):
        """Quantization and speculation compose: both caches int8, and
        the speculative stream equals the plain int8 stream exactly."""
        model, params = _model()
        rng = np.random.default_rng(7)
        prompts = _prompts(rng, [9, 4, 6])
        plain = self._run(model, params, prompts, 5,
                          cache_dtype=jnp.int8, prefix_sharing=False)
        spec = self._run(model, params, prompts, 5,
                         cache_dtype=jnp.int8, draft_model=model,
                         draft_params=params, spec_k=3)
        for a, b in zip(plain, spec):
            np.testing.assert_array_equal(a, b)

    def test_speculation_disables_prefix_sharing(self):
        model, params = _model()
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, attn_impl="lax",
                                    draft_model=model,
                                    draft_params=params)
        assert not eng.cache.config.share_prefix
        assert not eng.draft_cache.config.share_prefix

    def test_bad_configs_rejected(self):
        model, params = _model()
        dmodel, _ = _draft()
        with pytest.raises(ValueError, match="draft_params"):
            serving.ServingEngine(model, params, draft_model=model)
        with pytest.raises(ValueError, match="spec_k"):
            serving.ServingEngine(model, params, draft_model=model,
                                  draft_params=params, spec_k=1)
        other = GPT(GPTConfig.tiny(vocab_size=32))
        with pytest.raises(ValueError, match="vocabulary"):
            serving.ServingEngine(
                model, params, draft_model=other,
                draft_params=other.init(jax.random.PRNGKey(0)))


class TestSpeculativeObservability:
    def test_accept_rate_histogram_and_request_stats(self):
        model, params = _model()
        rng = np.random.default_rng(11)
        reg = obs.MetricsRegistry()
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, prefill_chunk=8,
                                    attn_impl="lax", registry=reg,
                                    draft_model=model,
                                    draft_params=params, spec_k=4)
        rids = [eng.submit(p, 6) for p in _prompts(rng, [5, 8])]
        while not eng.scheduler.idle():
            eng.step()
        h = reg.histogram("serving_spec_accept_rate").summary()
        assert h["count"] > 0
        assert reg.counter("serving_spec_proposed_total").value() > 0
        for r in rids:
            stats = eng.request_stats(r)
            assert stats["spec_proposed"] >= stats["spec_accepted"] > 0
            assert stats["tokens"] == 6.0

    def test_zero_steady_state_recompiles_with_speculation(self):
        model, params = _model()
        dmodel, dparams = _draft()
        rng = np.random.default_rng(12)
        reg = obs.MetricsRegistry()
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, attn_impl="lax",
                                    registry=reg, cache_dtype=jnp.int8,
                                    draft_model=dmodel,
                                    draft_params=dparams, spec_k=3)
        eng.warmup()
        det = obs.RecompileDetector("spec_steady", warmup=0, registry=reg)
        eng.generate_many(_prompts(rng, [9, 4, 6, 13]), max_new_tokens=5,
                          max_steps=200)
        det.check()
        assert det.recompiles == 0, \
            "speculative+quantized steady state recompiled"


class TestSpeculativeBucketCoverage:
    """warmup_plan()/bucket-coverage extend to the draft/verify buckets
    — the ahead-of-time zero-recompile proof covers speculation."""

    def _engine(self):
        model, params = _model()
        return serving.ServingEngine(model, params, num_slots=2,
                                     page_size=4,
                                     max_tokens_per_slot=32,
                                     attn_impl="lax", draft_model=model,
                                     draft_params=params, spec_k=4)

    def test_plan_covers_reachable_including_verify(self):
        eng = self._engine()
        plan = set(eng.warmup_plan())
        assert any(s[0] == "verify" for s in plan)
        assert any(s[0] == "draft" for s in plan)
        assert any(s[0] == "draft_prefill" for s in plan)
        assert not any(s[0] == "decode" for s in plan)
        assert hlo_lint.serving_bucket_coverage(eng) == []

    def test_missing_verify_bucket_fires(self):
        eng = self._engine()
        doctored = {s for s in eng.warmup_plan() if s[0] != "verify"}
        findings = hlo_lint.serving_bucket_coverage(eng, warmed=doctored)
        assert findings and all(f.severity == "error" for f in findings)
        assert any("verify" in f.message for f in findings)

    def test_warmup_executes_the_whole_plan(self):
        eng = self._engine()
        eng.warmup(cost_gauges=False)
        assert eng.warmed_signatures == set(eng.warmup_plan())


class TestSpeculativeMigrationGuard:
    def test_snapshot_and_restore_refused(self):
        model, params = _model()
        eng = serving.ServingEngine(model, params, num_slots=2,
                                    page_size=4, attn_impl="lax",
                                    draft_model=model,
                                    draft_params=params)
        # the guard fires before any slot/state lookup
        with pytest.raises(serving.SlotMigrationError,
                           match="speculative"):
            eng.snapshot_slot(0)
        with pytest.raises(serving.SlotMigrationError,
                           match="speculative"):
            eng.restore_slot({"format": "x"})
