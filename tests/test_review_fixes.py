"""Regression tests for review findings (round-1 code review)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.ops import nn as F


def test_conv2d_transpose_fluid_shape():
    # fluid: out = (H-1)*s + k - 2p
    x = np.random.randn(1, 4, 4, 1).astype(np.float32)
    w = np.random.randn(3, 3, 1, 2).astype(np.float32)
    out = F.conv2d_transpose(x, w, stride=2, padding=0)
    assert out.shape == (1, 9, 9, 2), out.shape
    out = F.conv2d_transpose(x, w, stride=1, padding=1)
    assert out.shape == (1, 4, 4, 2), out.shape


def test_conv2d_transpose_is_conv_input_grad():
    """Deconv(y, w) must equal d/dx sum(conv(x, w) * y) — fluid defines it as
    the conv input-gradient."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (1, 5, 5, 2))      # conv input
    w = jax.random.normal(key, (3, 3, 2, 4))      # HWIO
    y = jax.random.normal(key, (1, 5, 5, 4))      # cotangent, conv 'SAME' p=1

    grad_x = jax.grad(
        lambda xx: jnp.sum(F.conv2d(xx, w, stride=1, padding=1) * y))(x)
    # deconv weight layout (kh,kw,I=deconv-in,O=deconv-out): conv weight with
    # its channel dims swapped
    deconv = F.conv2d_transpose(y, w.swapaxes(2, 3), stride=1, padding=1)
    np.testing.assert_allclose(np.asarray(deconv), np.asarray(grad_x),
                               rtol=1e-4, atol=1e-4)


def test_sequential_mode_kwargs():
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.BatchNorm(4),
                        nn.Dropout(0.5))
    params = net.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 8, 8, 1))
    out = net(params, x, training=True, key=jax.random.PRNGKey(1))
    assert out.shape == (2, 8, 8, 4)
    out_eval = net(params, x, training=False)
    assert out_eval.shape == (2, 8, 8, 4)


def test_adamw_decay_mask():
    model = nn.Linear(4, 4)
    params = model.init(jax.random.PRNGKey(0))

    def no_bias_decay(p):
        return {"weight": True, "bias": False}

    o = opt.AdamW(learning_rate=0.0, weight_decay=0.1,
                  decay_mask_fn=no_bias_decay)
    # lr=0 means adam update is 0; only decay acts. But decay uses lr -> 0.
    # use lr>0 with zero grads instead:
    o = opt.AdamW(learning_rate=1.0, weight_decay=0.1,
                  decay_mask_fn=no_bias_decay, epsilon=1.0)
    st = o.init(params)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    p2, _ = o.update(zeros, st, params)
    # bias: no grad, no decay -> unchanged; weight: decayed
    np.testing.assert_array_equal(np.asarray(p2["bias"]),
                                  np.asarray(params["bias"]))
    assert not np.allclose(np.asarray(p2["weight"]),
                           np.asarray(params["weight"]))


def test_executor_inference_repeat():
    """Non-donating inference program can run twice with same params."""
    model = nn.Linear(4, 2)
    params = model.init(jax.random.PRNGKey(0))
    prog = pt.Program(fn=lambda p, x: model(p, x), name="infer")
    exe = pt.Executor()
    x = np.ones((3, 4), np.float32)
    _, out1 = exe.run(prog, params, feed={"x": x})
    _, out2 = exe.run(prog, params, feed={"x": x})  # must not be deleted
    np.testing.assert_array_equal(out1, out2)


def test_make_mesh_shape_requires_axis_names():
    with pytest.raises(ValueError, match="axis_names"):
        pt.make_mesh(shape=(1,))


def test_cross_entropy_n1_labels():
    probs = np.full((4, 5), 0.2, np.float32)
    out = F.cross_entropy(jnp.asarray(probs), jnp.asarray(
        np.array([[0], [1], [2], [3]])))
    assert out.shape == (4, 1)
    np.testing.assert_allclose(np.asarray(out), -np.log(0.2), rtol=1e-5)


def test_ops_namespace_clean():
    import paddle_tpu.ops as ops
    for leaked in ("np", "jax", "jnp", "register_op"):
        assert not hasattr(ops, leaked), leaked


# --- round-5 ADVICE fixes ---------------------------------------------------

def test_img_conv_group_per_layer_dropout_keys():
    """One dropout_key reused across sublayers correlates their masks;
    the fix derives per-layer keys via fold_in, so two dropout layers must
    see DIFFERENT masks for the same input."""
    from paddle_tpu.nn.nets import ImgConvGroup

    m = ImgConvGroup(1, [4, 4], pool_size=2, pool_stride=2,
                     conv_with_batchnorm=True,
                     conv_batchnorm_drop_rate=0.5, conv_act="relu")
    params = m.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 8, 8, 1))
    key = jax.random.PRNGKey(7)
    # identical input through both dropout layers: if keys were shared the
    # kept/dropped pattern after each conv block would be byte-identical
    # between two forward calls with swapped layer indices; directly assert
    # fold_in produces distinct per-layer keys
    k0, k1 = jax.random.fold_in(key, 0), jax.random.fold_in(key, 1)
    assert not np.array_equal(np.asarray(k0), np.asarray(k1))
    out = m(params, x, training=True, dropout_key=key)
    assert out.shape[0] == 2  # forward still works under training+dropout
    # eval path is deterministic and key-free
    out1 = m(params, x, training=False)
    out2 = m(params, x, training=False)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_beam_search_explicit_beam_size_zero_rejected():
    from paddle_tpu.ops import beam_search as bs

    scores, done = bs.beam_init(2, 4)
    logp = jnp.zeros((2, 4, 10))
    with pytest.raises(ValueError, match="beam_size must be >= 1"):
        bs.beam_search_step(logp, scores, done, eos_id=1, beam_size=0)
    # None still defaults to K; explicit shrink still works
    tok, s, d, parent = bs.beam_search_step(logp, scores, done, eos_id=1)
    assert tok.shape == (2, 4)
    tok2, *_ = bs.beam_search_step(logp, scores, done, eos_id=1, beam_size=2)
    assert tok2.shape == (2, 2)


def test_sequence_conv_pool_even_filter_window_alignment():
    """filter_size=4 must use context_start=-(4//2)=-2 (reference
    sequence_conv default), not the old hardcoded -1."""
    from paddle_tpu.nn.nets import SequenceConvPool
    from paddle_tpu.ops import sequence as S

    m = SequenceConvPool(3, 5, 4, act=None, pool_type="max", bias=False)
    params = m.init(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 6, 3), jnp.float32)
    lengths = jnp.array([6, 4])
    got = m(params, x, lengths)
    want = S.sequence_pool(
        S.sequence_conv(x, lengths, params["filter"], context_start=-2),
        lengths, pool_type="max")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    # and it differs from the old -1 alignment (the bug being fixed)
    old = S.sequence_pool(
        S.sequence_conv(x, lengths, params["filter"], context_start=-1),
        lengths, pool_type="max")
    assert not np.allclose(np.asarray(got), np.asarray(old))
