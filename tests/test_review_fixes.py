"""Regression tests for review findings (round-1 code review)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt
from paddle_tpu.ops import nn as F


def test_conv2d_transpose_fluid_shape():
    # fluid: out = (H-1)*s + k - 2p
    x = np.random.randn(1, 4, 4, 1).astype(np.float32)
    w = np.random.randn(3, 3, 1, 2).astype(np.float32)
    out = F.conv2d_transpose(x, w, stride=2, padding=0)
    assert out.shape == (1, 9, 9, 2), out.shape
    out = F.conv2d_transpose(x, w, stride=1, padding=1)
    assert out.shape == (1, 4, 4, 2), out.shape


def test_conv2d_transpose_is_conv_input_grad():
    """Deconv(y, w) must equal d/dx sum(conv(x, w) * y) — fluid defines it as
    the conv input-gradient."""
    key = jax.random.PRNGKey(0)
    x = jax.random.normal(key, (1, 5, 5, 2))      # conv input
    w = jax.random.normal(key, (3, 3, 2, 4))      # HWIO
    y = jax.random.normal(key, (1, 5, 5, 4))      # cotangent, conv 'SAME' p=1

    grad_x = jax.grad(
        lambda xx: jnp.sum(F.conv2d(xx, w, stride=1, padding=1) * y))(x)
    # deconv weight layout (kh,kw,I=deconv-in,O=deconv-out): conv weight with
    # its channel dims swapped
    deconv = F.conv2d_transpose(y, w.swapaxes(2, 3), stride=1, padding=1)
    np.testing.assert_allclose(np.asarray(deconv), np.asarray(grad_x),
                               rtol=1e-4, atol=1e-4)


def test_sequential_mode_kwargs():
    net = nn.Sequential(nn.Conv2D(1, 4, 3, padding=1), nn.BatchNorm(4),
                        nn.Dropout(0.5))
    params = net.init(jax.random.PRNGKey(0))
    x = jnp.ones((2, 8, 8, 1))
    out = net(params, x, training=True, key=jax.random.PRNGKey(1))
    assert out.shape == (2, 8, 8, 4)
    out_eval = net(params, x, training=False)
    assert out_eval.shape == (2, 8, 8, 4)


def test_adamw_decay_mask():
    model = nn.Linear(4, 4)
    params = model.init(jax.random.PRNGKey(0))

    def no_bias_decay(p):
        return {"weight": True, "bias": False}

    o = opt.AdamW(learning_rate=0.0, weight_decay=0.1,
                  decay_mask_fn=no_bias_decay)
    # lr=0 means adam update is 0; only decay acts. But decay uses lr -> 0.
    # use lr>0 with zero grads instead:
    o = opt.AdamW(learning_rate=1.0, weight_decay=0.1,
                  decay_mask_fn=no_bias_decay, epsilon=1.0)
    st = o.init(params)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    p2, _ = o.update(zeros, st, params)
    # bias: no grad, no decay -> unchanged; weight: decayed
    np.testing.assert_array_equal(np.asarray(p2["bias"]),
                                  np.asarray(params["bias"]))
    assert not np.allclose(np.asarray(p2["weight"]),
                           np.asarray(params["weight"]))


def test_executor_inference_repeat():
    """Non-donating inference program can run twice with same params."""
    model = nn.Linear(4, 2)
    params = model.init(jax.random.PRNGKey(0))
    prog = pt.Program(fn=lambda p, x: model(p, x), name="infer")
    exe = pt.Executor()
    x = np.ones((3, 4), np.float32)
    _, out1 = exe.run(prog, params, feed={"x": x})
    _, out2 = exe.run(prog, params, feed={"x": x})  # must not be deleted
    np.testing.assert_array_equal(out1, out2)


def test_make_mesh_shape_requires_axis_names():
    with pytest.raises(ValueError, match="axis_names"):
        pt.make_mesh(shape=(1,))


def test_cross_entropy_n1_labels():
    probs = np.full((4, 5), 0.2, np.float32)
    out = F.cross_entropy(jnp.asarray(probs), jnp.asarray(
        np.array([[0], [1], [2], [3]])))
    assert out.shape == (4, 1)
    np.testing.assert_allclose(np.asarray(out), -np.log(0.2), rtol=1e-5)


def test_ops_namespace_clean():
    import paddle_tpu.ops as ops
    for leaked in ("np", "jax", "jnp", "register_op"):
        assert not hasattr(ops, leaked), leaked
