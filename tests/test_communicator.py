"""Async-communicator / GeoSGD tests (the reference's non-BSP modes).

Reference analog: communicator.h:276 AsyncCommunicator (merged delayed
gradient application), :323 GeoSgdCommunicator (periodic delta sync of
locally-trained params), tested for convergence parity against the
synchronous baseline — the reference's dist tests assert the async modes
still reach comparable loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import optimizer as opt
from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.nn.layers import Linear
from paddle_tpu.nn.module import Layer
from paddle_tpu.parallel.communicator import (AsyncCommunicator,
                                              GeoSgdCommunicator,
                                              geo_sgd_sync)


class _MLP(Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = Linear(8, 32, sharding=None)
        self.fc2 = Linear(32, 1, sharding=None)

    def forward(self, params, x):
        return self.fc2(params["fc2"],
                        jnp.tanh(self.fc1(params["fc1"], x)))[:, 0]

    def loss(self, params, x, y):
        return ((self(params, x) - y) ** 2).mean()


def _data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (0.7 * x[:, 0] - 0.3 * x[:, 1] + 0.1).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


class TestAsyncCommunicator:
    def test_merges_and_applies_everything(self):
        model = _MLP()
        params = model.init(jax.random.PRNGKey(0))
        comm = AsyncCommunicator(opt.SGD(learning_rate=0.0), params,
                                 max_merge=4)
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        for _ in range(10):
            comm.push(g)
        comm.flush()
        assert comm.pushed == 10
        # lr=0: params unchanged regardless of merge pattern
        for a, b in zip(jax.tree_util.tree_leaves(comm.pull()),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        comm.stop()

    def test_async_training_converges(self):
        """Hogwild-style: device computes grads on stale params, the host
        thread applies merged updates; must converge near the sync run."""
        model = _MLP()
        x, y = _data()
        params0 = model.init(jax.random.PRNGKey(0))
        grad_fn = jax.jit(jax.grad(lambda p, x, y: model.loss(p, x, y)))
        loss_fn = jax.jit(model.loss)

        # sync baseline
        sgd = opt.SGD(learning_rate=0.1)
        p, s = params0, sgd.init(params0)
        for i in range(60):
            lo = (i * 32) % 224
            p, s = sgd.update(grad_fn(p, x[lo:lo + 32], y[lo:lo + 32]),
                              s, p)
        sync_loss = float(loss_fn(p, x, y))

        # async: pull (possibly stale) params every step
        comm = AsyncCommunicator(opt.SGD(learning_rate=0.1), params0,
                                 max_merge=4)
        for i in range(60):
            lo = (i * 32) % 224
            comm.push(grad_fn(comm.pull(), x[lo:lo + 32], y[lo:lo + 32]))
        comm.stop()
        async_loss = float(loss_fn(comm.pull(), x, y))
        start_loss = float(loss_fn(params0, x, y))
        assert async_loss < start_loss * 0.2
        assert async_loss < max(sync_loss * 3.0, 0.05), \
            (async_loss, sync_loss)


class TestGeoSgd:
    def test_replica_sync_math(self):
        comm = GeoSgdCommunicator(sync_every=4)
        anchor = {"w": jnp.zeros((3,))}
        stacked = {"w": jnp.stack([jnp.full((3,), 1.0),
                                   jnp.full((3,), 3.0)])}
        new_stacked, new_anchor = comm.sync(stacked, anchor)
        # anchor + mean of deltas = 0 + (1 + 3)/2 = 2
        np.testing.assert_allclose(np.asarray(new_anchor["w"]), 2.0)
        np.testing.assert_allclose(np.asarray(new_stacked["w"]), 2.0)

    def test_cadence(self):
        comm = GeoSgdCommunicator(sync_every=3)
        anchor = {"w": jnp.zeros((2,))}
        stacked = {"w": jnp.ones((2, 2))}
        out, _ = comm.maybe_sync(stacked, anchor, step=0)
        assert out is stacked                     # no sync yet
        out, _ = comm.maybe_sync(stacked, anchor, step=2)
        assert out is not stacked                 # synced at cadence

    def test_local_replicas_converge(self):
        """K vmapped local replicas with periodic delta merge reach the
        sync baseline's neighborhood (GeoSGD convergence parity)."""
        model = _MLP()
        x, y = _data(512)
        K = 4
        params0 = model.init(jax.random.PRNGKey(0))
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (K,) + a.shape), params0)
        comm = GeoSgdCommunicator(sync_every=5)
        anchor = comm.init_anchor(stacked)

        sgd = opt.SGD(learning_rate=0.05)

        def local_step(p, s, xb, yb):
            g = jax.grad(lambda p: model.loss(p, xb, yb))(p)
            return sgd.update(g, s, p)

        vstep = jax.jit(jax.vmap(local_step))
        opt_state = jax.vmap(sgd.init)(stacked)
        xs = x.reshape(K, -1, 8)
        ys = y.reshape(K, -1)
        loss_fn = jax.jit(model.loss)
        start = float(loss_fn(params0, x, y))
        for step in range(50):
            lo = (step * 16) % 112
            stacked, opt_state = vstep(stacked, opt_state,
                                       xs[:, lo:lo + 16], ys[:, lo:lo + 16])
            stacked, anchor = comm.maybe_sync(stacked, anchor, step)
        final = float(loss_fn(anchor, x, y))
        assert final < start * 0.2, (start, final)

    def test_partial_participation_anchor_matters(self):
        """Only replicas past their cadence push; the anchor is
        load-bearing: non-participants keep their local params and the
        anchor moves by the participants' deltas only."""
        comm = GeoSgdCommunicator(sync_every=1)
        anchor = {"w": jnp.zeros((3,))}
        stacked = {"w": jnp.stack([jnp.full((3,), 4.0),
                                   jnp.full((3,), 8.0)])}
        mask = jnp.asarray([True, False])
        new_stacked, new_anchor = comm.sync(stacked, anchor, mask)
        # anchor' = 0 + (4 - 0)/2 = 2; replica 0 resets, replica 1 stays
        np.testing.assert_allclose(np.asarray(new_anchor["w"]), 2.0)
        np.testing.assert_allclose(np.asarray(new_stacked["w"][0]), 2.0)
        np.testing.assert_allclose(np.asarray(new_stacked["w"][1]), 8.0)

    def test_per_replica_cadence(self):
        """sync_every can be per-replica (geo_need_push_nums per trainer):
        replica 0 pushes every step, replica 1 every 3rd."""
        comm = GeoSgdCommunicator(sync_every=np.array([1, 3]))
        anchor = {"w": jnp.zeros((1,))}
        stacked = {"w": jnp.asarray([[3.0], [9.0]])}
        out, a1 = comm.maybe_sync(stacked, anchor, step=0)  # only rep 0
        np.testing.assert_allclose(np.asarray(a1["w"]), 1.5)
        np.testing.assert_allclose(np.asarray(out["w"]), [[1.5], [9.0]])
        out, a2 = comm.maybe_sync(out, a1, step=2)          # both push
        # anchor'' = 1.5 + ((1.5-1.5) + (9-1.5))/2 = 5.25
        np.testing.assert_allclose(np.asarray(a2["w"]), 5.25)
        np.testing.assert_allclose(np.asarray(out["w"]), 5.25)

    def test_spmd_geo_sync_divergent_workers(self):
        """SPMD form: stacked rows sharded over dp hold genuinely
        divergent per-worker params; sync merges deltas to the anchor."""
        mesh = make_mesh(MeshConfig(dp=8))
        anchor = {"w": jnp.full((4,), 1.0)}
        stacked = {"w": jnp.arange(8.0)[:, None]
                   * jnp.ones((1, 4)) + 1.0}   # worker i holds 1 + i
        with mesh_context(mesh):
            new_stacked, new_anchor = jax.jit(
                lambda p, a: geo_sgd_sync(p, a, mesh=mesh))(stacked, anchor)
        # anchor' = 1 + mean(i) = 4.5, every row reset to it
        np.testing.assert_allclose(np.asarray(new_anchor["w"]), 4.5)
        np.testing.assert_allclose(np.asarray(new_stacked["w"]),
                                   np.full((8, 4), 4.5))

    def test_spmd_geo_sync_partial(self):
        mesh = make_mesh(MeshConfig(dp=8))
        anchor = {"w": jnp.zeros((4,))}
        stacked = {"w": jnp.broadcast_to(
            jnp.arange(8.0)[:, None], (8, 4))}  # worker i holds i
        mask = jnp.asarray([True] * 4 + [False] * 4)
        with mesh_context(mesh):
            new_stacked, new_anchor = jax.jit(
                lambda p, a, m: geo_sgd_sync(p, a, participants=m,
                                             mesh=mesh))(
                stacked, anchor, mask)
        # anchor' = (0+1+2+3)/8 = 0.75; workers 4..7 keep their params
        np.testing.assert_allclose(np.asarray(new_anchor["w"]), 0.75)
        got = np.asarray(new_stacked["w"])
        np.testing.assert_allclose(got[:4], 0.75)
        np.testing.assert_allclose(got[4:],
                                   np.arange(4.0, 8.0)[:, None]
                                   * np.ones((1, 4)))


class TestAsyncCommunicatorErrors:
    def test_bad_grads_surface_not_deadlock(self):
        model = _MLP()
        params = model.init(jax.random.PRNGKey(0))
        comm = AsyncCommunicator(opt.SGD(learning_rate=0.1), params)
        comm.push({"wrong": jnp.ones((2,))})   # structure mismatch
        with pytest.raises(RuntimeError, match="worker failed"):
            comm.flush()                        # raises, does NOT hang


class TestFLCommunicator:
    """FedAvg rounds (fl_listen_and_serv_op.cc:244 — sync RPC loop over
    Fanin clients; merged globals are re-broadcast each round)."""

    def test_weighted_aggregate_math(self):
        from paddle_tpu.parallel.communicator import FLCommunicator

        fl = FLCommunicator()
        stacked = {"w": jnp.asarray([[1.0, 1.0], [3.0, 3.0], [5.0, 5.0]])}
        # weights 1:1:2 -> (1*1 + 3*1 + 5*2) / 4 = 3.5
        g = fl.aggregate(stacked, num_examples=jnp.asarray([1.0, 1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(g["w"]), [3.5, 3.5])
        assert fl.rounds == 1

    def test_partial_participation_and_fanin(self):
        from paddle_tpu.parallel.communicator import FLCommunicator

        fl = FLCommunicator(min_fanin=2)
        stacked = {"w": jnp.asarray([[2.0], [4.0], [100.0]])}
        mask = jnp.asarray([True, True, False])  # straggler dropped
        g = fl.aggregate(stacked, num_examples=jnp.asarray([1.0, 1.0, 9.0]),
                         participants=mask)
        np.testing.assert_allclose(np.asarray(g["w"]), [3.0])
        with pytest.raises(ValueError, match="fanin"):
            fl.aggregate(stacked, num_examples=jnp.ones((3,)),
                         participants=jnp.asarray([True, False, False]))

    def test_federated_rounds_converge(self):
        """3 clients with DISJOINT data shards; FedAvg rounds reach a
        model that fits all shards (the federated premise)."""
        from paddle_tpu.parallel.communicator import FLCommunicator

        rng = np.random.RandomState(0)
        true_w = rng.randn(6).astype(np.float32)
        shards = []
        for k in range(3):
            x = rng.randn(64, 6).astype(np.float32) + 0.5 * k  # shifted domains
            y = x @ true_w
            shards.append((jnp.asarray(x), jnp.asarray(y)))
        n_examples = jnp.asarray([64.0, 64.0, 64.0])

        def local_train(w, x, y, steps=10, lr=0.02):
            def loss(w):
                return jnp.mean((x @ w - y) ** 2)
            for _ in range(steps):
                w = w - lr * jax.grad(loss)(w)
            return w

        fl = FLCommunicator()
        global_w = jnp.zeros((6,))
        for _ in range(20):
            clients = fl.broadcast(global_w, 3)
            trained = jnp.stack([
                local_train(clients[k], *shards[k]) for k in range(3)])
            global_w = fl.aggregate(trained, num_examples=n_examples)

        err = float(jnp.linalg.norm(global_w - jnp.asarray(true_w)))
        assert err < 0.15, err
        total = float(sum(jnp.mean((x @ global_w - y) ** 2)
                          for x, y in shards))
        assert total < 0.1, total
