"""Async-communicator / GeoSGD tests (the reference's non-BSP modes).

Reference analog: communicator.h:276 AsyncCommunicator (merged delayed
gradient application), :323 GeoSgdCommunicator (periodic delta sync of
locally-trained params), tested for convergence parity against the
synchronous baseline — the reference's dist tests assert the async modes
still reach comparable loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu import optimizer as opt
from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.nn.layers import Linear
from paddle_tpu.nn.module import Layer
from paddle_tpu.parallel.communicator import (AsyncCommunicator,
                                              GeoSgdCommunicator,
                                              geo_sgd_sync)


class _MLP(Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = Linear(8, 32, sharding=None)
        self.fc2 = Linear(32, 1, sharding=None)

    def forward(self, params, x):
        return self.fc2(params["fc2"],
                        jnp.tanh(self.fc1(params["fc1"], x)))[:, 0]

    def loss(self, params, x, y):
        return ((self(params, x) - y) ** 2).mean()


def _data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = (0.7 * x[:, 0] - 0.3 * x[:, 1] + 0.1).astype(np.float32)
    return jnp.asarray(x), jnp.asarray(y)


class TestAsyncCommunicator:
    def test_merges_and_applies_everything(self):
        model = _MLP()
        params = model.init(jax.random.PRNGKey(0))
        comm = AsyncCommunicator(opt.SGD(learning_rate=0.0), params,
                                 max_merge=4)
        g = jax.tree_util.tree_map(jnp.ones_like, params)
        for _ in range(10):
            comm.push(g)
        comm.flush()
        assert comm.pushed == 10
        # lr=0: params unchanged regardless of merge pattern
        for a, b in zip(jax.tree_util.tree_leaves(comm.pull()),
                        jax.tree_util.tree_leaves(params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        comm.stop()

    def test_async_training_converges(self):
        """Hogwild-style: device computes grads on stale params, the host
        thread applies merged updates; must converge near the sync run."""
        model = _MLP()
        x, y = _data()
        params0 = model.init(jax.random.PRNGKey(0))
        grad_fn = jax.jit(jax.grad(lambda p, x, y: model.loss(p, x, y)))
        loss_fn = jax.jit(model.loss)

        # sync baseline
        sgd = opt.SGD(learning_rate=0.1)
        p, s = params0, sgd.init(params0)
        for i in range(60):
            lo = (i * 32) % 224
            p, s = sgd.update(grad_fn(p, x[lo:lo + 32], y[lo:lo + 32]),
                              s, p)
        sync_loss = float(loss_fn(p, x, y))

        # async: pull (possibly stale) params every step
        comm = AsyncCommunicator(opt.SGD(learning_rate=0.1), params0,
                                 max_merge=4)
        for i in range(60):
            lo = (i * 32) % 224
            comm.push(grad_fn(comm.pull(), x[lo:lo + 32], y[lo:lo + 32]))
        comm.stop()
        async_loss = float(loss_fn(comm.pull(), x, y))
        start_loss = float(loss_fn(params0, x, y))
        assert async_loss < start_loss * 0.2
        assert async_loss < max(sync_loss * 3.0, 0.05), \
            (async_loss, sync_loss)


class TestGeoSgd:
    def test_replica_sync_math(self):
        comm = GeoSgdCommunicator(sync_every=4)
        anchor = {"w": jnp.zeros((3,))}
        stacked = {"w": jnp.stack([jnp.full((3,), 1.0),
                                   jnp.full((3,), 3.0)])}
        new_stacked, new_anchor = comm.sync(stacked, anchor)
        # anchor + mean of deltas = 0 + (1 + 3)/2 = 2
        np.testing.assert_allclose(np.asarray(new_anchor["w"]), 2.0)
        np.testing.assert_allclose(np.asarray(new_stacked["w"]), 2.0)

    def test_cadence(self):
        comm = GeoSgdCommunicator(sync_every=3)
        anchor = {"w": jnp.zeros((2,))}
        stacked = {"w": jnp.ones((2, 2))}
        out, _ = comm.maybe_sync(stacked, anchor, step=0)
        assert out is stacked                     # no sync yet
        out, _ = comm.maybe_sync(stacked, anchor, step=2)
        assert out is not stacked                 # synced at cadence

    def test_local_replicas_converge(self):
        """K vmapped local replicas with periodic delta merge reach the
        sync baseline's neighborhood (GeoSGD convergence parity)."""
        model = _MLP()
        x, y = _data(512)
        K = 4
        params0 = model.init(jax.random.PRNGKey(0))
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (K,) + a.shape), params0)
        comm = GeoSgdCommunicator(sync_every=5)
        anchor = comm.init_anchor(stacked)

        sgd = opt.SGD(learning_rate=0.05)

        def local_step(p, s, xb, yb):
            g = jax.grad(lambda p: model.loss(p, xb, yb))(p)
            return sgd.update(g, s, p)

        vstep = jax.jit(jax.vmap(local_step))
        opt_state = jax.vmap(sgd.init)(stacked)
        xs = x.reshape(K, -1, 8)
        ys = y.reshape(K, -1)
        loss_fn = jax.jit(model.loss)
        start = float(loss_fn(params0, x, y))
        for step in range(50):
            lo = (step * 16) % 112
            stacked, opt_state = vstep(stacked, opt_state,
                                       xs[:, lo:lo + 16], ys[:, lo:lo + 16])
            stacked, anchor = comm.maybe_sync(stacked, anchor, step)
        final = float(loss_fn(anchor, x, y))
        assert final < start * 0.2, (start, final)

    def test_spmd_geo_sync_on_mesh(self):
        """geo_sgd_sync over the dp axis: per-shard divergent params merge
        to anchor + mean delta, replicated everywhere."""
        mesh = make_mesh(MeshConfig(dp=8))
        anchor = {"w": jnp.zeros((8, 4))}
        # give each dp shard a different param value via iota on dim 0
        params = {"w": jnp.broadcast_to(
            jnp.arange(8.0)[:, None], (8, 4))}
        # params is sharded over dp? geo_sgd_sync expects REPLICATED leaves
        # per worker with in_specs P() — emulate divergence by the shard's
        # own value: use axis_index inside a shard_map-trained step. Here
        # we instead check the identity: identical params on all workers
        # merge to themselves.
        with mesh_context(mesh):
            new_params, new_anchor = jax.jit(
                lambda p, a: geo_sgd_sync(p, a, mesh=mesh))(params, anchor)
        np.testing.assert_allclose(np.asarray(new_params["w"]),
                                   np.asarray(params["w"]))
        np.testing.assert_allclose(np.asarray(new_anchor["w"]),
                                   np.asarray(params["w"]))

    def test_spmd_geo_sync_divergent_workers(self):
        """Per-worker divergence (via axis_index) merges to the delta
        mean: anchor 0, worker i holds i -> merged = mean(0..7) = 3.5."""
        from jax.sharding import PartitionSpec as P

        mesh = make_mesh(MeshConfig(dp=8))

        def diverge_and_sync(anchor):
            def body(a):
                i = jax.lax.axis_index("dp").astype(jnp.float32)
                local = a + i          # worker-local params
                n = jax.lax.axis_size("dp")
                merged = a + jax.lax.psum(local - a, "dp") / n
                return merged

            spec = P()
            return jax.shard_map(body, mesh=mesh, in_specs=(spec,),
                                 out_specs=spec, check_vma=False)(anchor)

        with mesh_context(mesh):
            out = jax.jit(diverge_and_sync)(jnp.zeros((4,)))
        np.testing.assert_allclose(np.asarray(out), 3.5)
