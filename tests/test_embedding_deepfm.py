"""Sharded embedding + DeepFM tests (PS-world replacement, SURVEY §5.8)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.models.deepfm import DeepFM
from paddle_tpu.parallel.embedding import ShardedEmbedding, vocab_parallel_lookup


@pytest.fixture(scope="module")
def tp_mesh():
    return make_mesh(MeshConfig(dp=2, tp=4))


class TestVocabParallelLookup:
    def test_matches_plain_take(self, tp_mesh):
        table = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
        ids = jax.random.randint(jax.random.PRNGKey(1), (4, 6), 0, 32)
        ref = jnp.take(table, ids, axis=0)
        with mesh_context(tp_mesh):
            out = jax.jit(lambda i, t: vocab_parallel_lookup(
                i, t, mesh=tp_mesh))(ids, table)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-6)

    def test_grads_are_scatter_adds(self, tp_mesh):
        table = jax.random.normal(jax.random.PRNGKey(0), (32, 8))
        ids = jnp.array([1, 1, 5])  # repeated id accumulates

        def f(t):
            return vocab_parallel_lookup(ids, t, mesh=tp_mesh).sum()

        def f_ref(t):
            return jnp.take(t, ids, axis=0).sum()

        with mesh_context(tp_mesh):
            g = jax.jit(jax.grad(f))(table)
        g_ref = jax.grad(f_ref)(table)
        np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                                   atol=1e-6)
        assert float(g[1].sum()) == pytest.approx(16.0)  # 2 hits x dim 8

    def test_no_mesh_fallback(self):
        table = jnp.arange(12.0).reshape(6, 2)
        ids = jnp.array([0, 5])
        out = vocab_parallel_lookup(ids, table, mesh=None)
        np.testing.assert_allclose(np.asarray(out), [[0, 1], [10, 11]])


class TestShardedEmbedding:
    def test_combiner_sum(self):
        layer = ShardedEmbedding(16, 4, combiner="sum")
        params = layer.init(jax.random.PRNGKey(0))
        ids = jnp.array([[1, 2, 3]])
        out = layer(params, ids)
        ref = params["weight"][jnp.array([1, 2, 3])].sum(0)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref),
                                   atol=1e-6)

    def test_padding_idx_zeroed(self):
        layer = ShardedEmbedding(16, 4, padding_idx=0)
        params = layer.init(jax.random.PRNGKey(0))
        out = layer(params, jnp.array([[0, 1]]))
        assert np.allclose(np.asarray(out[0, 0]), 0.0)
        assert not np.allclose(np.asarray(out[0, 1]), 0.0)


class TestDeepFM:
    def _batch(self, key, b=16, f=6, vocab=64):
        kid, kl = jax.random.split(key)
        ids = jax.random.randint(kid, (b, f), 0, vocab)
        label = jax.random.bernoulli(kl, 0.5, (b,)).astype(jnp.float32)
        return ids, label

    def test_forward_shape(self):
        model = DeepFM(64, 6, embed_dim=4, hidden=(16, 8))
        params = model.init(jax.random.PRNGKey(0))
        ids, _ = self._batch(jax.random.PRNGKey(1))
        logits = model(params, ids)
        assert logits.shape == (16,)

    def test_learns(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.train import build_train_step, make_train_state

        model = DeepFM(64, 6, embed_dim=4, hidden=(16, 8))
        optimizer = opt.Adam(learning_rate=1e-2)
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        ids, label = self._batch(jax.random.PRNGKey(1))

        def loss_fn(params, feat_ids, label):
            return model.loss(params, feat_ids, label)

        step = jax.jit(build_train_step(loss_fn, optimizer))
        losses = []
        for _ in range(20):
            state, m = step(state, feat_ids=ids, label=label)
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.8

    def test_sharded_train_step(self, tp_mesh):
        """Full DeepFM step with the table sharded over tp on a dp x tp
        mesh — the TPU replacement of the pserver CTR job."""
        from paddle_tpu import optimizer as opt
        from paddle_tpu.parallel import api as papi
        from paddle_tpu.train import build_train_step, make_train_state

        model = DeepFM(64, 6, embed_dim=4, hidden=(16, 8))
        optimizer = opt.Adam(learning_rate=1e-2)
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        ids, label = self._batch(jax.random.PRNGKey(1))

        def loss_fn(params, feat_ids, label):
            return model.loss(params, feat_ids, label)

        step = build_train_step(loss_fn, optimizer)
        hints = model.sharding_specs(state["params"])
        with mesh_context(tp_mesh):
            run, placed = papi.shard_train_step(
                step, tp_mesh, state, hints=hints,
                batch_spec=papi.batch_specs(
                    dict(feat_ids=ids, label=label)))
            new_state, m = run(placed, feat_ids=ids, label=label)
        assert np.isfinite(float(m["loss"]))
        # table really sharded: each device holds 64/4 rows
        emb_sh = new_state["params"]["embedding"]["weight"].sharding
        assert emb_sh.spec[0] == "tp"
