"""Multi-process distributed tests: real localhost worker processes.

Reference analog: ``test_dist_base.py`` — ``_run_cluster``:629 spawns
trainer subprocesses, ``check_with_place``:828 asserts per-step loss parity
between the distributed run and a local single-process run; pserver tests
kill processes to exercise failure detection. Here the workers bootstrap
with ``fleet.init`` -> ``jax.distributed.initialize`` over a localhost
coordinator (CPU backend, Gloo collectives) and train the same model
data-parallel; the kill test exercises HeartbeatMonitor / coordination-
service failure detection.

These tests manage their own subprocesses (each with its own single-device
CPU backend), independent of the in-process 8-device fixture.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest
pytestmark = pytest.mark.slow  # excluded from the quick CI gate


_WORKER = os.path.join(os.path.dirname(__file__), "dist_worker.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _env():
    from paddle_tpu.testing import subprocess_env
    env = subprocess_env()
    # a virtual-device-count flag from the parent suite would give every
    # worker 8 local devices and break the 2-process topology
    if "XLA_FLAGS" in env:
        env["XLA_FLAGS"] = " ".join(
            f for f in env["XLA_FLAGS"].split()
            if "xla_force_host_platform_device_count" not in f)
    return env


def _spawn(rank, nproc, port, out, *, steps=5, mode="parity", die_at=-1):
    # stderr goes to a file, not a pipe: an undrained pipe can fill and
    # block the child (spurious timeout); the file is read on failure
    errlog = open(out + ".stderr", "w")
    proc = subprocess.Popen(
        [sys.executable, _WORKER, "--rank", str(rank), "--nproc",
         str(nproc), "--port", str(port), "--out", out, "--steps",
         str(steps), "--mode", mode, "--die-at", str(die_at)],
        env=_env(), stdout=subprocess.DEVNULL, stderr=errlog)
    errlog.close()
    proc.errlog_path = out + ".stderr"
    return proc


def _wait_all(procs, timeout=180):
    deadline = time.monotonic() + timeout
    for p in procs:
        try:
            p.wait(timeout=max(1, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            for q in procs:
                if q.poll() is None:
                    q.kill()
            pytest.fail("distributed worker timed out")


class TestDistLossParity:
    def test_two_process_matches_single(self, tmp_path):
        """2-worker dp run must produce the same per-step losses as a
        single-process run on the same global batches (the reference's
        check_with_place delta assert, delta -> exact here: same arithmetic,
        psum mean over the same global batch)."""
        steps = 5
        # distributed: 2 processes
        port = _free_port()
        outs = [str(tmp_path / f"w{r}.json") for r in range(2)]
        procs = [_spawn(r, 2, port, outs[r], steps=steps) for r in range(2)]
        # local baseline: 1 process, full batch
        out1 = str(tmp_path / "single.json")
        single = _spawn(0, 1, _free_port(), out1, steps=steps)
        _wait_all(procs + [single])
        for p in procs + [single]:
            assert p.returncode == 0, open(p.errlog_path).read()[-800:]

        dist = [json.load(open(o)) for o in outs]
        base = json.load(open(out1))
        assert len(base["losses"]) == steps
        for w in dist:
            assert len(w["losses"]) == steps
            np.testing.assert_allclose(w["losses"], base["losses"],
                                       rtol=1e-5, atol=1e-6)
        # losses actually decreased (the run trained, not just agreed)
        assert base["losses"][-1] < base["losses"][0]

    def test_elastic_gang_restart_resumes_from_checkpoint(self, tmp_path):
        """Full fault-tolerance loop: rank 1 crashes mid-run, the
        ElasticCoordinator kills and respawns the gang, workers resume
        from the latest checkpoint, and the final per-step loss history
        is IDENTICAL to an uninterrupted run (deterministic data by step
        index). Reference: §5.3 restart policy over heart_beat_monitor
        detection."""
        from paddle_tpu.fleet import ElasticCoordinator

        steps = 6
        # baseline: uninterrupted 2-process run
        bport = _free_port()
        bouts = [str(tmp_path / f"base{r}.json") for r in range(2)]
        procs = [_spawn(r, 2, bport, bouts[r], steps=steps)
                 for r in range(2)]
        _wait_all(procs)
        base = json.load(open(bouts[0]))["losses"]
        assert len(base) == steps

        # elastic: crash rank 1 at step 3 on attempt 0
        ckpt = str(tmp_path / "elastic.ckpt")
        outs = [str(tmp_path / f"e{r}.json") for r in range(2)]
        ports = {}

        def spawn(rank, attempt):
            if attempt not in ports:
                ports[attempt] = _free_port()  # fresh coordinator per gang
            p = subprocess.Popen(
                [sys.executable, _WORKER, "--rank", str(rank), "--nproc",
                 "2", "--port", str(ports[attempt]), "--out", outs[rank],
                 "--steps", str(steps), "--mode", "elastic", "--die-at",
                 "3", "--ckpt", ckpt, "--attempt", str(attempt)],
                env=_env(), stdout=subprocess.DEVNULL,
                stderr=open(outs[rank] + f".a{attempt}.stderr", "w"))
            return p

        coord = ElasticCoordinator(spawn, 2, max_restarts=2,
                                   log_fn=lambda m: None)
        assert coord.run(timeout_s=240), "elastic job did not finish"
        assert coord.restarts == 1           # exactly one gang restart

        rec = json.load(open(outs[0]))
        assert any(e["kind"] == "resumed" and e["step"] == 3
                   for e in rec["events"]), rec["events"]
        np.testing.assert_allclose(rec["losses"], base, rtol=1e-6)

    def test_worker_death_is_detected(self, tmp_path):
        """Kill rank 1 mid-run; rank 0 must DETECT the failure (heartbeat
        stall callback or coordination-service error) and record it, not
        hang (test_dist_base kills pserver subprocesses similarly)."""
        port = _free_port()
        out0 = str(tmp_path / "w0.json")
        out1 = str(tmp_path / "w1.json")
        p0 = _spawn(0, 2, port, out0, steps=200, mode="stall", die_at=-1)
        p1 = _spawn(1, 2, port, out1, steps=200, mode="stall", die_at=3)
        _wait_all([p0, p1], timeout=180)
        assert p1.returncode == 9          # simulated crash
        assert p0.returncode in (3, 4), open(p0.errlog_path).read()[-800:]
        rec = json.load(open(out0))
        kinds = {e["kind"] for e in rec["events"]}
        assert kinds & {"stall_detected", "peer_failure"}, rec
        # some steps ran before the crash was noticed
        assert len(rec["losses"]) >= 1
