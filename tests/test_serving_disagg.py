"""Prefill/decode disaggregation (ISSUE 19): a flops-bound prefill
tier streaming prefill-complete slots into a KV-bound decode tier as
sha256-verified shard manifests (the live-migration transfer format).

The battery pins the acceptance: disaggregated greedy outputs are
BIT-IDENTICAL to the colocated fleet (fp and int8, tp=1 and tp=2, via
real shard manifests), a corrupt shard is refused all-or-nothing, no
request is ever lost (decode-capacity abort falls back to
decode-in-place, prefill/decode crashes redrive bit-identically), both
tiers run zero steady-state recompiles with per-tier bucket coverage,
the router never routes a fresh prompt to a decode-only replica, the
per-tier autoscaler scales each tier on ITS binding resource under a
fake clock, and the handoff is observable end to end (tier labels,
handoff counters, ``router.handoff`` spans on the request's trace,
``prefill_done_s``/``handoff_s``/``decode_start_s`` stamps)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.serving import fleet
from paddle_tpu.serving.engine import SlotMigrationError
from paddle_tpu.models.gpt import GPT, GPTConfig

VOCAB = 64


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_params, tracer=None, **kw):
    model, params = model_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_tokens_per_slot", 32)
    kw.setdefault("prefill_chunk", 4)
    return serving.ServingEngine(model, params, attn_impl="lax",
                                 registry=obs.MetricsRegistry(),
                                 tracer=tracer, **kw)


def _disagg_fleet(model_params, tracer=None, faults=None,
                  pre_kw=None, dec_kw=None, wrap=None, **kw):
    """1 prefill + 1 decode LocalReplica behind a FleetRouter; ``wrap``
    maps tier -> ChaosSpec kwargs."""
    tracer = tracer or obs.Tracer(enabled=False)
    pre = fleet.LocalReplica(
        _engine(model_params, tracer=tracer, tier="prefill",
                **dict(kw, **(pre_kw or {}))), name="p0").warmup()
    dec = fleet.LocalReplica(
        _engine(model_params, tracer=tracer, tier="decode",
                **dict(kw, **(dec_kw or {}))), name="d0").warmup()
    reps = {"prefill": pre, "decode": dec}
    if wrap:
        for tier, spec in wrap.items():
            reps[tier] = fleet.ChaosReplica(reps[tier], **spec)
    router = fleet.FleetRouter(
        [reps["prefill"], reps["decode"]], policy="p2c",
        registry=obs.MetricsRegistry(), tracer=tracer, seed=0,
        **({"faults": faults} if faults is not None else {}))
    return router, reps["prefill"], reps["decode"]


def _prompts(n, rng=None, lo=3, hi=9):
    rng = rng or np.random.default_rng(0)
    return [rng.integers(1, VOCAB, int(rng.integers(lo, hi)))
            .astype(np.int32) for _ in range(n)]


_REF = {}


def _reference(model_params, prompts, max_new, **kw):
    """Failure-free colocated reference, one engine per config key."""
    key = (max_new, tuple(sorted(kw.items(), key=lambda x: str(x))),
           tuple(int(p.sum()) for p in prompts))
    if key not in _REF:
        eng = _engine(model_params, **kw)
        eng.warmup()
        _REF[key] = [np.asarray(t) for t in
                     eng.generate_many(prompts, max_new, eos_id=None)]
    return _REF[key]


def _drain(router, max_steps=3000):
    out = {}
    for _ in range(max_steps):
        out.update(router.step())
        if router.idle():
            break
    else:
        raise AssertionError("fleet not idle")
    return out


class TestDisaggParity:
    """Greedy tokens through the prefill -> handoff -> decode pipeline
    must be BIT-IDENTICAL to a colocated run — the handoff is the
    hash-verified migration format, so nothing may drift."""

    def test_fp_parity_and_streaming(self, model_params):
        prompts = _prompts(6)
        ref = _reference(model_params, prompts, 8)
        router, pre, dec = _disagg_fleet(model_params)
        frids = [router.submit(p, 8) for p in prompts]
        _drain(router)
        outs = [router.result(f) for f in frids]
        assert all(o is not None for o in outs)
        assert all(np.array_equal(o, r) for o, r in zip(outs, ref))
        # every request crossed the tier boundary
        assert router.handoffs_total == len(prompts)
        # decode happened on the decode tier, not in place
        assert dec.engine.migrated_in_total == len(prompts)

    def test_int8_parity(self, model_params):
        prompts = _prompts(4, rng=np.random.default_rng(7))
        ref = _reference(model_params, prompts, 6,
                         cache_dtype=jnp.int8, num_pages=65)
        router, _pre, _dec = _disagg_fleet(
            model_params, cache_dtype=jnp.int8, num_pages=65)
        frids = [router.submit(p, 6) for p in prompts]
        _drain(router)
        outs = [router.result(f) for f in frids]
        assert all(np.array_equal(o, r) for o, r in zip(outs, ref))
        assert router.handoffs_total == len(prompts)

    @pytest.mark.skipif(len(jax.devices()) < 4,
                        reason="tp tests need >= 4 (virtual) devices")
    def test_tp2_parity_real_shard_manifests(self, model_params):
        """tp=2 on both tiers: the prefill tier runs the REAL Megatron
        MLP shard (ffn column/row split, second psum) and the handoff
        carries per-(page, tp-shard) manifests; decode must still be
        bit-identical to the tp=1 colocated reference."""
        from paddle_tpu.core.mesh import MeshConfig, make_mesh
        prompts = _prompts(4, rng=np.random.default_rng(3))
        ref = _reference(model_params, prompts, 6)
        kw = dict(page_size=8, max_tokens_per_slot=64)

        def mesh():
            return make_mesh(MeshConfig(tp=2),
                             devices=jax.devices()[:2])

        router, pre, _dec = _disagg_fleet(
            model_params, pre_kw={"mesh": mesh()},
            dec_kw={"mesh": mesh()}, **kw)
        assert pre.engine._mlp_sharded, \
            "prefill tier must run the sharded MLP"
        frids = [router.submit(p, 6) for p in prompts]
        _drain(router)
        outs = [router.result(f) for f in frids]
        assert all(np.array_equal(o, r) for o, r in zip(outs, ref))
        assert router.handoffs_total == len(prompts)

    def test_corrupt_shard_refused_all_or_nothing(self, model_params):
        """A flipped bit in one page shard must fail the sha256 check
        BEFORE anything is written: the decode engine stays empty and
        the same snapshot restores cleanly elsewhere."""
        pre = _engine(model_params, tier="prefill")
        dec = _engine(model_params, tier="decode")
        pre.submit(_prompts(1)[0], 8)
        handoffs = []
        for _ in range(50):
            pre.step()
            handoffs = pre.poll_handoffs()
            if handoffs:
                break
        (rid, snap), = handoffs
        evil = dict(snap, shards=[np.array(s, copy=True)
                                  for s in snap["shards"]])
        flat = evil["shards"][0].reshape(-1)
        flat[0] = flat[0] + 1
        with pytest.raises(SlotMigrationError):
            dec.restore_slot(evil)
        assert not dec.scheduler.active_slots(), \
            "corrupt restore must write NOTHING"
        # the pristine snapshot still restores (nothing was consumed)
        nrid = dec.restore_slot(snap)
        assert nrid in {st.request.rid
                        for s in dec.scheduler.active_slots()
                        for st in [dec.scheduler.slots[s]]}

    def test_decode_tier_mid_prefill_restore_refused(self, model_params):
        """Decode-tier engines restore only prefill-COMPLETE slots."""
        src = _engine(model_params, prefill_budget=4)
        dec = _engine(model_params, tier="decode")
        p = np.arange(1, 17, dtype=np.int32)     # 16 tokens, chunk=4
        src.submit(p, 8)
        slot = None
        for _ in range(50):                      # stop mid-prefill
            src.step()
            mid = [s for s in src.scheduler.active_slots()
                   if not src.scheduler.slots[s].prefill_done]
            if mid:
                slot = mid[0]
                break
        assert slot is not None, "never observed a mid-prefill slot"
        snap = src.snapshot_slot(slot)
        with pytest.raises(SlotMigrationError, match="prefill-complete"):
            dec.restore_slot(snap)


class TestNoLostRequests:
    def test_decode_capacity_abort_decodes_in_place(self, model_params):
        """Decode tier too small for the wave: the unplaceable handoff
        restores BACK into the prefill replica with the
        decode-in-place marker — every request still finishes with
        bit-identical tokens, none lost, no Reject needed."""
        prompts = _prompts(6)
        ref = _reference(model_params, prompts, 8)
        reg = obs.MetricsRegistry()
        pre = fleet.LocalReplica(
            _engine(model_params, tier="prefill"), name="p0").warmup()
        dec = fleet.LocalReplica(
            _engine(model_params, tier="decode", num_slots=2,
                    num_pages=17), name="d0").warmup()
        router = fleet.FleetRouter([pre, dec], policy="p2c",
                                   registry=reg, seed=0)
        frids = [router.submit(p, 8) for p in prompts]
        _drain(router)
        outs = [router.result(f) for f in frids]
        assert all(o is not None for o in outs), "request lost"
        assert all(np.array_equal(o, r) for o, r in zip(outs, ref))
        fb = reg.get("fleet_handoff_fallback_total")
        assert fb is not None and fb.value(replica="p0") > 0, \
            "expected at least one decode-in-place fallback"

    def test_prefill_crash_mid_handoff_redrives_bit_identical(
            self, model_params):
        """ChaosReplica kills the prefill replica exactly at
        poll_handoffs: in-flight requests redrive from the replay
        records onto the surviving colocated peer, outputs
        bit-identical, 0 lost."""
        prompts = _prompts(4)
        ref = _reference(model_params, prompts, 8)
        tracer = obs.Tracer(enabled=False)
        pre = fleet.ChaosReplica(
            fleet.LocalReplica(
                _engine(model_params, tier="prefill"),
                name="p0").warmup(),
            crash_on_handoff=True)
        # the survivor is colocated so redriven prompts can decode
        colo = fleet.LocalReplica(
            _engine(model_params), name="c0").warmup()
        router = fleet.FleetRouter(
            [pre, colo], policy="p2c", registry=obs.MetricsRegistry(),
            tracer=tracer, seed=0,
            faults=fleet.FaultPolicy(max_consecutive_failures=1,
                                     probe_timeout_s=30.0))
        frids = [router.submit(p, 8) for p in prompts]
        _drain(router)
        done, shed = 0, 0
        for f, r in zip(frids, ref):
            out = router.result(f)
            if out is not None:
                assert np.array_equal(out, r), \
                    "redriven output diverged"
                done += 1
            else:
                assert router.reject_reason(f) is not None, \
                    f"request {f} silently lost"
                shed += 1
        assert done + shed == len(frids)
        assert done > 0
        assert pre not in router.replicas, "dead prefill not ejected"

    def test_decode_crash_mid_restore_no_lost(self, model_params):
        """ChaosReplica kills the decode replica at restore(): the
        handoff placement fails over (decode-in-place on the source),
        the dead replica is ejected, and every request completes or
        sheds with a structured reason."""
        prompts = _prompts(4)
        ref = _reference(model_params, prompts, 8)
        pre = fleet.LocalReplica(
            _engine(model_params, tier="prefill"), name="p0").warmup()
        dec = fleet.ChaosReplica(
            fleet.LocalReplica(
                _engine(model_params, tier="decode"),
                name="d0").warmup(),
            crash_on_restore=True)
        router = fleet.FleetRouter(
            [pre, dec], policy="p2c", registry=obs.MetricsRegistry(),
            seed=0,
            faults=fleet.FaultPolicy(max_consecutive_failures=1,
                                     probe_timeout_s=30.0))
        frids = [router.submit(p, 8) for p in prompts]
        _drain(router)
        done, shed = 0, 0
        for f, r in zip(frids, ref):
            out = router.result(f)
            if out is not None:
                assert np.array_equal(out, r)
                done += 1
            elif router.reject_reason(f) is not None:
                shed += 1
            else:
                raise AssertionError(f"request {f} silently lost")
        assert done + shed == len(frids)
        assert done > 0


class TestTierContracts:
    def test_decode_tier_refuses_fresh_prompts(self, model_params):
        eng = _engine(model_params, tier="decode")
        with pytest.raises(ValueError, match="restored slots"):
            eng.submit(_prompts(1)[0], 4)

    def test_router_never_routes_prompts_to_decode_tier(
            self, model_params):
        router, pre, dec = _disagg_fleet(model_params)
        for p in _prompts(6):
            router.submit(p, 4)
        # every submit landed on the prefill replica
        assert dec.engine.scheduler.queue_depth() == 0
        assert not dec.engine.scheduler.active_slots()
        assert pre.engine.scheduler.queue_depth() \
            + len(pre.engine.scheduler.active_slots()) == 6
        _drain(router)

    def test_decode_only_fleet_has_no_prompt_candidates(
            self, model_params):
        dec = fleet.LocalReplica(
            _engine(model_params, tier="decode"), name="d0").warmup()
        router = fleet.FleetRouter([dec], policy="p2c",
                                   registry=obs.MetricsRegistry())
        with pytest.raises(SlotMigrationError, match="no routable"):
            router.submit(_prompts(1)[0], 4)

    def test_tier_validation(self, model_params):
        with pytest.raises(ValueError, match="tier"):
            _engine(model_params, tier="frontend")

    def test_zero_recompiles_and_bucket_coverage_both_tiers(
            self, model_params):
        """Post-warmup steady state compiles NOTHING on either tier,
        and each tier's warmup plan covers exactly its reachable
        signatures (prefill never compiles decode buckets, decode
        never compiles prefill buckets)."""
        router, pre, dec = _disagg_fleet(model_params)
        for eng, tier in ((pre.engine, "prefill"),
                          (dec.engine, "decode")):
            plan = set(eng.warmup_plan())
            reach = eng.reachable_signatures()
            assert plan >= reach, \
                f"{tier} coverage hole: {reach - plan}"
        kinds_pre = {s[0] for s in pre.engine.warmup_plan()}
        kinds_dec = {s[0] for s in dec.engine.warmup_plan()}
        assert "decode" not in kinds_pre and "prefill" in kinds_pre
        assert "prefill" not in kinds_dec and "decode" in kinds_dec
        frids = [router.submit(p, 8) for p in _prompts(6)]
        _drain(router)
        assert all(router.result(f) is not None for f in frids)
        assert pre.engine.recompile_detector.recompiles == 0, \
            "prefill tier recompiled in steady state"
        assert dec.engine.recompile_detector.recompiles == 0, \
            "decode tier recompiled in steady state"


class TestDisaggObservability:
    def test_health_tier_and_handoff_counters(self, model_params):
        router, pre, dec = _disagg_fleet(model_params)
        reg = router._reg
        frids = [router.submit(p, 6) for p in _prompts(4)]
        _drain(router)
        h = router.health()
        assert h["per_replica"]["p0"]["tier"] == "prefill"
        assert h["per_replica"]["d0"]["tier"] == "decode"
        assert h["handoffs_total"] == len(frids)
        assert reg.counter("fleet_handoff_total",
                           "x").value(src="p0", dst="d0") == len(frids)
        assert reg.counter("fleet_handoff_bytes_total",
                           "x").value(src="p0", dst="d0") > 0

    def test_colocated_health_has_no_tier_surprises(self, model_params):
        """A colocated engine advertises tier="colocated" and the
        monitor's per-replica gauges keep their exact pre-tier label
        sets (no tier label) — dashboards stay byte-identical."""
        eng = _engine(model_params)
        assert eng.health()["tier"] == "colocated"
        rep = fleet.LocalReplica(eng, name="m0")
        reg = obs.MetricsRegistry()
        router = fleet.FleetRouter([rep], policy="p2c", registry=reg)
        mon = fleet.FleetMonitor(router, registry=reg)
        mon.collect()
        assert reg.get("fleet_replica_queue_depth") \
            .value(replica="m0") == 0.0

    def test_monitor_tier_labels_on_tiered_fleet(self, model_params):
        router, _pre, _dec = _disagg_fleet(model_params)
        reg = obs.MetricsRegistry()
        mon = fleet.FleetMonitor(router, registry=reg)
        mon.collect()
        g = reg.get("fleet_replica_slot_occupancy")
        assert g.value(replica="p0", tier="prefill") == 0.0
        assert g.value(replica="d0", tier="decode") == 0.0

    def test_handoff_span_and_phase_stamps(self, model_params,
                                           tmp_path):
        """The router.handoff span rides the request's ONE trace id,
        request_stats carries ordered prefill_done_s <= handoff_s <=
        decode_start_s, and the exported trace passes
        check_metrics_log --trace (which validates handoff spans)."""
        tracer = obs.Tracer(capacity=4096)
        router, _pre, _dec = _disagg_fleet(model_params, tracer=tracer)
        frid = router.submit(_prompts(1)[0], 6)
        tid = router.trace_id(frid)
        assert tid
        _drain(router)
        st = router.request_stats(frid)
        assert st is not None
        assert 0 < st["prefill_done_s"] <= st["handoff_s"] \
            <= st["decode_start_s"]
        spans = [s for s in tracer.spans()
                 if s.name == "router.handoff"]
        assert spans, "no router.handoff span recorded"
        assert all(s.trace_id == tid for s in spans)
        assert spans[0].attrs["src"] == "p0"
        assert spans[0].attrs["dst"] == "d0"
        assert spans[0].attrs["bytes"] > 0
        path = str(tmp_path / "trace.jsonl")
        tracer.export_jsonl(path)
        from paddle_tpu.observability.tracing import validate_trace_log
        assert validate_trace_log(path, require_spans=1) > 0

    def test_trace_validator_rejects_bad_handoff_span(self):
        from paddle_tpu.observability.tracing import \
            validate_trace_record
        good = {"kind": "span", "trace_id": 7, "span_id": 1,
                "parent_id": 0, "name": "router.handoff", "ts": 1.0,
                "dur_s": 0.0, "attrs": {"src": "p0", "dst": "d0"}}
        validate_trace_record(good)
        with pytest.raises(ValueError, match="src"):
            validate_trace_record(
                dict(good, attrs={"dst": "d0"}))
        with pytest.raises(ValueError, match="trace_id=0"):
            validate_trace_record(dict(good, trace_id=0))
        with pytest.raises(ValueError, match="dst"):
            validate_trace_record(dict(good, attrs={"src": "p0"}))
        # a fallback handoff span legitimately has no dst
        validate_trace_record(dict(good, attrs={"src": "p0"},
                                   status="decode_in_place"))


class _FakeTiered(fleet.ReplicaHandle):
    """Health-only fake for autoscaler decision tests: a tier plus the
    headroom plane the per-tier signals read."""

    def __init__(self, name, tier, *, flops=1.0, pages=1.0, slots=1.0,
                 queue=0):
        self.name = name
        self.tier = tier
        self.flops = flops
        self.pages = pages
        self.slots = slots
        self.queue = queue
        self.warmed = False
        self.closed = False

    def page_size(self):
        return 4

    def prefix_digests(self):
        return frozenset()

    def health(self):
        return {"tier": self.tier, "queue_depth": self.queue,
                "requests_in_flight": 0, "slot_occupancy": 0.0,
                "page_utilization": 0.0,
                "headroom": {"flops": self.flops, "pages": self.pages,
                             "slots": self.slots, "hbm": 1.0}}

    def idle(self):
        return True

    def step(self):
        return {}

    def warmup(self):
        self.warmed = True
        return self

    def drain_queue(self):
        return []

    def snapshot_inflight(self):
        return []

    def close(self):
        self.closed = True


class TestTieredAutoscaler:
    def _scaler(self, tiers, **kw):
        kw.setdefault("sustain_s", 2.0)
        kw.setdefault("idle_s", 5.0)
        kw.setdefault("cooldown_s", 3.0)
        clock = [0.0]
        a = fleet.FleetAutoscaler(lambda i: None, tiers=tiers,
                                  registry=obs.MetricsRegistry(),
                                  clock=lambda: clock[0], **kw)
        return a, clock

    def test_prefill_scales_on_queue_pressure_decode_untouched(self):
        spawned = []

        def spawn(i):
            r = _FakeTiered(f"p{i}", "prefill")
            spawned.append(r)
            return r

        tiers = {"prefill": {"spawn": spawn, "min": 1, "max": 3,
                             "queue_hot": 4},
                 "decode": {"spawn": lambda i: _FakeTiered(
                     f"d{i}", "decode"), "min": 1, "max": 3}}
        a, clock = self._scaler(tiers)
        pre = _FakeTiered("p0", "prefill", queue=8)
        dec = _FakeTiered("d0", "decode")
        router = fleet.FleetRouter([pre, dec], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        assert a.tick() is None            # hot, not sustained
        clock[0] = 2.5
        assert a.tick() == "scale_out:prefill"
        assert spawned and spawned[0].warmed and spawned[0].tier == \
            "prefill"
        assert len(router.replicas) == 3
        clock[0] = 4.0                     # prefill cooldown holds
        assert a.tick() is None

    def test_decode_scales_on_kv_headroom(self):
        spawned = []

        def spawn(i):
            r = _FakeTiered(f"d{i}", "decode")
            spawned.append(r)
            return r

        tiers = {"decode": {"spawn": spawn, "min": 1, "max": 2,
                            "headroom_floor": 0.25}}
        a, clock = self._scaler(tiers)
        pre = _FakeTiered("p0", "prefill")
        dec = _FakeTiered("d0", "decode", pages=0.1)   # KV-starved
        router = fleet.FleetRouter([pre, dec], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        assert a.tick() is None
        clock[0] = 2.5
        assert a.tick() == "scale_out:decode"
        assert len(router.replicas) == 3
        dec.pages = 0.9
        spawned[0].pages = 0.9
        # max reached: pressure again never exceeds the tier cap
        dec.pages = 0.1
        clock[0] = 10.0
        assert a.tick() is None
        clock[0] = 13.0
        assert a.tick() is None, "scaled past the decode tier max"

    def test_per_tier_scale_in_on_idle(self, monkeypatch):
        tiers = {"prefill": {"spawn": lambda i: None, "min": 1,
                             "max": 3},
                 "decode": {"spawn": lambda i: None, "min": 1,
                            "max": 3}}
        a, clock = self._scaler(tiers)
        p0, p1 = (_FakeTiered("p0", "prefill"),
                  _FakeTiered("p1", "prefill"))
        dec = _FakeTiered("d0", "decode")
        router = fleet.FleetRouter([p0, p1, dec], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        drained = []
        monkeypatch.setattr(router, "drain_replica",
                            lambda rep, **kw: drained.append(rep) or 0)
        assert a.tick() is None            # idle starts counting
        clock[0] = 5.5
        assert a.tick() == "scale_in:prefill"
        assert drained and drained[0].tier == "prefill"
        # decode tier holds at its min=1 — never drained
        assert all(r.tier != "decode" for r in drained)

    def test_tier_replace_restores_lost_capacity(self):
        spawned = []

        def spawn(i):
            r = _FakeTiered(f"d{i}", "decode")
            spawned.append(r)
            return r

        tiers = {"decode": {"spawn": spawn, "min": 1, "max": 2}}
        a, clock = self._scaler(tiers)
        pre = _FakeTiered("p0", "prefill")
        dec = _FakeTiered("d0", "decode")
        router = fleet.FleetRouter([pre, dec], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        dec.draining = True                # decode capacity gone
        assert a.tick() == "replace:decode"
        assert spawned and spawned[0].warmed
        assert a.events[-1]["action"] == "replace"
        assert a.events[-1]["tier"] == "decode"

    def test_tiers_config_validation(self):
        with pytest.raises(ValueError, match="unknown tier"):
            fleet.FleetAutoscaler(lambda i: None,
                                  tiers={"frontend": {"spawn":
                                                      lambda i: None}})
        with pytest.raises(ValueError, match="spawn"):
            fleet.FleetAutoscaler(lambda i: None,
                                  tiers={"prefill": {}})
