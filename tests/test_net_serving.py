"""Network serving (ISSUE 17): process-isolated replicas behind the
wire-protocol ReplicaHandle and the streaming front door.

The battery pins the ISSUE acceptance:

- the wire codec round-trips every structure the ReplicaHandle surface
  traffics in (ndarrays, tuples, int-keyed maps, bytes, sets, the
  FullReplay marker), and corruption — bad magic, torn frames, checksum
  mismatches — raises ``WireError`` (a ``ConnectionError``, i.e.
  already inside the router's ``TRANSPORT_ERRORS``);
- structured rejects/errors survive the socket for the FULL
  ``Reject.reason`` vocabulary — a remote shed re-raises client-side
  with its typed verdict intact;
- ``NetReplica`` is indistinguishable from ``LocalReplica`` to the
  ``FleetRouter`` (zero router forks): a mixed net+local fleet produces
  bit-identical greedy outputs;
- heartbeat ages cross the wire as the sender's MONOTONIC deltas
  (patched-wall-clock regression test);
- socket chaos: a hung server opens the breaker and the deliberate
  probe closes it again (full open → half_open → closed over a real
  socket); a dead server is ejected on consecutive transport failures,
  its in-flight requests redriven bit-identically with 0 lost and a
  CLIENT-side postmortem (the remote witness is gone);
- the front door streams >=2 partial deliveries, sheds slow readers
  with a structured ``Reject`` (never a bare disconnect), and its
  crash-safe netlog validates: monotonic frames, every accepted rid
  terminated exactly once.

Subprocess legs (real ``kill -9``, SIGTERM drain → ``EXIT_DRAINED``)
run under ``-m slow`` with the rest of the multi-process tier; the
CI-gated bench (``bench.py --model net_router --dryrun``) exercises
the same battery against real processes on every run.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import jax
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.resilience.preempt import EXIT_DRAINED
from paddle_tpu.resilience.retry import RetryPolicy
from paddle_tpu.serving import fleet
from paddle_tpu.serving.fleet import net
from paddle_tpu.serving.fleet.net import frontdoor, wire
from paddle_tpu.serving.fleet.router import TRANSPORT_ERRORS
from paddle_tpu.serving.scheduler import LoadShedError, Reject

VOCAB = 64

CODECS = ["json"] + (["msgpack"] if wire.msgpack is not None else [])

# the full structured-shed vocabulary: engine submit/reap sheds, router
# redrive/requeue sheds, and the front door's own slow-reader verdict —
# read from the one registered source of truth so the parametrized wire
# tests can never drift from what the protocol validates
from paddle_tpu.serving.scheduler import REJECT_REASONS  # noqa: E402

FAST_RETRY = RetryPolicy(max_attempts=2, base_delay_s=0.02,
                         max_delay_s=0.1, deadline_s=2.0,
                         retry_on=(OSError, TimeoutError))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_params, **kw):
    model, params = model_params
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_tokens_per_slot", 48)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("decode_block", 2)
    return serving.ServingEngine(model, params, attn_impl="lax",
                                 registry=obs.MetricsRegistry(), **kw)


def _prompts(n, rng_seed=0, lens=(3, 5, 7)):
    rng = np.random.default_rng(rng_seed)
    return [rng.integers(1, VOCAB, int(k)).astype(np.int32)
            for k in rng.choice(lens, n)]


def _drive(handle, rids, max_steps=300):
    """Step ``handle`` until every rid in ``rids`` finished; returns
    the accumulated {rid: tokens} (results are pop-on-read upstream,
    so accumulate from step returns — never re-poll)."""
    done = {}
    for _ in range(max_steps):
        done.update(handle.step())
        if all(r in done for r in rids):
            return done
    raise AssertionError(f"{len(done)}/{len(rids)} finished "
                         f"in {max_steps} steps")


class ServerHarness:
    """A ReplicaServer driven from a plain thread, pausable (a paused
    server IS a hung host: accepted TCP, no replies) and stoppable (a
    stopped server IS a dead host: RST/refused)."""

    def __init__(self, engine, **kw):
        self.srv = net.ReplicaServer(engine, **kw)
        self._stop = threading.Event()
        self._pause = threading.Event()
        self._parked = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            if self._pause.is_set():
                self._parked.set()
                time.sleep(0.005)
                continue
            self.srv.serve_step(0.02)

    @property
    def address(self):
        return self.srv.address

    def pause(self):
        # synchronous: an in-flight serve_step could still answer an RPC
        # sent right after pause() returns, so wait until the loop parks
        self._parked.clear()
        self._pause.set()
        self._parked.wait(timeout=10)

    def resume(self):
        self._pause.clear()
        self._parked.clear()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=10)
        self.srv.close()


@pytest.fixture(scope="module")
def rig(model_params):
    """One warmed engine behind an in-thread ReplicaServer + a second
    warmed engine for local peers — shared across the quick tier."""
    eng_srv = _engine(model_params)
    harness = ServerHarness(eng_srv, name="netrig")
    rep = net.NetReplica(harness.address)
    rep.warmup()
    eng_local = _engine(model_params)
    fleet.LocalReplica(eng_local, name="warmer").warmup()
    yield {"harness": harness, "rep": rep, "eng_local": eng_local}
    rep.close()
    harness.stop()


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

class TestWire:
    @pytest.mark.parametrize("codec", CODECS)
    def test_payload_roundtrip(self, codec):
        payload = {
            "arr": np.arange(6, dtype=np.float32).reshape(2, 3),
            "ids": np.array([3, 1, 4], dtype=np.int32),
            "blob": b"\x00\xff\x10page",
            "tup": (1, "a", (2.5, None)),
            "intmap": {3: [1, 2], 7: []},
            "reserved": {"__buf__like": 1},
            "aset": {3, 1, 2},
            "replay": fleet.FullReplay([5, 6, 7]),
            "none": None, "flag": True, "f": 1.5,
        }
        dec = wire.MessageDecoder()
        msgs = dec.feed(wire.encode_message(payload, codec=codec))
        assert len(msgs) == 1
        out = msgs[0]
        assert np.array_equal(out["arr"], payload["arr"])
        assert out["arr"].dtype == np.float32
        assert np.array_equal(out["ids"], payload["ids"])
        assert out["blob"] == payload["blob"]
        assert out["tup"] == (1, "a", (2.5, None))
        assert isinstance(out["tup"], tuple)
        assert out["intmap"] == {3: [1, 2], 7: []}
        assert set(out["intmap"]) == {3, 7}          # int keys, not str
        assert out["reserved"] == {"__buf__like": 1}
        assert out["aset"] == frozenset({1, 2, 3})
        assert isinstance(out["replay"], fleet.FullReplay)
        assert list(out["replay"]) == [5, 6, 7]
        assert out["none"] is None and out["flag"] is True
        assert out["f"] == 1.5

    def test_pipelined_messages_in_ragged_chunks(self):
        a = wire.encode_message({"n": 1, "x": np.ones(4, np.int32)})
        b = wire.encode_message({"n": 2})
        stream = a + b
        dec = wire.MessageDecoder()
        got = []
        for i in range(0, len(stream), 7):        # deliberately torn reads
            got.extend(dec.feed(stream[i:i + 7]))
        assert [m["n"] for m in got] == [1, 2]
        assert np.array_equal(got[0]["x"], np.ones(4, np.int32))

    def test_checksum_mismatch_is_wire_error(self):
        msg = bytearray(wire.encode_message(
            {"snap": np.arange(32, dtype=np.float32)}))
        msg[-1] ^= 0xFF                           # corrupt the page bytes
        with pytest.raises(wire.WireError, match="checksum"):
            wire.MessageDecoder().feed(bytes(msg))

    def test_bad_magic_is_wire_error(self):
        with pytest.raises(wire.WireError, match="magic"):
            wire.MessageDecoder().feed(b"HTTP/1.1 200 OK\r\n\r\n")

    def test_frame_bound_is_wire_error(self):
        msg = wire.encode_message({"big": "x" * 1024})
        with pytest.raises(wire.WireError, match="exceeds"):
            wire.MessageDecoder(max_frame_bytes=64).feed(msg)

    def test_wire_error_feeds_the_breaker(self):
        # WireError must land in the router's transport vocabulary
        assert issubclass(wire.WireError, ConnectionError)
        assert issubclass(wire.WireError, TRANSPORT_ERRORS)

    @pytest.mark.parametrize("reason", REJECT_REASONS)
    @pytest.mark.parametrize("codec", CODECS)
    def test_reject_roundtrip_full_vocabulary(self, reason, codec):
        rej = Reject(reason, "interactive", 7, 0.25, 1.5)
        d = wire.reject_to_wire(rej)
        # force it through the actual codec, not just the dict helpers
        [d2] = wire.MessageDecoder().feed(
            wire.encode_message(d, codec=codec))
        assert wire.reject_from_wire(d2) == rej

    @pytest.mark.parametrize("reason", REJECT_REASONS)
    def test_load_shed_error_roundtrip(self, reason):
        rej = Reject(reason, "batch", 3, 1.0, 0.5)
        err = wire.error_from_wire(
            wire.error_to_wire(LoadShedError(rej)))
        assert isinstance(err, LoadShedError)
        assert err.reject == rej

    def test_error_roundtrip_typed_and_unknown(self):
        e = wire.error_from_wire(
            wire.error_to_wire(fleet.ReplicaCrashed("thread died")))
        assert isinstance(e, fleet.ReplicaCrashed)
        assert "thread died" in str(e)
        e = wire.error_from_wire(wire.error_to_wire(ValueError("nope")))
        assert isinstance(e, ValueError)

        class Weird(Exception):
            pass

        e = wire.error_from_wire(wire.error_to_wire(Weird("odd")))
        assert isinstance(e, wire.RemoteError)
        assert "Weird" in str(e) and "odd" in str(e)


# ---------------------------------------------------------------------------
# NetReplica over an in-thread server
# ---------------------------------------------------------------------------

class TestNetReplica:
    def test_hello_handshake(self, rig):
        rep = rig["rep"]
        assert rep.page_size() == 4
        assert rep.remote_pid == os.getpid()      # in-thread server
        assert rep.name == "netrig"               # adopted from the server

    def test_submit_step_parity_vs_local(self, rig, model_params):
        rep = rig["rep"]
        local = fleet.LocalReplica(rig["eng_local"], name="localpeer")
        prompts = _prompts(4, rng_seed=1)
        outs = {}
        for handle in (rep, local):
            rids = [handle.submit(p, 8) for p in prompts]
            done = _drive(handle, rids)
            outs[handle.name] = [np.asarray(done[r]) for r in rids]
        for a, b in zip(outs["netrig"], outs["localpeer"]):
            assert np.array_equal(a, b)           # the socket changed nothing

    def test_health_heartbeat_is_monotonic_delta(self, model_params,
                                                 monkeypatch):
        clk = FakeClock()
        eng = _engine(model_params)
        harness = ServerHarness(eng, name="clocked", clock=clk)
        try:
            rep = net.NetReplica(harness.address)
            rep.submit(np.array([1, 2, 3], np.int32), 4)  # submit beats
            clk.advance(7.25)
            # an NTP step on EITHER host must not fake a hang verdict:
            # jump the wall clock a year and the age must not move
            real_time = time.time
            monkeypatch.setattr(time, "time",
                                lambda: real_time() + 3.15e7)
            h = rep.health()
            assert h["heartbeat_age_s"] == pytest.approx(7.25, abs=0.01)
            assert h["rpcs_total"] >= 3           # hello + submit + health
            assert h["draining"] is False
            rep.close()
        finally:
            harness.stop()

    def test_progress_full_replay_over_wire(self, rig):
        rep = rig["rep"]
        rid = rep.submit(np.array([5, 6, 7, 8], np.int32), 8)
        live = {}
        for _ in range(200):                      # step to MID-flight
            rep.step()
            live = rep.progress()
            if len(live.get(rid, ())) >= 2:
                break
        assert len(live.get(rid, ())) >= 2
        # stale cursors (desync, post-restore rewind) answer with the
        # marked FULL stream — and the marker survives the socket
        for bogus in (10_000, -3):
            replay = rep.progress(since={rid: bogus})[rid]
            assert isinstance(replay, fleet.FullReplay)
            assert replay.full_replay is True
            assert list(replay) == list(live[rid])
        # a sane cursor still gets the cheap incremental tail
        tail = rep.progress(since={rid: 1})[rid]
        assert not isinstance(tail, fleet.FullReplay)
        assert list(tail) == list(live[rid])[1:]
        _drive(rep, [rid])                        # leave the rig idle

    def test_local_progress_stale_cursor_marks_full_replay(self, rig):
        local = fleet.LocalReplica(rig["eng_local"], name="lp2")
        rid = local.submit(np.array([9, 10, 11], np.int32), 6)
        live = {}
        for _ in range(200):
            local.step()
            live = local.progress()
            if len(live.get(rid, ())) >= 2:
                break
        normal = local.progress(since={rid: 1})[rid]
        assert not isinstance(normal, fleet.FullReplay)
        replay = local.progress(since={rid: 99})[rid]
        assert isinstance(replay, fleet.FullReplay)
        assert list(replay) == list(live[rid])
        _drive(local, [rid])

    def test_draining_refuses_submit_structurally(self, rig):
        rep = rig["rep"]
        try:
            rep.request_drain(True)
            assert rep.draining and not rep.can_accept(8)
            with pytest.raises(fleet.ReplicaUnavailable):
                rep.submit(np.array([1, 2], np.int32), 4)
        finally:
            rep.request_drain(False)
        assert rep.can_accept(8)

    def test_remote_error_reraises_typed(self, rig):
        with pytest.raises(ValueError, match="unknown op"):
            rig["rep"]._call("definitely_not_an_op", {})

    def test_timeout_drops_connection_then_reconnects(self, rig):
        harness = rig["harness"]
        rep2 = net.NetReplica(harness.address, name="impatient",
                              call_timeout_s=0.2, retry=FAST_RETRY)
        try:
            harness.pause()
            with pytest.raises(TRANSPORT_ERRORS):
                rep2.idle()
            # the socket died WITH the timed-out call: a late reply can
            # never be mis-paired with the next request
            assert not rep2.connected()
        finally:
            harness.resume()
        assert rep2.idle() in (True, False)       # lazy reconnect worked
        assert rep2.reconnects_total >= 2
        rep2.close()


# ---------------------------------------------------------------------------
# the router cannot tell (zero router forks)
# ---------------------------------------------------------------------------

class TestMixedFleet:
    def test_net_and_local_replicas_bit_identical(self, rig):
        rep_net = rig["rep"]
        rep_local = fleet.LocalReplica(rig["eng_local"], name="mixlocal")
        router = fleet.FleetRouter([rep_net, rep_local], seed=3,
                                   registry=obs.MetricsRegistry())
        prompts = _prompts(8, rng_seed=2)
        frids = [router.submit(p, 8) for p in prompts]
        placed = {router._where[f][0].name for f in frids}
        out = router.run_until_idle(max_steps=2000)
        assert sorted(out) == sorted(frids)
        # greedy decode is deterministic in the weights alone, so every
        # output must equal the single-replica reference regardless of
        # which side of the socket served it
        ref_rep = fleet.LocalReplica(rig["eng_local"], name="ref")
        for p, f in zip(prompts, frids):
            rid = ref_rep.submit(p, 8)
            done = _drive(ref_rep, [rid])
            assert np.array_equal(np.asarray(out[f]),
                                  np.asarray(done[rid]))
        # both transports actually served traffic in ONE router
        assert placed == {"netrig", "mixlocal"}


# ---------------------------------------------------------------------------
# socket chaos (in-thread tier; real subprocesses below under -m slow)
# ---------------------------------------------------------------------------

class TestSocketChaos:
    def test_hung_server_breaker_full_cycle(self, rig):
        harness = rig["harness"]
        rep_c = net.NetReplica(harness.address, name="hungC",
                               call_timeout_s=0.3, retry=FAST_RETRY)
        rep_ok = fleet.LocalReplica(rig["eng_local"], name="okpeer")
        fpol = fleet.FaultPolicy(max_consecutive_failures=10,
                                 probe_timeout_s=120.0,
                                 breaker_threshold=2,
                                 breaker_cooldown_s=0.25, max_redrives=3)
        router = fleet.FleetRouter([rep_c, rep_ok], seed=5, faults=fpol,
                                   registry=obs.MetricsRegistry())

        def trans():
            return [(o, n) for (name, o, n) in router.breaker_transitions
                    if name == "hungC"]

        harness.pause()                 # a hung host, not a dead one
        try:
            for _ in range(6):
                router.step()
                if ("closed", "open") in trans():
                    break
            assert ("closed", "open") in trans(), trans()
        finally:
            harness.resume()
        time.sleep(fpol.breaker_cooldown_s + 0.05)
        frids = [router.submit(np.array([1, 2, 3], np.int32), 4)
                 for _ in range(3)]
        done = router.run_until_idle(max_steps=2000)
        it = iter(trans())
        assert all(t in it for t in               # ordered subsequence
                   [("closed", "open"), ("open", "half_open"),
                    ("half_open", "closed")]), trans()
        assert router.ejected_total == 0          # quarantined, not killed
        for f in frids:
            assert f in done or router.reject_reason(f) is not None
        rep_c.close()

    def test_dead_server_ejected_redriven_bit_identical(self, rig,
                                                        model_params):
        eng_victim = _engine(model_params)
        harness = ServerHarness(eng_victim, name="victim")
        rep_net = net.NetReplica(harness.address, retry=FAST_RETRY,
                                 registry=obs.MetricsRegistry())
        rep_local = fleet.LocalReplica(rig["eng_local"], name="survivor")
        fpol = fleet.FaultPolicy(max_consecutive_failures=3,
                                 probe_timeout_s=120.0,
                                 breaker_threshold=2,
                                 breaker_cooldown_s=0.2, max_redrives=3)
        router = fleet.FleetRouter([rep_net, rep_local], seed=7,
                                   faults=fpol,
                                   registry=obs.MetricsRegistry())
        prompts = _prompts(6, rng_seed=3)
        # failure-free reference first: same prompts, same weights
        frids_clean = [router.submit(p, 8) for p in prompts]
        clean = router.run_until_idle(max_steps=2000)

        frids = [router.submit(p, 8) for p in prompts]   # chaos burst
        victim_frids = [f for f in frids
                        if router._where[f][0] is rep_net]
        assert victim_frids, "routing placed nothing on the victim"
        done = {}
        for _ in range(200):            # let the victim emit some tokens
            done.update(router.step())
            if any(router.progress(f) for f in victim_frids
                   if f not in done):
                break
        harness.stop()                  # the dead socket: RST + refused
        done.update(router.run_until_idle(max_steps=5000))
        missing = [f for f in frids if f not in done]
        verdicts = {f: router.reject_reason(f) for f in missing}
        silently_lost = [f for f, v in verdicts.items() if v is None]
        assert silently_lost == [], f"silently lost {silently_lost}"
        # with a healthy survivor and budget left, every request must
        # actually finish — and bit-identically to the clean run
        assert missing == [], f"shed instead of redriven: {verdicts}"
        for fc, f in zip(frids_clean, frids):
            assert np.array_equal(np.asarray(clean[fc]),
                                  np.asarray(done[f]))
        assert router.ejected_total >= 1
        assert router.redrives_total >= 1
        bundles = router.postmortems()
        assert "eject" in {b.get("reason") for b in bundles}
        for b in bundles:
            obs.validate_postmortem_bundle(b)
        # the remote witness is DEAD, so the eject bundle must be the
        # client-side flight recorder's testimony
        client_side = [b for b in bundles
                       if b.get("reason") == "eject"
                       and b.get("extra", {}).get("remote") is False]
        assert client_side, bundles
        assert client_side[0]["extra"]["transport_error"]
        rep_net.close()


# ---------------------------------------------------------------------------
# front door: streaming, backpressure, netlog
# ---------------------------------------------------------------------------

def _door_router(rig):
    rep = fleet.LocalReplica(rig["eng_local"], name="doorrep")
    return fleet.FleetRouter([rep], registry=obs.MetricsRegistry())


class TestFrontDoor:
    def test_streams_incrementally_with_netlog(self, rig, tmp_path):
        log = str(tmp_path / "door.netlog.jsonl")
        door = net.FrontDoor(_door_router(rig), netlog_path=log).start()
        try:
            results = []
            for i in range(2):
                cli = net.FrontDoorClient(door.address)
                try:
                    results.append(cli.generate(
                        _prompts(1, rng_seed=10 + i)[0], 24,
                        tag=f"t{i}", timeout_s=60.0))
                finally:
                    cli.close()
        finally:
            door.close()
        for r in results:
            assert r["reject"] is None
            assert len(r["tokens"]) == 24
            assert r["partials"] >= 2, "buffered, not streamed"
            # the incremental stream is a strict prefix of the result
            # (the final chunk rides the finished frame)
            assert r["streamed"] == r["tokens"][:len(r["streamed"])]
            assert r["ttft_s"] is not None
        summary = net.validate_netlog_file(log, require_requests=2)
        assert summary["accepted_requests"] == 2
        assert summary["finished"] == 2
        assert summary["stream"] >= 4
        assert summary["shed"] == 0

    def test_bad_request_is_structured_reject(self, rig):
        door = net.FrontDoor(_door_router(rig))
        cli = net.FrontDoorClient(door.address)
        try:
            cli.sock.sendall(wire.encode_message({"op": "nonsense"}))
            for _ in range(100):
                if door.pump():
                    break
                time.sleep(0.01)
            ev = cli.next_event(timeout=5.0)
            assert ev["event"] == "reject"
            assert ev["reason"] == "bad_request"
        finally:
            cli.close()
            door.close()

    def test_slow_reader_is_shed_with_typed_reject(self, rig, tmp_path):
        log = str(tmp_path / "slow.netlog.jsonl")
        door = net.FrontDoor(_door_router(rig), netlog_path=log,
                             max_buffer_frames=2)
        cli = net.FrontDoorClient(door.address)
        try:
            cli.send_generate(_prompts(1, rng_seed=20)[0], 24)
            for _ in range(200):
                door.pump()
                if door.accepted_total == 1:
                    break
            assert door.accepted_total == 1
            conn = next(iter(door._conns.values()))
            real_sock = conn.sock

            class _PluggedPipe:
                """A reader that stopped draining: every send blocks."""

                def send(self, _buf):
                    raise BlockingIOError

                def __getattr__(self, item):
                    return getattr(real_sock, item)

            conn.sock = _PluggedPipe()
            for _ in range(500):
                door.pump()             # decode keeps producing frames
                if door.shed_total >= 1:
                    break
            assert door.shed_total >= 1, "bounded buffer never shed"
            assert conn.closing
            conn.sock = real_sock       # let the final verdict flush
            for _ in range(50):
                door.pump()
                if conn.sock not in door._conns:
                    break
            # the client hears a TYPED verdict, not a bare disconnect
            ev = cli.next_event(timeout=5.0)
            while ev.get("event") != "reject":
                ev = cli.next_event(timeout=5.0)
            assert ev["reason"] == "slow_reader"
            rej = wire.reject_from_wire(ev["reject"])
            assert rej.reason == "slow_reader"
            assert rej.retry_after_s > 0
        finally:
            cli.close()
            door.close()
        summary = net.validate_netlog_file(log, require_requests=1)
        assert summary["shed"] == 1     # terminal accounting still holds

    def test_close_orphans_live_requests_as_redriven(self, rig, tmp_path):
        log = str(tmp_path / "orphan.netlog.jsonl")
        door = net.FrontDoor(_door_router(rig), netlog_path=log)
        cli = net.FrontDoorClient(door.address)
        try:
            cli.send_generate(_prompts(1, rng_seed=30)[0], 24)
            for _ in range(200):
                door.pump()
                if door.accepted_total == 1:
                    break
            assert door.accepted_total == 1
        finally:
            door.close()                # mid-decode shutdown
            cli.close()
        summary = net.validate_netlog_file(log, require_requests=1)
        assert summary["redriven"] == 1  # handed to the router, not lost

    def test_exposition_debug_netlog_route(self, rig, tmp_path):
        import urllib.error
        import urllib.request
        door = net.FrontDoor(_door_router(rig),
                             netlog_path=str(tmp_path / "e.jsonl"),
                             registry=obs.MetricsRegistry())
        srv = door.start_exposition(port=0)
        try:
            with pytest.raises(ValueError, match="reserved"):
                srv.add_json("/metrics", lambda: {})
            body = json.loads(urllib.request.urlopen(
                f"{srv.url}/debug/netlog", timeout=5).read())
            assert body["accepted_total"] == 0
            assert body["netlog_path"].endswith("e.jsonl")

            def sick():
                raise RuntimeError("provider down")

            srv.add_json("/debug/sick", sick)
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/debug/sick",
                                       timeout=5)
            assert ei.value.code == 503  # sick provider, live endpoint
        finally:
            srv.stop()
            door.close()


# ---------------------------------------------------------------------------
# netlog validator
# ---------------------------------------------------------------------------

def _nl(frame, event, **fields):
    rec = {"schema": frontdoor.NETLOG_SCHEMA, "frame": frame,
           "ts": 123.0 + frame, "event": event}
    rec.update(fields)
    return json.dumps(rec)


def _write_log(tmp_path, lines, name="log.jsonl"):
    p = tmp_path / name
    p.write_text("\n".join(lines) + "\n")
    return str(p)


class TestNetlogValidator:
    def _good(self):
        return [_nl(0, "listen", host="h", port=1),
                _nl(1, "conn_open", conn=1),
                _nl(2, "accept", rid=7, conn=1),
                _nl(3, "stream", rid=7, conn=1, tokens=2),
                _nl(4, "finished", rid=7, conn=1, tokens=8),
                _nl(5, "close")]

    def test_good_log(self, tmp_path):
        s = net.validate_netlog_file(
            _write_log(tmp_path, self._good()), require_requests=1)
        assert s["accepted_requests"] == 1
        assert s["finished"] == 1 and s["stream"] == 1
        assert s["lines"] == 6

    def test_torn_final_line_tolerated(self, tmp_path):
        p = tmp_path / "torn.jsonl"
        p.write_text("\n".join(self._good())
                     + '\n{"schema": "paddle_tpu.net')   # kill -9 here
        s = net.validate_netlog_file(str(p))
        assert s["lines"] == 6

    def test_torn_interior_line_is_corruption(self, tmp_path):
        lines = self._good()
        lines.insert(3, '{"schema": "paddle')
        with pytest.raises(ValueError, match="not JSON"):
            net.validate_netlog_file(_write_log(tmp_path, lines))

    def test_non_monotonic_frame(self, tmp_path):
        lines = self._good()
        lines[3] = _nl(1, "stream", rid=7, conn=1)
        with pytest.raises(ValueError, match="not monotonic"):
            net.validate_netlog_file(_write_log(tmp_path, lines))

    def test_accepted_without_terminal(self, tmp_path):
        lines = [_nl(0, "listen"), _nl(1, "conn_open", conn=1),
                 _nl(2, "accept", rid=7, conn=1),
                 _nl(3, "stream", rid=7, conn=1, tokens=2),
                 _nl(4, "close")]
        with pytest.raises(ValueError, match="no terminal"):
            net.validate_netlog_file(_write_log(tmp_path, lines))

    def test_terminal_for_unaccepted_rid(self, tmp_path):
        lines = [_nl(0, "listen"), _nl(1, "conn_open", conn=1),
                 _nl(2, "accept", rid=7, conn=1),
                 _nl(3, "finished", rid=7, conn=1, tokens=8),
                 _nl(4, "shed", rid=99, reason="x"),
                 _nl(5, "close")]
        with pytest.raises(ValueError, match="never accepted"):
            net.validate_netlog_file(_write_log(tmp_path, lines))

    def test_double_terminal(self, tmp_path):
        lines = [_nl(0, "listen"), _nl(1, "conn_open", conn=1),
                 _nl(2, "accept", rid=7, conn=1),
                 _nl(3, "finished", rid=7, conn=1, tokens=8),
                 _nl(4, "shed", rid=7, reason="x"),
                 _nl(5, "close")]
        with pytest.raises(ValueError, match="terminated twice"):
            net.validate_netlog_file(_write_log(tmp_path, lines))

    def test_duplicate_accept(self, tmp_path):
        lines = [_nl(0, "listen"), _nl(1, "conn_open", conn=1),
                 _nl(2, "accept", rid=7, conn=1),
                 _nl(3, "accept", rid=7, conn=1),
                 _nl(4, "finished", rid=7, conn=1, tokens=8),
                 _nl(5, "close")]
        with pytest.raises(ValueError, match="accepted twice"):
            net.validate_netlog_file(_write_log(tmp_path, lines))

    def test_unknown_event_and_schema(self, tmp_path):
        lines = self._good()
        lines[3] = _nl(3, "telemetry", rid=7)
        with pytest.raises(ValueError, match="unknown event"):
            net.validate_netlog_file(_write_log(tmp_path, lines))
        bad = json.loads(self._good()[0])
        bad["schema"] = "v0"
        with pytest.raises(ValueError, match="schema"):
            net.validate_netlog_file(
                _write_log(tmp_path, [json.dumps(bad)], name="s.jsonl"))

    def test_require_requests_gate(self, tmp_path):
        p = _write_log(tmp_path, self._good())
        with pytest.raises(ValueError, match="required >= 2"):
            net.validate_netlog_file(p, require_requests=2)

    def test_check_metrics_log_cli(self, tmp_path, capsys):
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "check_metrics_log_for_test",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), "tools",
                "check_metrics_log.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        good = _write_log(tmp_path, self._good())
        assert mod.main([good, "--netlog", "--require-requests", "1"]) == 0
        assert mod.main([good, "--netlog", "--require-requests", "9"]) == 1
        capsys.readouterr()


# ---------------------------------------------------------------------------
# real processes: kill -9 and SIGTERM drain (the slow tier; the CI-run
# bench dryrun drives the same battery on every run_ci.sh invocation)
# ---------------------------------------------------------------------------

SUBPROC_CONFIG = dict(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                      num_heads=2, ffn_size=32, max_position=64,
                      dropout=0.0, attn_impl="xla")
SUBPROC_ENGINE = dict(num_slots=2, page_size=4, max_tokens_per_slot=48,
                      prefill_chunk=4, decode_block=2, attn_impl="lax")


@pytest.mark.slow
class TestSubprocessChaos:
    def test_kill9_ejects_redrives_bit_identical(self):
        spawned = [net.spawn_replica_server(
            config=SUBPROC_CONFIG, engine=SUBPROC_ENGINE, seed=0,
            name=f"proc{i}", warmup=False) for i in range(2)]
        procs = [p for p, _ in spawned]
        try:
            reps = [net.NetReplica(addr, name=f"proc{i}",
                                   retry=FAST_RETRY)
                    for i, (_p, addr) in enumerate(spawned)]
            fpol = fleet.FaultPolicy(max_consecutive_failures=3,
                                     probe_timeout_s=120.0,
                                     breaker_threshold=2,
                                     breaker_cooldown_s=0.2,
                                     max_redrives=3)
            router = fleet.FleetRouter(reps, seed=11, faults=fpol,
                                       registry=obs.MetricsRegistry())
            prompts = _prompts(6, rng_seed=4)
            frids_clean = [router.submit(p, 8) for p in prompts]
            clean = router.run_until_idle(max_steps=5000)
            ref = [np.asarray(clean[f]) for f in frids_clean]

            frids = [router.submit(p, 8) for p in prompts]
            victim = reps[0]
            victim_frids = [f for f in frids
                            if router._where[f][0] is victim]
            if not victim_frids:        # routing went all-one-way: flip
                victim = reps[1]
                victim_frids = [f for f in frids
                                if router._where[f][0] is victim]
            assert victim_frids
            done = {}
            for _ in range(200):
                done.update(router.step())
                if any(router.progress(f) for f in victim_frids
                       if f not in done):
                    break
            vproc = procs[reps.index(victim)]
            os.kill(vproc.pid, signal.SIGKILL)    # the real dead socket
            vproc.wait(timeout=30)
            done.update(router.run_until_idle(max_steps=10_000))
            missing = [f for f in frids if f not in done]
            assert missing == [], {
                f: router.reject_reason(f) for f in missing}
            assert router.ejected_total >= 1
            assert router.redrives_total >= 1
            for f, r in zip(frids, ref):          # exactly-once, bit-equal
                assert np.array_equal(np.asarray(done[f]), r)
            reasons = {b.get("reason") for b in router.postmortems()}
            assert "eject" in reasons, reasons
            for rep in reps:
                rep.close()
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait()

    def test_sigterm_drains_to_exit_drained(self):
        proc, addr = net.spawn_replica_server(
            config=SUBPROC_CONFIG, engine=SUBPROC_ENGINE, seed=0,
            name="drainer", warmup=False)
        try:
            rep = net.NetReplica(addr, name="drainer")
            rid = rep.submit(np.array([1, 2, 3, 4], np.int32), 6)
            proc.send_signal(signal.SIGTERM)
            # draining refuses NEW work but finishes what is in flight
            deadline = time.monotonic() + 60
            while not rep.draining and time.monotonic() < deadline:
                rep.health()
                time.sleep(0.02)
            assert rep.draining
            with pytest.raises(fleet.ReplicaUnavailable):
                rep.submit(np.array([5, 6], np.int32), 4)
            done = {}
            while rid not in done and time.monotonic() < deadline:
                done.update(rep.step())
            assert len(done[rid]) == 6            # in-flight work finished
            rep.close()                           # last client leaves...
            proc.wait(timeout=60)                 # ...and the process exits
            assert proc.returncode == EXIT_DRAINED
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait()
