"""Executor + mesh placement tests: a mesh'd Executor.run must actually
shard the state per plan (CompiledProgram.with_data_parallel parity —
the reference broadcasts/places params per device builder decisions;
replicating silently is the bug under test)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.mesh import MeshConfig, make_mesh
from paddle_tpu.nn.layers import Linear
from paddle_tpu.nn.module import Layer


class _MLP(Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = Linear(16, 64, sharding=None)
        self.fc2 = Linear(64, 4, sharding=None)

    def forward(self, params, x):
        return self.fc2(params["fc2"], jnp.tanh(self.fc1(params["fc1"], x)))


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(MeshConfig(dp=2, fsdp=4))


class TestExecutorSharding:
    def test_state_shardings_are_applied(self, mesh):
        from paddle_tpu.parallel import plan as plan_lib

        model = _MLP()
        params = model.init(jax.random.PRNGKey(0))
        plan = plan_lib.fsdp_plan(min_size=16)
        specs = plan.params_specs(params, model.sharding_specs(params))

        program = pt.Program(fn=lambda p, x: model(p, x), name="infer",
                             state_shardings=specs)
        exe = pt.Executor(mesh=mesh)
        x = jnp.ones((8, 16))
        _, out = exe.run(program, params, feed={"x": x})
        assert out.shape == (8, 4)

        # the compiled program really placed the params per plan: fc1
        # weight (16, 64) is large enough for the fsdp plan to shard
        compiled = exe._cache[id(program)][1]
        sh = jax.tree_util.tree_leaves(
            compiled.state_shardings,
            is_leaf=lambda s: hasattr(s, "spec"))
        assert any("fsdp" in str(s.spec) for s in sh), \
            [str(s.spec) for s in sh]

        # run again through the cache: placement must persist
        _, out2 = exe.run(program, params, feed={"x": x})
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2))

    def test_mesh_without_shardings_warns(self, mesh):
        model = _MLP()
        params = model.init(jax.random.PRNGKey(0))
        program = pt.Program(fn=lambda p, x: model(p, x), name="naked")
        exe = pt.Executor(mesh=mesh)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            exe.run(program, params, feed={"x": jnp.ones((8, 16))})
        assert any("WITHOUT state_shardings" in str(x.message) for x in w)

    def test_single_device_no_warning(self):
        model = _MLP()
        params = model.init(jax.random.PRNGKey(0))
        program = pt.Program(fn=lambda p, x: model(p, x))
        exe = pt.Executor()
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            exe.run(program, params, feed={"x": jnp.ones((8, 16))})
        assert not [x for x in w if "state_shardings" in str(x.message)]
