"""Tests: book model zoo, GPT, Trainer driver, detection ops."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow  # excluded from the quick CI gate


from paddle_tpu import optimizer as opt
from paddle_tpu.models.book import (LinearRegression, RNNLanguageModel,
                                    SentimentLSTM, SkipGramNS, Word2Vec)
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.ops import detection as det
from paddle_tpu.train import build_train_step, make_train_state
from paddle_tpu.trainer import Trainer


def _fit(model, loss_kwargs_fn, steps=25, lr=1e-2, optimizer=None):
    optimizer = optimizer or opt.Adam(learning_rate=lr)
    state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

    def loss_fn(params, **kw):
        return model.loss(params, **kw)

    step = jax.jit(build_train_step(loss_fn, optimizer))
    losses = []
    for _ in range(steps):
        state, m = step(state, **loss_kwargs_fn())
        losses.append(float(m["loss"]))
    return losses, state, m


class TestBookModels:
    def test_fit_a_line(self):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(32, 13)).astype(np.float32))
        w_true = jnp.asarray(rng.normal(size=13).astype(np.float32))
        y = x @ w_true + 0.5
        losses, _, _ = _fit(LinearRegression(13),
                            lambda: dict(x=x, y=y), steps=200, lr=0.1)
        assert losses[-1] < 0.05

    def test_word2vec_ngram(self):
        model = Word2Vec(vocab_size=50, embed_dim=8, context=4, hidden=16)
        ctx = jax.random.randint(jax.random.PRNGKey(0), (16, 4), 0, 50)
        tgt = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 50)
        losses, _, _ = _fit(model, lambda: dict(context_ids=ctx,
                                                target_ids=tgt), steps=40)
        assert losses[-1] < losses[0]

    def test_skipgram_ns(self):
        model = SkipGramNS(vocab_size=50, embed_dim=8)
        c = jax.random.randint(jax.random.PRNGKey(0), (32,), 0, 50)
        p = jax.random.randint(jax.random.PRNGKey(1), (32,), 0, 50)
        n = jax.random.randint(jax.random.PRNGKey(2), (32, 5), 0, 50)
        losses, _, _ = _fit(model, lambda: dict(center=c, positive=p,
                                                negatives=n), steps=30)
        assert losses[-1] < losses[0]

    def test_sentiment_lstm(self):
        model = SentimentLSTM(vocab_size=40, num_classes=2, embed_dim=8,
                              hidden=16, num_layers=1)
        ids = jax.random.randint(jax.random.PRNGKey(0), (8, 12), 1, 40)
        lengths = jnp.full((8,), 12)
        label = (ids[:, 0] % 2).astype(jnp.int32)  # learnable signal
        losses, _, m = _fit(model, lambda: dict(ids=ids, lengths=lengths,
                                                label=label), steps=50)
        assert losses[-1] < losses[0]
        assert float(m["acc"]) > 0.7

    def test_rnn_lm_ppl(self):
        model = RNNLanguageModel(vocab_size=30, embed_dim=16, hidden=16)
        ids = jax.random.randint(jax.random.PRNGKey(0), (4, 10), 0, 30)
        tgt = jnp.roll(ids, -1, axis=1)
        losses, _, m = _fit(model, lambda: dict(ids=ids, targets=tgt),
                            steps=40)
        assert losses[-1] < losses[0]
        assert float(m["ppl"]) == pytest.approx(np.exp(losses[-1]), rel=1e-3)


class TestGPT:
    def test_lm_learns_and_generates(self):
        cfg = GPTConfig.tiny(attn_impl="xla")
        model = GPT(cfg)
        ids = jax.random.randint(jax.random.PRNGKey(0), (4, 16), 0,
                                 cfg.vocab_size)
        losses, state, _ = _fit(model, lambda: dict(ids=ids), steps=40,
                                lr=3e-3)
        assert losses[-1] < losses[0]
        out = jax.jit(lambda p, x: model.generate(p, x, max_new_tokens=8))(
            state["params"], ids[:2, :4])
        assert out.shape == (2, 12)

    def test_causality(self):
        cfg = GPTConfig.tiny(attn_impl="xla")
        model = GPT(cfg)
        params = model.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (1, 12), 0,
                                 cfg.vocab_size)
        ids2 = ids.at[0, 10].set((ids[0, 10] + 1) % cfg.vocab_size)
        l1 = model(params, ids)
        l2 = model(params, ids2)
        np.testing.assert_allclose(np.asarray(l1[0, :10]),
                                   np.asarray(l2[0, :10]), atol=1e-5)


class TestTrainer:
    def _pieces(self, tmp_path=None):
        model = LinearRegression(4)
        optimizer = opt.SGD(learning_rate=0.1)
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        step = jax.jit(build_train_step(
            lambda p, **kw: model.loss(p, **kw), optimizer))
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(16, 4)).astype(np.float32))
        y = jnp.asarray(x[:, 0] * 2 - 1)
        batches = [dict(x=x, y=y)] * 10
        return step, state, batches

    def test_fit_runs_and_logs(self):
        step, state, batches = self._pieces()
        logs = []
        tr = Trainer(step, state, log_every=5, log_fn=logs.append)
        metrics = tr.fit(batches, epochs=2,
                         make_iter=lambda: iter(list(batches)))
        assert tr.step_count == 20
        assert metrics["loss"] < 1.0
        assert any("step" in l for l in logs)

    def test_checkpoint_resume(self, tmp_path):
        step, state, batches = self._pieces()
        tr = Trainer(step, state, checkpoint_dir=str(tmp_path / "c"),
                     checkpoint_every=5, log_every=0, log_fn=lambda s: None)
        tr.fit(batches, epochs=1)
        assert tr.manager.latest_step() == 10

        # crash + restart: a fresh trainer resumes where the first stopped
        step2, state2, _ = self._pieces()
        tr2 = Trainer(step2, state2, checkpoint_dir=str(tmp_path / "c"),
                      log_every=0, log_fn=lambda s: None)
        resumed = tr2.restore()
        assert resumed == 10
        tr2.fit(batches, epochs=1)
        assert tr2.step_count == 20

    def test_hooks_called(self):
        step, state, batches = self._pieces()
        calls = []
        tr = Trainer(step, state, log_every=0,
                     hooks=[lambda t, n, m: calls.append(n)])
        tr.fit(batches, epochs=1)
        assert calls == list(range(1, 11))


class TestDetectionOps:
    def test_box_iou(self):
        b1 = jnp.array([[0, 0, 2, 2]], jnp.float32)
        b2 = jnp.array([[1, 1, 3, 3], [0, 0, 2, 2]], jnp.float32)
        iou = det.box_iou(b1, b2)
        np.testing.assert_allclose(np.asarray(iou[0]), [1 / 7, 1.0],
                                   atol=1e-6)

    def test_box_code_roundtrip(self):
        anchors = jnp.array([[0, 0, 10, 10], [5, 5, 20, 25]], jnp.float32)
        boxes = jnp.array([[1, 2, 11, 13], [4, 6, 22, 24]], jnp.float32)
        deltas = det.box_encode(boxes, anchors)
        back = det.box_decode(deltas, anchors)
        np.testing.assert_allclose(np.asarray(back), np.asarray(boxes),
                                   atol=1e-4)

    def test_prior_box(self):
        boxes = det.prior_box(2, 2, 32, 32, min_sizes=(8,), max_sizes=(16,),
                              aspect_ratios=(1.0, 2.0))
        # A = 1 (min) + 2 (ar=2 two orientations? no: ar2 adds 1) + 1 (max)
        assert boxes.shape[1] == 4
        assert float(boxes.min()) >= 0.0 and float(boxes.max()) <= 1.0

    def test_nms_suppresses(self):
        boxes = jnp.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                          jnp.float32)
        scores = jnp.array([0.9, 0.8, 0.7])
        idxs, valid = det.nms(boxes, scores, iou_threshold=0.5,
                              max_outputs=3)
        kept = np.asarray(idxs)[np.asarray(valid)]
        assert list(kept) == [0, 2]  # box 1 suppressed by box 0

    def test_nms_score_threshold(self):
        boxes = jnp.array([[0, 0, 1, 1], [5, 5, 6, 6]], jnp.float32)
        scores = jnp.array([0.9, 0.01])
        _, valid = det.nms(boxes, scores, score_threshold=0.5,
                           max_outputs=2)
        assert int(np.asarray(valid).sum()) == 1

    def test_multiclass_nms(self):
        boxes = jnp.array([[0, 0, 10, 10], [20, 20, 30, 30]], jnp.float32)
        scores = jnp.array([[0.9, 0.1], [0.2, 0.8]])
        cls_ids, idxs, valid = det.multiclass_nms(
            boxes, scores, score_threshold=0.5, max_per_class=2)
        kept = [(int(c), int(i)) for c, i, v in
                zip(cls_ids, idxs, np.asarray(valid)) if v]
        assert (0, 0) in kept and (1, 1) in kept

    def test_yolo_box_shapes(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 3 * 7, 4, 4))
        img_size = jnp.array([[416, 416], [320, 640]], jnp.int32)
        boxes, scores = det.yolo_box(x, img_size,
                                     anchors=[(10, 13), (16, 30), (33, 23)],
                                     class_num=2)
        assert boxes.shape == (2, 48, 4)
        assert scores.shape == (2, 48, 2)

    def test_roi_align_constant_field(self):
        feat = jnp.ones((16, 16, 3))
        rois = jnp.array([[2, 2, 10, 10]], jnp.float32)
        out = det.roi_align(feat, rois, output_size=(4, 4))
        assert out.shape == (1, 4, 4, 3)
        np.testing.assert_allclose(np.asarray(out), 1.0, atol=1e-5)
