"""OpTest parity for the eight niche root ops (ops/niche.py) against
brute-force numpy references transcribed from the reference kernels
(sample_logits_op.h, unpool_op.cc, spp_op.h, conv_shift_op.cc,
tree_conv_op.h/tree2col.cc, var_conv_2d_op.cc, modified_huber_loss_op.h,
sequence_topk_avg_pooling_op.h)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import niche as NI
from paddle_tpu.testing import check_grad, check_output


class TestModifiedHuberLoss:
    def test_forward_branches(self):
        x = np.asarray([-3.0, -0.5, 0.2, 2.0, 0.9], np.float32)
        y = np.asarray([1.0, 0.0, 1.0, 1.0, 0.0], np.float32)
        a = x * (2 * y - 1)
        want = np.where(a < -1, -4 * a, np.where(a < 1, (1 - a) ** 2, 0))
        np.testing.assert_allclose(
            np.asarray(NI.modified_huber_loss(jnp.asarray(x),
                                              jnp.asarray(y))), want)

    def test_grad(self):
        rng = np.random.RandomState(0)
        x = rng.randn(8).astype(np.float32) * 2
        # keep away from the |a|=1 kinks where FD is invalid
        x = np.where(np.abs(np.abs(x) - 1.0) < 0.05, x + 0.2, x)
        y = (rng.rand(8) > 0.5).astype(np.float32)
        check_grad(lambda a: NI.modified_huber_loss(a, jnp.asarray(y)), [x])


class TestUnpool:
    def test_roundtrip_with_maxpool_indices(self):
        rng = np.random.RandomState(1)
        x = rng.randn(2, 3, 4, 6).astype(np.float32)
        # brute-force 2x2 max pool with indices (unpool_op's producer)
        pooled = np.zeros((2, 3, 2, 3), np.float32)
        idx = np.zeros((2, 3, 2, 3), np.int32)
        for b in range(2):
            for c in range(3):
                for i in range(2):
                    for j in range(3):
                        win = x[b, c, 2 * i:2 * i + 2, 2 * j:2 * j + 2]
                        k = int(np.argmax(win))
                        pooled[b, c, i, j] = win.flat[k]
                        idx[b, c, i, j] = (2 * i + k // 2) * 6 + (2 * j + k % 2)
        out = np.asarray(NI.unpool(jnp.asarray(pooled), jnp.asarray(idx),
                                   (4, 6)))
        want = np.zeros_like(x)
        for b in range(2):
            for c in range(3):
                for i in range(6):
                    want[b, c].flat[idx[b, c].flat[i]] = pooled[b, c].flat[i]
        np.testing.assert_allclose(out, want)
        # every pooled value lands at its argmax; rest zero
        assert (np.count_nonzero(out) <= 2 * 3 * 6)

    def test_grad_routes_to_indices(self):
        pooled = np.asarray([[[[1.0, 2.0]]]], np.float32)
        idx = np.asarray([[[[0, 3]]]], np.int32)
        g = jax.grad(lambda p: jnp.sum(
            NI.unpool(p, jnp.asarray(idx), (2, 2)) * 2.0))(jnp.asarray(pooled))
        np.testing.assert_allclose(np.asarray(g), [[[[2.0, 2.0]]]])


def _spp_ref(x, pyramid_height, pooling_type):
    n, c, h, w = x.shape
    outs = []
    for p in range(pyramid_height):
        bins = 2 ** p
        kh, kw = math.ceil(h / bins), math.ceil(w / bins)
        ph, pw = (kh * bins - h + 1) // 2, (kw * bins - w + 1) // 2
        lvl = np.zeros((n, c, bins, bins), np.float64)
        for b in range(n):
            for ch in range(c):
                for i in range(bins):
                    for j in range(bins):
                        y0, x0 = i * kh - ph, j * kw - pw
                        ys = slice(max(y0, 0), min(y0 + kh, h))
                        xs = slice(max(x0, 0), min(x0 + kw, w))
                        win = x[b, ch, ys, xs]
                        lvl[b, ch, i, j] = (win.max() if pooling_type == "max"
                                            else win.mean())
        outs.append(lvl.reshape(n, c * bins * bins))
    return np.concatenate(outs, 1)


class TestSpp:
    @pytest.mark.parametrize("ptype", ["max", "avg"])
    def test_matches_bruteforce(self, ptype):
        rng = np.random.RandomState(2)
        # shapes chosen so no pyramid window falls entirely in padding
        # (there the reference's own kernel hits -FLT_MAX / 0-divide)
        x = rng.randn(2, 3, 8, 6).astype(np.float32)
        got = np.asarray(NI.spp(jnp.asarray(x), 3, ptype))
        want = _spp_ref(x, 3, ptype)
        assert got.shape == (2, 3 * (1 + 4 + 16))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_grad(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 2, 4, 4).astype(np.float32)
        check_grad(lambda a: NI.spp(a, 2, "avg"), [x])


class TestConvShift:
    def test_matches_reference_formula(self):
        rng = np.random.RandomState(4)
        x = rng.randn(3, 7).astype(np.float32)
        y = rng.randn(3, 5).astype(np.float32)
        check_output(NI.conv_shift, NI._conv_shift_ref, [x, y])

    def test_grad_both_inputs(self):
        rng = np.random.RandomState(5)
        x = rng.randn(2, 6).astype(np.float32)
        y = rng.randn(2, 3).astype(np.float32)
        check_grad(NI.conv_shift, [x, y], wrt=(0, 1))

    def test_even_filter_rejected(self):
        with pytest.raises(ValueError):
            NI.conv_shift(jnp.zeros((1, 4)), jnp.zeros((1, 4)))

    def test_oversized_filter_rejected(self):
        with pytest.raises(ValueError):
            NI.conv_shift(jnp.zeros((1, 3)), jnp.zeros((1, 5)))


class TestTreeConv:
    def test_single_root_star_tree(self):
        # star: node 1 connected to 2,3,4; features one-hot
        edges = np.asarray([[[1, 2], [1, 3], [1, 4]]], np.int32)
        feats = np.eye(4, dtype=np.float32)[None]              # (1,4,4)
        f, out_size, m = 4, 2, 3
        filt = np.ones((f, 3, out_size, m), np.float32)
        out = np.asarray(NI.tree_conv(jnp.asarray(feats),
                                      jnp.asarray(edges),
                                      jnp.asarray(filt), max_depth=2))
        assert out.shape == (1, 4, out_size, m)
        # root 1's patch covers all nodes; each leaf's patch is itself +
        # (depth-limited) nothing else at max_depth=2... the filter sums
        # eta weights * features, so out[0,0] > out[0,1] elementwise
        assert (out[0, 0] > out[0, 1]).all()

    def test_depth_weights_match_manual(self):
        # chain 1-2, max_depth 2: patch(1) = {1 (d0), 2 (d1)}
        edges = np.asarray([[[1, 2]]], np.int32)
        feats = np.asarray([[[1.0], [10.0]]], np.float32)      # (1,2,1)
        filt = np.zeros((1, 3, 1, 1), np.float32)
        filt[0, 2, 0, 0] = 1.0                                 # eta_t tap
        out = np.asarray(NI.tree_conv(jnp.asarray(feats),
                                      jnp.asarray(edges),
                                      jnp.asarray(filt), max_depth=2))
        # root1: eta_t(d0)=1 on node1, eta_t(d1)=0.5 on node2 -> 1 + 5
        np.testing.assert_allclose(out[0, 0, 0, 0], 6.0, rtol=1e-6)
        # root2 is a leaf: edges are directed parent->child
        # (tree2col.cc construct_tree), so its patch is just itself —
        # 10 * eta_t(d0)=1 -> 10.0, NOT 10.5 (climbing to node 1 would
        # add 1 * 0.5 from the undirected traversal)
        np.testing.assert_allclose(out[0, 1, 0, 0], 10.0, rtol=1e-6)

    def test_leaf_rooted_patch_only_contains_leaf(self):
        # chain 1->2->3: patch(3) must be {3} alone even at max_depth=3
        edges = np.asarray([[1, 2], [2, 3]], np.int32)
        ws = NI._tree_patch_weights(edges, 3, 3)
        assert ws[2, 0].sum() == 0 and ws[2, 1].sum() == 0
        assert ws[2, 2].sum() > 0
        # and node 2's patch is {2, 3} (its descendant), never node 1
        assert ws[1, 0].sum() == 0
        assert ws[1, 1].sum() > 0 and ws[1, 2].sum() > 0

    def test_grad_wrt_features_and_filter(self):
        edges = np.asarray([[[1, 2], [2, 3]]], np.int32)
        rng = np.random.RandomState(6)
        feats = rng.randn(1, 3, 2).astype(np.float32)
        filt = rng.randn(2, 3, 2, 2).astype(np.float32)
        check_grad(lambda nv, fl: NI.tree_conv(nv, jnp.asarray(edges), fl,
                                               max_depth=2),
                   [feats, filt], wrt=(0, 1))


def _var_conv_ref(x, row_lens, col_lens, w, ic, oc, kh, kw, sh, sw):
    bsz, _, hm, wm = x.shape
    oh, ow = (hm - 1) // sh + 1, (wm - 1) // sw + 1
    out = np.zeros((bsz, oc, oh, ow), np.float64)
    kern = w.reshape(oc, ic, kh, kw)
    for b in range(bsz):
        h, wdt = int(row_lens[b]), int(col_lens[b])
        if h == 0 or wdt == 0:
            continue
        th, tw = (h - 1) // sh + 1, (wdt - 1) // sw + 1
        for o in range(oc):
            for y in range(th):
                for xx in range(tw):
                    acc = 0.0
                    for z in range(ic):
                        for ky in range(kh):
                            for kx in range(kw):
                                iy = y * sh + ky - kh // 2
                                ix = xx * sw + kx - kw // 2
                                if 0 <= iy < h and 0 <= ix < wdt:
                                    acc += kern[o, z, ky, kx] * x[b, z, iy, ix]
                    out[b, o, y, xx] = acc
    return out


class TestVarConv2d:
    def test_matches_bruteforce_varlen(self):
        rng = np.random.RandomState(7)
        x = rng.randn(2, 2, 6, 5).astype(np.float32)
        row = np.asarray([6, 3])
        col = np.asarray([5, 2])
        w = rng.randn(3, 2 * 3 * 3).astype(np.float32)
        got = np.asarray(NI.var_conv_2d(
            jnp.asarray(x), jnp.asarray(row), jnp.asarray(col),
            jnp.asarray(w), input_channel=2, output_channel=3,
            kernel_h=3, kernel_w=3, stride_h=2, stride_w=1))
        want = _var_conv_ref(x, row, col, w, 2, 3, 3, 3, 2, 1)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_zero_length_sample(self):
        x = np.ones((1, 1, 4, 4), np.float32)
        out = np.asarray(NI.var_conv_2d(
            jnp.asarray(x), jnp.asarray([0]), jnp.asarray([4]),
            jnp.ones((1, 9), jnp.float32), input_channel=1,
            output_channel=1))
        assert (out == 0).all()

    def test_grad(self):
        rng = np.random.RandomState(8)
        x = rng.randn(1, 1, 4, 4).astype(np.float32)
        w = rng.randn(2, 9).astype(np.float32)
        check_grad(lambda a, b: NI.var_conv_2d(
            a, jnp.asarray([3]), jnp.asarray([4]), b, input_channel=1,
            output_channel=2), [x, w], wrt=(0, 1))


class TestSampleLogits:
    def test_customized_samples_exact(self):
        rng = np.random.RandomState(9)
        logits = rng.randn(2, 10).astype(np.float32)
        labels = np.asarray([[1], [7]])
        cs = np.asarray([[1, 3, 7], [7, 2, 7]])
        cp = np.full((2, 3), 0.25, np.float32)
        s, p, sl, slab = NI.sample_logits(
            jnp.asarray(logits), jnp.asarray(labels), 2,
            customized_samples=jnp.asarray(cs),
            customized_probabilities=jnp.asarray(cp))
        np.testing.assert_array_equal(np.asarray(s), cs)
        np.testing.assert_array_equal(np.asarray(slab), [[0], [0]])
        want = logits[np.arange(2)[:, None], cs] - np.log(0.25)
        # accidental hits: row0 col2 (==label 1? no, 7 != 1) none;
        # row1 cols 1,2: sample 7 == label 7 at col2 (negative part)
        want[1, 2] -= 1e20
        got = np.asarray(sl)
        np.testing.assert_allclose(got[0], want[0], rtol=1e-5)
        np.testing.assert_allclose(got[1, :2], want[1, :2], rtol=1e-5)
        assert got[1, 2] < -1e19

    def test_sampled_distribution_and_q(self):
        logits = jnp.zeros((4, 50))
        labels = jnp.asarray([[0], [1], [2], [3]])
        s, p, sl, _ = NI.sample_logits(
            logits, labels, 16, rng=jax.random.PRNGKey(0),
            remove_accidental_hits=False)
        s = np.asarray(s)
        assert s.shape == (4, 17)
        assert (s >= 0).all() and (s < 50).all()
        # negatives shared across batch — SampleWithProb writes each drawn
        # v into every row (sample_prob.h:78-92) and the CUDA kernel
        # copies row 0's columns to all rows (sample_prob.cu:86)
        assert (s[:, 1:] == s[0, 1:]).all()
        # Q matches the log-uniform closed form * num_samples, every row
        v = s.astype(np.float64)
        q = np.log((v + 2) / (v + 1)) / np.log(51.0) * 16
        np.testing.assert_allclose(np.asarray(p), q, rtol=1e-5)

    def test_log_uniform_skew(self):
        # log-uniform sampling strongly favors small class ids
        logits = jnp.zeros((1, 10000))
        labels = jnp.zeros((1, 1), jnp.int32)
        s, _, _, _ = NI.sample_logits(
            logits, labels, 2000, rng=jax.random.PRNGKey(1))
        neg = np.asarray(s)[0, 1:]
        assert (neg < 100).mean() > 0.3   # P(<100) = log(101)/log(10001) ≈ .5


def _topk_avg_ref(x, row_lens, col_lens, topks):
    b, c, rm, cm = x.shape
    out = np.zeros((b, rm, c, len(topks)), np.float64)
    max_k = max(topks)
    for i in range(b):
        for j in range(c):
            for r in range(int(row_lens[i])):
                row = x[i, j, r, :int(col_lens[i])]
                top = np.sort(row)[::-1]
                sums = np.zeros(max_k)
                for k in range(max_k):
                    sums[k] = (sums[k - 1] if k >= len(top)
                               else (sums[k - 1] if k else 0) + top[k])
                for ki, k in enumerate(topks):
                    out[i, r, j, ki] = sums[k - 1] / k
    return out


class TestSequenceTopkAvgPooling:
    def test_matches_bruteforce(self):
        rng = np.random.RandomState(10)
        x = rng.randn(2, 3, 4, 6).astype(np.float32)
        row = np.asarray([4, 2])
        col = np.asarray([6, 3])
        got = np.asarray(NI.sequence_topk_avg_pooling(
            jnp.asarray(x), jnp.asarray(row), jnp.asarray(col),
            topks=(1, 3, 5)))
        want = _topk_avg_ref(x, row, col, (1, 3, 5))
        assert got.shape == (2, 4, 3, 3)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_saturating_sum_short_rows(self):
        # 2 valid columns, k=4: average of the 2 valid values over 4
        x = jnp.asarray([[[[3.0, 1.0, 99.0, 99.0]]]])
        got = np.asarray(NI.sequence_topk_avg_pooling(
            x, jnp.asarray([1]), jnp.asarray([2]), topks=(4,)))
        np.testing.assert_allclose(got[0, 0, 0, 0], 1.0)   # (3+1)/4

    def test_grad(self):
        rng = np.random.RandomState(11)
        x = rng.randn(1, 2, 2, 4).astype(np.float32)
        check_grad(lambda a: NI.sequence_topk_avg_pooling(
            a, jnp.asarray([2]), jnp.asarray([3]), topks=(2,)), [x])
