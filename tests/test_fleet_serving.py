"""Multi-replica serving fleet (ISSUE 11): prefix-affinity routing vs
round-robin, power-of-two-choices balance bounds, live request
migration byte-parity, concurrent health polling, router→replica
trace-id propagation, burn-rate autoscaling, and the voluntary-drain
exit code."""

import threading

import numpy as np
import jax
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.serving import fleet
from paddle_tpu.serving.paged_cache import prompt_prefix_digests
from paddle_tpu.models.gpt import GPT, GPTConfig

VOCAB = 64


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=64,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_params, tracer=None, **kw):
    model, params = model_params
    kw.setdefault("num_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_tokens_per_slot", 32)
    kw.setdefault("prefill_chunk", 4)
    return serving.ServingEngine(model, params, attn_impl="lax",
                                 registry=obs.MetricsRegistry(),
                                 tracer=tracer, **kw)


def _step_until_mid_decode(router, rep, cap, max_steps=1000):
    """Step the fleet until ``rep`` holds a mid-decode request (some
    tokens generated, more to go) — the deterministic drain window the
    migration tests need regardless of decode_block/cap timing."""
    eng = rep.engine
    for _ in range(max_steps):
        router.step()
        mid = [i for i in eng.scheduler.decode_slots()
               if 0 < len(eng.scheduler.slots[i].generated) < cap]
        if mid:
            return
    raise AssertionError("no mid-decode window reached")


def _fleet(model_params, n, tracer=None, policy="affinity", seed=0,
           autoscaler=None, prefix_fetch=True, **kw):
    tracer = tracer or obs.Tracer(enabled=False)
    reps = [fleet.LocalReplica(_engine(model_params, tracer=tracer, **kw),
                               name=f"r{i}").warmup()
            for i in range(n)]
    router = fleet.FleetRouter(reps, policy=policy,
                               registry=obs.MetricsRegistry(),
                               tracer=tracer, seed=seed,
                               autoscaler=autoscaler,
                               prefix_fetch=prefix_fetch)
    return router, reps


class TestPrefixDigests:
    def test_digests_match_published_index(self, model_params):
        eng = _engine(model_params)
        eng.warmup()
        rng = np.random.default_rng(0)
        prompt = rng.integers(1, VOCAB, 13).astype(np.int32)
        eng.generate_many([prompt], 4, max_steps=10_000)
        want = prompt_prefix_digests(prompt, 4)
        assert len(want) == 3            # 13 tokens, limit 12 -> 3 pages
        held = eng.cache.published_digests()
        assert set(want) <= held, "published index missed prefix pages"

    def test_digest_cap_leaves_one_token(self):
        # a page-aligned prompt never digests its last page: at least
        # one token must prefill on whoever serves it
        p = np.arange(1, 9, dtype=np.int32)      # 8 tokens, ps=4
        assert len(prompt_prefix_digests(p, 4)) == 1

    def test_distinct_prompts_distinct_digests(self):
        a = prompt_prefix_digests(np.arange(1, 10, dtype=np.int32), 4)
        b = prompt_prefix_digests(np.arange(2, 11, dtype=np.int32), 4)
        assert a and b and a[0] != b[0]

    def test_published_digests_memoized_on_index_gen(self, model_params):
        eng = _engine(model_params)
        eng.warmup()
        d0 = eng.cache.published_digests()
        assert eng.cache.published_digests() is d0   # no per-call build
        rng = np.random.default_rng(2)
        eng.generate_many([rng.integers(1, VOCAB, 13).astype(np.int32)],
                          4, max_steps=10_000)
        d1 = eng.cache.published_digests()
        assert d1 is not d0 and len(d1) > len(d0)    # refreshed on change


class TestExternalTraceId:
    def test_submit_adopts_router_trace_id(self, model_params):
        tracer = obs.Tracer(capacity=256)
        eng = _engine(model_params, tracer=tracer)
        eng.warmup()
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 3,
                         trace_id=777)
        assert eng._req_spans[rid].trace_id == 777
        while not eng.scheduler.idle():
            eng.step()
        st = eng.request_stats(rid)
        assert st["trace_id"] == 777.0
        spans = [s for s in tracer.spans() if s.trace_id == 777]
        assert any(s.name == "serving.request" for s in spans)

    def test_trace_id_carried_with_tracing_off(self, model_params):
        eng = _engine(model_params)       # disabled default tracer
        eng.warmup()
        rid = eng.submit(np.arange(1, 6, dtype=np.int32), 3,
                         trace_id=555)
        while not eng.scheduler.idle():
            eng.step()
        assert eng.request_stats(rid)["trace_id"] == 555.0


class TestConcurrentHealth:
    def test_health_poll_during_step_loop(self, model_params):
        """Satellite regression: a router thread hammers ``health()``
        while the engine thread runs ``step()`` — snapshot reads must
        never throw or return torn values."""
        eng = _engine(model_params)
        eng.warmup()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(1, VOCAB, int(n)).astype(np.int32)
                   for n in rng.integers(4, 12, 12)]
        errs = []
        stop = threading.Event()

        def poll():
            try:
                while not stop.is_set():
                    h = eng.health()
                    assert 0.0 <= h["slot_occupancy"] <= 1.0
                    assert h["queue_depth"] >= 0
                    assert 0.0 <= h["page_utilization"] <= 1.0
                    assert h["free_slots"] >= 0
                    assert h["requests_in_flight"] >= 0
            except Exception as e:          # pragma: no cover
                errs.append(e)

        t = threading.Thread(target=poll)
        t.start()
        try:
            eng.generate_many(prompts, 8, max_steps=100_000)
        finally:
            stop.set()
            t.join()
        assert not errs, errs
        h = eng.health()
        assert h["requests_in_flight"] == 0 and h["queue_depth"] == 0

    def test_snapshot_updates_on_submit_and_step(self, model_params):
        eng = _engine(model_params)
        eng.warmup()
        assert eng.health()["queue_depth"] == 0
        eng.submit(np.arange(1, 6, dtype=np.int32), 2)
        assert eng.health()["queue_depth"] == 1
        while not eng.scheduler.idle():
            eng.step()
        assert eng.health()["queue_depth"] == 0
        assert eng.health()["steps"] >= 1


def _shared_prefix_traffic(rng, sys_prompt, n, tail=4):
    return [np.concatenate([sys_prompt,
                            rng.integers(1, VOCAB, tail).astype(np.int32)])
            for _ in range(n)]


class TestRouting:
    def _shared_tokens(self, router):
        return sum(int(r.engine._reg.counter(
            "serving_prefix_shared_tokens_total").value())
            for r in router.replicas)

    def _run_shared_traffic(self, model_params, policy):
        rng = np.random.default_rng(7)
        sysp = rng.integers(1, VOCAB, 13).astype(np.int32)
        # fleet prefix fetch would let round-robin import the pages it
        # missed — disable it to compare the ROUTING policies alone
        router, _ = _fleet(model_params, 2, policy=policy, seed=3,
                           prefix_fetch=False)
        # wave 1 publishes the prefix on ONE replica
        router.submit(_shared_prefix_traffic(rng, sysp, 1)[0], 4)
        router.run_until_idle(max_steps=10_000)
        # wave 2: the affinity signal exists now
        for p in _shared_prefix_traffic(rng, sysp, 8):
            router.submit(p, 4)
        router.run_until_idle(max_steps=10_000)
        return router

    def test_affinity_beats_round_robin_on_shared_prefix(self,
                                                         model_params):
        aff = self._run_shared_traffic(model_params, "affinity")
        rr = self._run_shared_traffic(model_params, "round_robin")
        got_aff = self._shared_tokens(aff)
        got_rr = self._shared_tokens(rr)
        # affinity keeps every wave-2 request on the publisher: all 8
        # share the 3-page prefix; round-robin spreads them, half land
        # on the replica that never saw the prefix (until its own
        # follower publishes — strictly fewer shared tokens)
        assert got_aff > got_rr, (got_aff, got_rr)
        assert aff.routed_affinity_total >= 8

    def test_p2c_imbalance_bounded_random_arrivals(self, model_params):
        router, reps = _fleet(model_params, 4, policy="p2c", seed=11)
        rng = np.random.default_rng(11)
        counts = {r.name: 0 for r in reps}
        for _ in range(64):
            p = rng.integers(1, VOCAB, int(rng.integers(4, 12))
                             ).astype(np.int32)
            frid = router.submit(p, 2)
            rep = router._where[frid][0]
            counts[rep.name] += 1
        vals = np.array(list(counts.values()), float)
        assert vals.min() > 0, counts      # no starved replica
        # power-of-two-choices keeps the spread tight even with a
        # queue-depth-only signal: max within 2x of mean
        assert vals.max() / vals.mean() <= 2.0, counts
        router.run_until_idle(max_steps=100_000)

    def test_round_robin_cycles(self, model_params):
        router, reps = _fleet(model_params, 2, policy="round_robin")
        a = router.submit(np.arange(1, 6, dtype=np.int32), 2)
        b = router.submit(np.arange(1, 6, dtype=np.int32), 2)
        assert router._where[a][0] is not router._where[b][0]
        router.run_until_idle(max_steps=10_000)

    def test_fleet_results_and_stats_by_fleet_rid(self, model_params):
        router, _ = _fleet(model_params, 2)
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, VOCAB, 6).astype(np.int32)
                   for _ in range(6)]
        frids = [router.submit(p, 5) for p in prompts]
        out = router.run_until_idle(max_steps=10_000)
        assert set(out) == set(frids)
        for f in frids:
            st = router.request_stats(f)
            assert st is not None and st["tokens"] == 5.0
            assert st["replica"].startswith("r")


class TestMigration:
    def test_drain_mid_decode_byte_identical(self, model_params):
        """ISSUE acceptance: greedy tokens through a mid-decode drain
        are byte-identical to an unmigrated run."""
        rng = np.random.default_rng(9)
        prompts = [rng.integers(1, VOCAB, int(n)).astype(np.int32)
                   for n in (5, 9, 6, 11)]
        ref_router, _ = _fleet(model_params, 2, seed=1,
                               decode_block=4)
        ref_frids = [ref_router.submit(p, 16) for p in prompts]
        ref_router.run_until_idle(max_steps=10_000)
        ref = [ref_router.result(f) for f in ref_frids]

        router, reps = _fleet(model_params, 2, seed=1,
                              decode_block=4)
        frids = [router.submit(p, 16) for p in prompts]
        _step_until_mid_decode(router, reps[1], 16)
        migrated = router.drain_replica(reps[1])
        assert migrated > 0
        assert len(router.replicas) == 1
        router.run_until_idle(max_steps=10_000)
        got = [router.result(f) for f in frids]
        for want, have in zip(ref, got):
            assert have is not None
            np.testing.assert_array_equal(want, have)
        assert router.migrations_total == migrated

    def test_excess_shard_refused_before_touching_pages(self,
                                                        model_params):
        """A snapshot carrying more shards than its live length
        explains must be refused: the extra shard would index past the
        reserved block-table entries and overwrite the null page."""
        import hashlib
        eng = _engine(model_params)
        eng.warmup()
        eng.submit(np.arange(1, 8, dtype=np.int32), 24)
        for _ in range(2):
            eng.step()
        snap = eng.snapshot_slot(eng.scheduler.active_slots()[0])
        forged = np.zeros_like(snap["shards"][0])
        snap["shards"].append(forged)
        snap["manifest"].append({
            "index": len(snap["manifest"]),
            "sha256": hashlib.sha256(forged.tobytes()).hexdigest(),
            "bytes": forged.nbytes})        # hash-valid, count-invalid
        target = _engine(model_params)
        target.warmup()
        with pytest.raises(serving.SlotMigrationError,
                           match="inconsistent"):
            target.restore_slot(snap)
        assert target.scheduler.active_slots() == []
        target.cache.check_invariants()

    def test_drain_queue_closes_request_bookkeeping(self, model_params):
        """Queued requests popped by a drain must not leak engine-side
        spans/maps: the root span finishes as 'requeued'."""
        tracer = obs.Tracer(capacity=256)
        eng = _engine(model_params, tracer=tracer)
        eng.warmup()
        rep = fleet.LocalReplica(eng, name="dq")
        rids = [eng.submit(np.arange(1, 6, dtype=np.int32), 4)
                for _ in range(3)]          # queued, never stepped
        assert len(eng._req_spans) == 3
        popped = rep.drain_queue()
        assert [t[0] for t in popped] == rids
        assert eng._req_spans == {} and eng._phase_acc == {}
        closed = [s for s in tracer.spans()
                  if s.name == "serving.request"
                  and s.status == "requeued"]
        assert len(closed) == 3

    def test_corrupt_shard_refused(self, model_params):
        eng = _engine(model_params)
        eng.warmup()
        eng.submit(np.arange(1, 8, dtype=np.int32), 24)
        for _ in range(2):
            eng.step()
        snap = eng.snapshot_slot(eng.scheduler.active_slots()[0])
        flat = snap["shards"][0].reshape(-1).copy()
        flat[0] += 1                       # bit-flip one value
        snap["shards"][0] = flat.reshape(snap["shards"][0].shape)
        target = _engine(model_params)
        target.warmup()
        with pytest.raises(serving.SlotMigrationError,
                           match="sha256 mismatch"):
            target.restore_slot(snap)
        # target untouched: nothing reserved, no slot installed
        assert target.scheduler.active_slots() == []
        target.cache.check_invariants()

    def test_drain_abort_restores_everything(self, model_params):
        """No peer capacity: the drain aborts, every snapshot goes back
        into the source, and every request still completes."""
        router, reps = _fleet(model_params, 2, num_slots=2, seed=2,
                              decode_block=4)
        rng = np.random.default_rng(3)
        # saturate BOTH replicas' slots so nothing can migrate
        frids = [router.submit(rng.integers(1, VOCAB, 5).astype(np.int32),
                               16) for _ in range(4)]
        _step_until_mid_decode(router, reps[1], 16)
        with pytest.raises(serving.SlotMigrationError, match="aborted"):
            router.drain_replica(reps[1])
        assert len(router.replicas) == 2
        assert not reps[1].draining
        out = router.run_until_idle(max_steps=10_000)
        assert set(out) == set(frids)

    def test_migration_trace_continuity(self, model_params):
        tracer = obs.Tracer(capacity=2048)
        router, reps = _fleet(model_params, 2, tracer=tracer, seed=4,
                              decode_block=4)
        rng = np.random.default_rng(4)
        frids = [router.submit(rng.integers(1, VOCAB, 6).astype(np.int32),
                               16) for _ in range(4)]
        _step_until_mid_decode(router, reps[1], 16)
        router.drain_replica(reps[1])
        router.run_until_idle(max_steps=10_000)
        spans = tracer.spans()
        req_tids = {s.trace_id for s in spans
                    if s.name == "serving.request"}
        route_tids = {s.trace_id for s in spans
                      if s.name == "router.route"}
        mig = [s for s in spans if s.name == "router.migrate"]
        assert mig, "no migrate spans"
        for s in mig:
            # the migrate span AND the restored request continuation
            # live on the original router-minted trace
            assert s.trace_id in req_tids
            assert s.trace_id in route_tids
            assert s.attrs["src"] == "r1"
            assert s.attrs["dst"] == "r0"
        migrated_in = [s for s in spans if s.name == "serving.request"
                       and s.attrs.get("migrated")]
        assert migrated_in
        for s in migrated_in:
            assert s.trace_id in route_tids

    def test_migrated_stats_and_counters(self, model_params):
        router, reps = _fleet(model_params, 2, seed=6, decode_block=4)
        rng = np.random.default_rng(6)
        frids = [router.submit(rng.integers(1, VOCAB, 6).astype(np.int32),
                               16) for _ in range(4)]
        _step_until_mid_decode(router, reps[1], 16)
        n = router.drain_replica(reps[1])
        assert reps[0].engine.migrated_in_total == n
        assert reps[1].engine.migrated_out_total == n
        router.run_until_idle(max_steps=10_000)
        for f in frids:
            assert router.result(f) is not None


class _QueueFake(fleet.ReplicaHandle):
    """Interface-level fake: accepts (or sheds) submissions, hands its
    queue back on drain — lets the requeue paths be tested without
    engines."""

    def __init__(self, name, shed=False):
        self.name = name
        self.shed = shed
        self.accepted = []
        self._rids = iter(range(1, 1000))

    def page_size(self):
        return 4

    def prefix_digests(self):
        return frozenset()

    def health(self):
        return {"queue_depth": len(self.accepted),
                "requests_in_flight": 0, "slot_occupancy": 0.0,
                "page_utilization": 0.0, "free_slots": 4}

    def idle(self):
        return True

    def step(self):
        return {}

    def warmup(self):
        return self

    def submit(self, prompt, max_new_tokens, eos_id=None, *,
               lane="default", ttft_deadline_s=None, trace_id=None):
        if self.shed:
            from paddle_tpu.serving.scheduler import Reject
            raise serving.LoadShedError(
                Reject("queue_full", lane, 99, 1.0, 0.1))
        rid = next(self._rids)
        self.accepted.append((rid, prompt, max_new_tokens, eos_id,
                              lane, ttft_deadline_s))
        return rid

    def drain_queue(self):
        out, self.accepted = self.accepted, []
        return out

    def snapshot_inflight(self):
        return []

    def close(self):
        pass


class TestDrainRequeue:
    def test_requeue_retries_every_peer_before_shedding(self):
        victim = _QueueFake("victim")
        shedder = _QueueFake("shedder", shed=True)
        acceptor = _QueueFake("acceptor")
        # round_robin puts the first submit on the victim; the shedder
        # (load 0) is the first re-route target, the acceptor must
        # still get the request
        router = fleet.FleetRouter([victim, shedder, acceptor],
                                   policy="round_robin",
                                   registry=obs.MetricsRegistry())
        frid = router.submit(np.arange(1, 6, dtype=np.int32), 4)
        assert router._where[frid][0] is victim
        router._rr = 0      # pin the re-route's first pick to the shedder
        router.drain_replica(victim)
        assert len(acceptor.accepted) == 1, "retry never reached peer"
        assert router._where[frid][0] is acceptor

    def test_requeue_shed_everywhere_cleans_fleet_maps(self):
        victim = _QueueFake("victim")
        s1 = _QueueFake("s1", shed=True)
        s2 = _QueueFake("s2", shed=True)
        router = fleet.FleetRouter([victim, s1, s2],
                                   policy="round_robin",
                                   registry=obs.MetricsRegistry())
        frid = router.submit(np.arange(1, 6, dtype=np.int32), 4)
        router.drain_replica(victim)
        assert frid not in router._where, "stale mapping leaked"
        assert frid not in router._trace


class TestThreadedReplica:
    def test_background_loop_serves_and_health_polls(self, model_params):
        rep = fleet.LocalReplica(_engine(model_params), name="bg")
        rep.warmup()
        rep.start()
        try:
            rng = np.random.default_rng(8)
            rids = [rep.submit(rng.integers(1, VOCAB, 6).astype(np.int32),
                               4) for _ in range(6)]
            import time
            deadline = time.monotonic() + 60.0
            while not rep.idle():
                assert time.monotonic() < deadline, "replica stuck"
                h = rep.health()            # poll while it steps
                assert 0.0 <= h["slot_occupancy"] <= 1.0
            for r in rids:
                got = rep.result(r)
                assert got is not None and len(got) == 4
        finally:
            rep.stop()
        assert not rep.running()


class _FakeReplica(fleet.ReplicaHandle):
    def __init__(self, name, burn=0.0):
        self.name = name
        self.burn = burn
        self.closed = False
        self.warmed = False
        self.inflight = 0

    def page_size(self):
        return 4

    def prefix_digests(self):
        return frozenset()

    def health(self):
        return {"queue_depth": 0, "requests_in_flight": self.inflight,
                "slot_occupancy": 0.0, "page_utilization": 0.0,
                "free_slots": 4,
                "slo": {"burn_fast": self.burn,
                        "burn_slow": self.burn}}

    def idle(self):
        return True

    def step(self):
        return {}

    def warmup(self):
        self.warmed = True
        return self

    def drain_queue(self):
        return []

    def snapshot_inflight(self):
        return []

    def close(self):
        self.closed = True


class TestAutoscaler:
    def _scaler(self, spawn, **kw):
        kw.setdefault("min_replicas", 1)
        kw.setdefault("max_replicas", 3)
        kw.setdefault("scale_out_burn", 6.0)
        kw.setdefault("sustain_s", 2.0)
        kw.setdefault("idle_s", 5.0)
        kw.setdefault("cooldown_s", 3.0)
        clock = [0.0]
        a = fleet.FleetAutoscaler(spawn, registry=obs.MetricsRegistry(),
                                  clock=lambda: clock[0], **kw)
        return a, clock

    def test_sustained_burn_scales_out_prewarmed(self):
        spawned = []

        def spawn(i):
            r = _FakeReplica(f"auto{i}")
            spawned.append(r)
            return r

        a, clock = self._scaler(spawn)
        base = _FakeReplica("base", burn=20.0)
        router = fleet.FleetRouter([base], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        assert a.tick() is None            # hot but not sustained yet
        clock[0] = 1.0
        assert a.tick() is None
        clock[0] = 2.5
        assert a.tick() == "scale_out"
        assert spawned and spawned[0].warmed, \
            "replica attached before warmup"
        assert len(router.replicas) == 2
        clock[0] = 4.0                     # cooldown holds
        assert a.tick() is None

    def test_spike_alone_never_scales(self):
        a, clock = self._scaler(lambda i: _FakeReplica(f"a{i}"))
        base = _FakeReplica("base")
        fleet.FleetRouter([base], policy="p2c",
                          registry=obs.MetricsRegistry(), autoscaler=a)
        base.burn = 20.0
        assert a.tick() is None
        base.burn = 0.0                    # pressure gone before sustain
        clock[0] = 2.5
        assert a.tick() is None
        assert a.scale_outs == 0

    def test_sustained_idle_scales_in_via_drain(self):
        a, clock = self._scaler(lambda i: _FakeReplica(f"a{i}"))
        r0, r1 = _FakeReplica("r0"), _FakeReplica("r1")
        router = fleet.FleetRouter([r0, r1], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        assert a.tick() is None            # idle starts counting
        clock[0] = 5.5
        assert a.tick() == "scale_in"
        assert len(router.replicas) == 1
        assert r0.closed or r1.closed
        assert a.events[-1]["action"] == "scale_in"

    def test_never_below_min_replicas(self):
        a, clock = self._scaler(lambda i: _FakeReplica(f"a{i}"))
        base = _FakeReplica("base")
        router = fleet.FleetRouter([base], policy="p2c",
                                   registry=obs.MetricsRegistry(),
                                   autoscaler=a)
        clock[0] = 100.0
        assert a.tick() is None
        assert len(router.replicas) == 1

    def test_scale_in_abort_backs_off_instead_of_crashing(self,
                                                          model_params):
        """Both replicas saturated: the autoscaler's drain attempt
        aborts (no peer capacity), which must cool down — NOT raise
        out of router.step() — and every request still completes."""
        clock = [0.0]
        a = fleet.FleetAutoscaler(
            lambda i: (_ for _ in ()).throw(AssertionError()),
            min_replicas=1, max_replicas=2, idle_occupancy=1.0,
            idle_s=0.0, cooldown_s=1000.0,
            registry=obs.MetricsRegistry(), clock=lambda: clock[0])
        router, reps = _fleet(model_params, 2, num_slots=2, seed=20,
                              decode_block=4, autoscaler=a)
        rng = np.random.default_rng(20)
        frids = [router.submit(rng.integers(1, VOCAB, 5).astype(np.int32),
                               16) for _ in range(4)]
        out = router.run_until_idle(max_steps=10_000)   # must not raise
        assert set(out) == set(frids)
        assert a.scale_ins == 0
        aborted = [e for e in a.events
                   if e["action"] == "scale_in_aborted"]
        assert aborted, "drain abort never recorded"
        assert len(router.replicas) == 2

    def test_real_fleet_idle_scale_in_migrates(self, model_params):
        """Integration: a real 2-replica fleet with in-flight work on
        the drain victim — scale-in live-migrates, requests finish."""
        model, params = model_params

        def spawn(i):                      # pragma: no cover
            raise AssertionError("no scale-out expected")

        clock = [0.0]
        a = fleet.FleetAutoscaler(spawn, min_replicas=1, max_replicas=2,
                                  idle_occupancy=1.0, idle_s=0.0,
                                  cooldown_s=0.0,
                                  registry=obs.MetricsRegistry(),
                                  clock=lambda: clock[0])
        router, reps = _fleet(model_params, 2, seed=12, autoscaler=a)
        rng = np.random.default_rng(12)
        frids = [router.submit(rng.integers(1, VOCAB, 5).astype(np.int32),
                               12) for _ in range(2)]
        # idle_occupancy=1.0 makes "idle" true despite in-flight work,
        # so the first tick (inside router.step) drains immediately —
        # exercising migration THROUGH the autoscaler path
        out = router.run_until_idle(max_steps=10_000)
        assert a.scale_ins == 1
        assert len(router.replicas) == 1
        assert set(out) == set(frids)


class TestDrainExitCode:
    class _Proc:
        def __init__(self, rc):
            self.returncode = None
            self._rc = rc
            self.killed = False

        def poll(self):
            self.returncode = self._rc
            return self._rc

        def kill(self):                    # pragma: no cover
            self.killed = True

        def wait(self):
            return self.returncode

    def test_drained_rank_retires_without_budget(self):
        from paddle_tpu import fleet as proc_fleet
        from paddle_tpu.resilience import EXIT_DRAINED
        rcs = {0: 0, 1: EXIT_DRAINED}
        spawned = []

        def spawn(rank, attempt):
            p = self._Proc(rcs[rank])
            spawned.append((rank, attempt))
            return p

        coord = proc_fleet.ElasticCoordinator(
            spawn, 2, max_restarts=1, poll_s=0.01, gang=False,
            log_fn=lambda *a: None)
        assert coord.run(timeout_s=10.0)
        assert coord.drained_exits == 1
        assert coord.restarts == 0
        assert coord.rank_restarts == [0, 0]
        assert coord.preemption_restarts == 0
        assert len(spawned) == 2           # nobody respawned

    def test_gang_restart_never_resurrects_drained_rank(self):
        """A gang respawn after a peer's crash must leave a drained
        rank retired — its work migrated away; respawning it would
        re-grow the fleet the autoscaler just shrank."""
        from paddle_tpu import fleet as proc_fleet
        from paddle_tpu.resilience import EXIT_DRAINED
        spawns = []

        def spawn(rank, attempt):
            spawns.append((rank, attempt))
            if rank == 0:
                return self._Proc(EXIT_DRAINED)
            # rank 1 crashes once, then succeeds after the gang restart
            return self._Proc(7 if attempt == 0 else 0)

        coord = proc_fleet.ElasticCoordinator(
            spawn, 2, max_restarts=1, poll_s=0.01, gang=True,
            log_fn=lambda *a: None)
        assert coord.run(timeout_s=10.0)
        assert coord.drained_exits == 1
        assert coord.restarts == 1
        assert spawns.count((0, 0)) == 1
        assert all(r != 0 for (r, a) in spawns if a > 0), \
            f"drained rank respawned: {spawns}"

    def test_gang_failure_same_window_still_retires_drained_rank(self):
        """Rank A crashes and rank B drains in the SAME poll window:
        the exit scan must record B's retirement before the gang
        respawn, or B gets resurrected."""
        from paddle_tpu import fleet as proc_fleet
        from paddle_tpu.resilience import EXIT_DRAINED
        spawns = []

        def spawn(rank, attempt):
            spawns.append((rank, attempt))
            if rank == 1:
                return self._Proc(EXIT_DRAINED)
            return self._Proc(7 if attempt == 0 else 0)

        coord = proc_fleet.ElasticCoordinator(
            spawn, 2, max_restarts=1, poll_s=0.01, gang=True,
            log_fn=lambda *a: None)
        assert coord.run(timeout_s=10.0)
        assert coord.drained_exits == 1
        assert all(r != 1 for (r, a) in spawns if a > 0), \
            f"drained rank respawned: {spawns}"

    def test_crash_still_consumes_budget(self):
        from paddle_tpu import fleet as proc_fleet
        calls = {"n": 0}

        def spawn(rank, attempt):
            calls["n"] += 1
            return self._Proc(7)           # always crashes

        coord = proc_fleet.ElasticCoordinator(
            spawn, 1, max_restarts=1, poll_s=0.01, gang=False,
            log_fn=lambda *a: None)
        assert not coord.run(timeout_s=10.0)
        assert coord.rank_restarts == [1]
        assert coord.drained_exits == 0


class TestFleetMonitorAndFacade:
    def test_monitor_aggregates_gauges(self, model_params):
        reg = obs.MetricsRegistry()
        tracer = obs.Tracer(enabled=False)
        reps = [fleet.LocalReplica(
            _engine(model_params, tracer=tracer, ttft_budget_s=4.0),
            name=f"m{i}").warmup() for i in range(2)]
        router = fleet.FleetRouter(reps, registry=reg, tracer=tracer)
        mon = fleet.FleetMonitor(router, registry=reg)
        rng = np.random.default_rng(13)
        router.submit(rng.integers(1, VOCAB, 6).astype(np.int32), 4)
        mon.collect()
        assert reg.gauge("fleet_replicas").value() == 2
        assert reg.gauge("fleet_queue_depth").value() >= 0
        assert reg.gauge("fleet_replica_queue_depth").value(
            replica="m0") >= 0
        router.run_until_idle(max_steps=10_000)
        h = mon.collect()
        assert h["requests_in_flight"] == 0
        # burn gauges exist because the engines armed SLO monitors
        assert reg.gauge("fleet_burn_rate_max").value() >= 0.0

    def test_make_serving_fleet_facade(self, model_params):
        from paddle_tpu import inference
        model, params = model_params
        router = inference.make_serving_fleet(
            model, params, num_replicas=2, num_slots=2, page_size=4,
            max_tokens_per_slot=32, prefill_chunk=4,
            registry=obs.MetricsRegistry())
        rng = np.random.default_rng(14)
        frids = [router.submit(rng.integers(1, VOCAB, 6).astype(np.int32),
                               4) for _ in range(4)]
        out = router.run_until_idle(max_steps=10_000)
        assert set(out) == set(frids)
        for rep in router.replicas:
            assert rep.engine.warmed_signatures  # facade pre-warmed

    def test_fleet_zero_steady_state_recompiles(self, model_params):
        router, _ = _fleet(model_params, 2, seed=15)
        det = obs.RecompileDetector("fleet_test", warmup=0,
                                    registry=obs.MetricsRegistry())
        rng = np.random.default_rng(15)
        for p in [rng.integers(1, VOCAB, int(n)).astype(np.int32)
                  for n in (5, 9, 6, 11, 7, 8)]:
            router.submit(p, 6)
        router.run_until_idle(max_steps=10_000)
        det.check()
        assert det.recompiles == 0, \
            "steady-state fleet traffic recompiled"


class TestWarmupCoverageWithMigration:
    def test_page_io_in_plan_and_reachable(self, model_params):
        eng = _engine(model_params)
        plan = set(eng.warmup_plan())
        assert ("page_read",) in plan and ("page_write",) in plan
        assert set(eng.reachable_signatures()) == plan

    def test_bucket_coverage_still_clean(self, model_params):
        from paddle_tpu import analysis
        eng = _engine(model_params)
        assert analysis.serving_bucket_coverage(eng) == []
