"""Second op-library long-tail batch: comparisons/logicals, creation ops,
loss tail (dice/bpr/npair/center/nce/hsigmoid/sampled-softmax), 3-D
conv/pool, resize aliases, sequence/array tail, detection composites,
CTC greedy decode, in-graph edit distance. OpTest-style numpy parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow  # excluded from the quick CI gate


from paddle_tpu.ops import control_flow as CF
from paddle_tpu.ops import crf as CRF
from paddle_tpu.ops import detection as D
from paddle_tpu.ops import elementwise as E
from paddle_tpu.ops import nn as N
from paddle_tpu.ops import sequence as S
from paddle_tpu.ops import tensor as T


class TestComparisons:
    def test_all_comparisons_match_numpy(self):
        rng = np.random.RandomState(0)
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.randn(4, 5).astype(np.float32)
        for ours, ref in [(E.equal, np.equal), (E.not_equal, np.not_equal),
                          (E.less_than, np.less),
                          (E.less_equal, np.less_equal),
                          (E.greater_than, np.greater),
                          (E.greater_equal, np.greater_equal)]:
            np.testing.assert_array_equal(
                np.asarray(ours(jnp.asarray(x), jnp.asarray(y))),
                ref(x, y))
        a = x > 0
        b = y > 0
        np.testing.assert_array_equal(
            np.asarray(E.logical_and(jnp.asarray(a), jnp.asarray(b))),
            a & b)
        np.testing.assert_array_equal(
            np.asarray(E.logical_xor(jnp.asarray(a), jnp.asarray(b))),
            a ^ b)
        np.testing.assert_array_equal(
            np.asarray(E.logical_not(jnp.asarray(a))), ~a)


class TestTensorTail:
    def test_creation_and_queries(self):
        assert T.ones((2, 3)).shape == (2, 3)
        assert float(T.zeros((2,)).sum()) == 0.0
        x = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
        np.testing.assert_allclose(np.asarray(T.scale(x, 2.0, 1.0)),
                                   np.arange(6).reshape(2, 3) * 2 + 1)
        assert int(T.rank(x)) == 2 and int(T.size(x)) == 6
        np.testing.assert_allclose(
            np.asarray(T.sum_op([x, x, x])), 3 * np.asarray(x))
        f = T.fill_constant_batch_size_like(x, [7, 4], 5.0)
        assert f.shape == (2, 4) and float(f[0, 0]) == 5.0
        np.testing.assert_allclose(np.asarray(T.reverse(x, 1)),
                                   np.asarray(x)[:, ::-1])
        assert not bool(T.is_empty(x))
        assert not bool(T.has_nan(x)) and not bool(T.has_inf(x))
        assert bool(T.has_nan(jnp.asarray([np.nan])))

    def test_scatter_nd_and_unique(self):
        idx = jnp.asarray([[0], [2], [0]])
        upd = jnp.asarray([1.0, 2.0, 3.0])
        out = np.asarray(T.scatter_nd(idx, upd, (4,)))
        np.testing.assert_allclose(out, [4.0, 0.0, 2.0, 0.0])
        u, inv, cnt = T.unique_with_counts(jnp.asarray([3, 1, 3, 2]))
        assert set(np.asarray(u).tolist()) >= {1, 2, 3}
        np.testing.assert_array_equal(
            np.asarray(u)[np.asarray(inv)], [3, 1, 3, 2])

    def test_hash_stable_and_spread(self):
        ids = jnp.arange(1000, dtype=jnp.int64)
        h1 = np.asarray(T.hash_op(ids, mod_by=997))
        h2 = np.asarray(T.hash_op(ids, mod_by=997))
        np.testing.assert_array_equal(h1, h2)
        assert len(np.unique(h1)) > 500        # spreads
        h3 = np.asarray(T.hash_op(ids, mod_by=997, num_hash=3))
        assert h3.shape == (1000, 3)

    def test_pad_constant_like_and_random(self):
        ref = jnp.zeros((3, 4))
        x = jnp.ones((2, 2))
        out = np.asarray(T.pad_constant_like(ref, x, -1.0))
        assert out.shape == (3, 4)
        assert out[2, 3] == -1.0 and out[0, 0] == 1.0
        key = jax.random.PRNGKey(0)
        g = T.gaussian_random_batch_size_like(ref, [9, 5], key)
        assert g.shape == (3, 5)
        u = T.uniform_random_batch_size_like(ref, [9, 5], key, 0.0, 1.0)
        assert float(u.min()) >= 0.0
        s = T.sampling_id(jnp.asarray([[0.0, 1.0, 0.0]]), key)
        assert int(s[0]) == 1
        crop = T.random_crop(jnp.ones((2, 8, 8, 3)), (4, 4), key)
        assert crop.shape == (2, 4, 4, 3)


class TestLossTail:
    def test_mse_dice(self):
        x = jnp.asarray([[0.9, 0.1], [0.2, 0.8]])
        lab = jnp.asarray([0, 1])
        assert float(N.mse_loss(jnp.ones((3,)), jnp.zeros((3,)))) == 1.0
        d = float(N.dice_loss(x, lab))
        d_bad = float(N.dice_loss(x, jnp.asarray([1, 0])))
        assert d < d_bad

    def test_bpr_and_npair(self):
        scores = jnp.asarray([[5.0, 0.0, 0.0], [0.0, 5.0, 0.0]])
        good = float(N.bpr_loss(scores, jnp.asarray([0, 1])))
        bad = float(N.bpr_loss(scores, jnp.asarray([1, 0])))
        assert good < bad
        anchor = jnp.asarray([[1.0, 0.0], [0.0, 1.0]])
        lab = jnp.asarray([0, 1])
        ln = float(N.npair_loss(anchor, anchor, lab))
        assert np.isfinite(ln)

    def test_center_loss_moves_centers(self):
        feats = jnp.asarray([[1.0, 1.0], [3.0, 3.0]])
        labels = jnp.asarray([0, 0])
        centers = jnp.zeros((2, 2))
        loss, new_c = N.center_loss(feats, labels, centers, alpha=0.5)
        assert loss.shape == (2,)
        np.testing.assert_allclose(np.asarray(new_c)[0], [1.0, 1.0])
        np.testing.assert_allclose(np.asarray(new_c)[1], [0.0, 0.0])

    def test_hsigmoid_and_nce_descend(self):
        rng = np.random.RandomState(0)
        n, d, c = 16, 8, 10
        x = jnp.asarray(rng.randn(n, d).astype(np.float32))
        labels = jnp.asarray(rng.randint(0, c, (n,)))
        w = jnp.asarray(rng.randn(c - 1, d).astype(np.float32) * 0.1)
        b = jnp.zeros((c - 1,))
        loss_fn = lambda w_, b_: N.hsigmoid(x, w_, b_, labels,
                                            num_classes=c)
        l0 = float(loss_fn(w, b))
        for _ in range(20):
            gw, gb = jax.grad(loss_fn, argnums=(0, 1))(w, b)
            w, b = w - 0.5 * gw, b - 0.5 * gb
        assert float(loss_fn(w, b)) < l0 * 0.8

        wn = jnp.asarray(rng.randn(c, d).astype(np.float32) * 0.1)
        bn = jnp.zeros((c,))
        key = jax.random.PRNGKey(0)
        nce_fn = lambda w_: N.nce(x, w_, bn, labels, key, num_neg=4,
                                  num_classes=c)
        n0 = float(nce_fn(wn))
        for _ in range(10):
            wn = wn - 0.3 * jax.grad(nce_fn)(wn)
        assert float(nce_fn(wn)) < n0

    def test_sampled_softmax(self):
        rng = np.random.RandomState(1)
        n, d, c = 4, 8, 100
        emb = jnp.asarray(rng.randn(n, d).astype(np.float32))
        table = jnp.asarray(rng.randn(c, d).astype(np.float32))
        labels = jnp.asarray([3, 7, 11, 13])
        loss = N.sampled_softmax_with_cross_entropy(
            lambda ids: emb @ table[ids].T, labels,
            jax.random.PRNGKey(0), num_samples=20, num_classes=c)
        assert np.isfinite(float(loss))

    def test_teacher_student(self):
        x = jnp.asarray([0.0, 2.0, -2.0])
        z = jax.nn.sigmoid(x)
        near = float(N.teacher_student_sigmoid_loss(x, z))
        far = float(N.teacher_student_sigmoid_loss(x, 1.0 - z))
        assert near < far


class TestNNTail:
    def test_data_norm(self):
        x = jnp.asarray([[1.0], [3.0]])
        out, n, s, sq = N.data_norm(x, 2.0, jnp.asarray([4.0]),
                                    jnp.asarray([10.0]))
        # mean=2, var=10/2-4=1 -> normalized = [-1, 1]
        np.testing.assert_allclose(np.asarray(out)[:, 0], [-1.0, 1.0],
                                   rtol=1e-3)
        assert float(n) == 4.0 and float(s[0]) == 8.0

    def test_spectral_norm_unit_sigma(self):
        rng = np.random.RandomState(0)
        w = jnp.asarray(rng.randn(6, 4).astype(np.float32))
        u = jnp.ones((6,)) / np.sqrt(6)
        wn, u = N.spectral_norm(w, u, power_iters=20)
        sigma = np.linalg.svd(np.asarray(wn), compute_uv=False)[0]
        np.testing.assert_allclose(sigma, 1.0, rtol=1e-3)

    def test_add_position_encoding(self):
        x = jnp.zeros((1, 4, 8))
        out = np.asarray(N.add_position_encoding(x))
        assert out.shape == (1, 4, 8)
        # position 0: sin(0)=0, cos(0)=1
        np.testing.assert_allclose(out[0, 0, :4], 0.0, atol=1e-6)
        np.testing.assert_allclose(out[0, 0, 4:], 1.0, atol=1e-6)

    def test_mean_iou_perfect_and_half(self):
        p = jnp.asarray([0, 1, 1, 0])
        assert float(N.mean_iou(p, p, 2)) == pytest.approx(1.0)
        half = float(N.mean_iou(p, jnp.asarray([0, 1, 0, 1]), 2))
        assert 0.0 < half < 1.0

    def test_row_conv_lookahead_only(self):
        x = jnp.asarray(np.eye(4, dtype=np.float32)[None, :, :])
        w = jnp.asarray([[1.0] * 4, [0.5] * 4])
        out = np.asarray(N.row_conv(x, w))
        # out[t] = x[t] + 0.5 x[t+1]: strictly future context
        np.testing.assert_allclose(out[0, 0], [1.0, 0.5, 0.0, 0.0])
        np.testing.assert_allclose(out[0, 3], [0.0, 0.0, 0.0, 1.0])

    def test_im2sequence(self):
        x = jnp.arange(16, dtype=jnp.float32).reshape(1, 4, 4, 1)
        seq = np.asarray(N.im2sequence(x, 2, stride=2))
        assert seq.shape == (1, 4, 4)
        np.testing.assert_allclose(seq[0, 0], [0, 1, 4, 5])

    def test_conv3d_matches_manual(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1, 4, 4, 4, 2).astype(np.float32))
        w = jnp.asarray(rng.randn(2, 2, 2, 2, 3).astype(np.float32))
        out = N.conv3d(x, w)
        assert out.shape == (1, 3, 3, 3, 3)
        manual = (np.asarray(x)[0, :2, :2, :2, :, None]
                  * np.asarray(w)).sum((0, 1, 2, 3))
        np.testing.assert_allclose(np.asarray(out)[0, 0, 0, 0], manual,
                                   rtol=1e-4)

    def test_conv3d_transpose_shape_roundtrip(self):
        x = jnp.ones((1, 3, 3, 3, 2))
        w = jnp.ones((2, 2, 2, 2, 4))
        out = N.conv3d_transpose(x, w, stride=2)
        assert out.shape[1] == 2 * 3 + (2 - 2)  # (D-1)*s + k = 6

    def test_pool3d_and_adaptive(self):
        x = jnp.arange(8, dtype=jnp.float32).reshape(1, 2, 2, 2, 1)
        mx = float(N.pool3d(x, 2)[0, 0, 0, 0, 0])
        assert mx == 7.0
        avg = float(N.pool3d(x, 2, pool_type="avg")[0, 0, 0, 0, 0])
        assert avg == 3.5
        ad = N.adaptive_pool3d(jnp.ones((1, 4, 4, 4, 2)), 2)
        assert ad.shape == (1, 2, 2, 2, 2)
        with pytest.raises(NotImplementedError):
            N.adaptive_pool3d(jnp.ones((1, 5, 4, 4, 2)), 2)

    def test_resize_aliases(self):
        x = jnp.ones((1, 4, 6, 3))
        assert N.resize_bilinear(x, (8, 12)).shape == (1, 8, 12, 3)
        assert N.resize_nearest(x, 2).shape == (1, 2, 2, 3)
        short = N.image_resize_short(x, 2)
        assert short.shape == (1, 2, 3, 3)
        v = jnp.ones((1, 2, 4, 4, 1))
        assert N.resize_trilinear(v, (4, 8, 8)).shape == (1, 4, 8, 8, 1)


class TestSequenceTail:
    def test_first_last_step(self):
        x = jnp.arange(12, dtype=jnp.float32).reshape(2, 3, 2)
        lengths = jnp.asarray([3, 2])
        np.testing.assert_allclose(
            np.asarray(S.sequence_first_step(x, lengths)),
            np.asarray(x)[:, 0])
        last = np.asarray(S.sequence_last_step(x, lengths))
        np.testing.assert_allclose(last[0], np.asarray(x)[0, 2])
        np.testing.assert_allclose(last[1], np.asarray(x)[1, 1])

    def test_expand_as_and_reshape(self):
        x = jnp.asarray([[1.0], [2.0]])
        out = np.asarray(S.sequence_expand_as(x, jnp.asarray([3, 1]), 4))
        np.testing.assert_allclose(out[0, :, 0], [1, 1, 1, 0])
        np.testing.assert_allclose(out[1, :, 0], [2, 0, 0, 0])
        y = jnp.arange(12, dtype=jnp.float32).reshape(1, 3, 4)
        r, ln = S.sequence_reshape(y, jnp.asarray([2]), 2)
        assert r.shape == (1, 6, 2)
        assert int(ln[0]) == 4

    def test_sequence_scatter(self):
        x = jnp.zeros((2, 5))
        idx = jnp.asarray([[0, 2], [1, 4]])
        upd = jnp.asarray([[1.0, 2.0], [3.0, 4.0]])
        out = np.asarray(S.sequence_scatter(x, idx, upd,
                                            jnp.asarray([2, 1])))
        np.testing.assert_allclose(out[0], [1, 0, 2, 0, 0])
        np.testing.assert_allclose(out[1], [0, 3, 0, 0, 0])  # 2nd ignored


class TestArrays:
    def test_array_layer_roundtrip(self):
        arr = CF.create_array(3, jnp.zeros((2,)))
        arr = CF.array_write(arr, 0, jnp.asarray([1.0, 2.0]))
        arr = CF.array_write(arr, 2, jnp.asarray([5.0, 6.0]))
        assert CF.array_length(arr) == 3
        np.testing.assert_allclose(np.asarray(CF.array_read(arr, 0)),
                                   [1.0, 2.0])
        stacked = CF.tensor_array_to_tensor(arr)
        assert stacked.shape == (3, 2)
        cat = CF.tensor_array_to_tensor(arr, axis=1)
        assert cat.shape == (6,)


class TestDetectionComposites:
    def test_detection_output_shapes(self):
        rng = np.random.RandomState(0)
        p, c, b = 16, 4, 2
        anchors = jnp.asarray(
            np.sort(rng.rand(p, 2, 2), axis=1).reshape(p, 4).astype(
                np.float32))
        loc = jnp.asarray(rng.randn(b, p, 4).astype(np.float32) * 0.1)
        conf = jnp.asarray(rng.randn(b, p, c).astype(np.float32))
        boxes, cls, scores, valid = D.detection_output(
            loc, conf, anchors, keep_top_k=10)
        assert boxes.shape[0] == b and boxes.shape[2] == 4
        v = np.asarray(valid)
        assert v.any()
        cl = np.asarray(cls)[v]
        assert ((cl >= 1) & (cl < c)).all()   # background never returned

    def test_multiclass_nms2_returns_indices(self):
        boxes = jnp.asarray([[0, 0, 1, 1], [5, 5, 6, 6]], jnp.float32)
        scores = jnp.asarray([[0.9, 0.1], [0.2, 0.8]])
        cls, idxs, valid, idx2 = D.multiclass_nms2(boxes, scores,
                                                   max_per_class=2)
        np.testing.assert_array_equal(np.asarray(idxs), np.asarray(idx2))

    def test_box_decoder_and_assign(self):
        anchors = jnp.asarray([[0, 0, 10, 10]], jnp.float32)
        deltas = jnp.zeros((1, 8))        # 2 classes x 4
        scores = jnp.asarray([[0.2, 0.8]])
        decoded, assigned = D.box_decoder_and_assign(anchors, deltas,
                                                     scores)
        assert decoded.shape == (1, 2, 4)
        np.testing.assert_allclose(np.asarray(assigned),
                                   np.asarray(decoded)[:, 1], rtol=1e-6)

    def test_retinanet_target_assign(self):
        anchors = jnp.asarray([[0, 0, 10, 10], [20, 20, 30, 30],
                               [100, 100, 110, 110]], jnp.float32)
        gt = jnp.asarray([[0, 0, 10, 10]], jnp.float32)
        cls, tgt, fg, n_fg = D.retinanet_target_assign(
            anchors, gt, jnp.asarray([3]), jnp.asarray([True]))
        lab = np.asarray(cls)
        assert lab[0] == 3 and lab[1] == 0 and lab[2] == 0
        assert int(n_fg) == 1


class TestCTCDecodeAndEditDistance:
    def test_greedy_decoder_merges_and_drops(self):
        # frames: a a blank a b b -> "a a b" (merge repeats per segment)
        ids = [1, 1, 0, 1, 2, 2]
        probs = jax.nn.one_hot(jnp.asarray([ids]), 3)
        toks, lens = CRF.ctc_greedy_decoder(probs, jnp.asarray([6]))
        assert int(lens[0]) == 3
        np.testing.assert_array_equal(np.asarray(toks)[0, :3], [1, 1, 2])

    def test_edit_distance_op_matches_host_metric(self):
        from paddle_tpu.metrics import EditDistance as HostED
        rng = np.random.RandomState(0)
        b, l1, l2 = 4, 7, 6
        hyp = rng.randint(1, 5, (b, l1))
        ref = rng.randint(1, 5, (b, l2))
        hl = np.array([7, 5, 3, 1])
        rl = np.array([6, 6, 2, 4])
        out = np.asarray(CRF.edit_distance(
            jnp.asarray(hyp), jnp.asarray(hl), jnp.asarray(ref),
            jnp.asarray(rl), normalized=False))
        for i in range(b):
            want = HostED.levenshtein(hyp[i, :hl[i]], ref[i, :rl[i]])
            assert out[i] == pytest.approx(want), i


class TestRCNNTail:
    def test_psroi_pool_groups(self):
        # k=2, D=1: 4 channel groups; group g is constant g+1
        k, d, h, w = 2, 1, 8, 8
        feats = jnp.stack([jnp.full((h, w), g + 1.0)
                           for g in range(k * k)], -1)
        rois = jnp.asarray([[0.0, 0.0, 8.0, 8.0]])
        out = np.asarray(D.psroi_pool(feats, rois, output_size=2))
        # bin (i, j) pools only group i*k+j -> value i*k+j+1
        np.testing.assert_allclose(out[0, :, :, 0],
                                   [[1.0, 2.0], [3.0, 4.0]], rtol=1e-5)

    def test_prroi_pool_constant_field(self):
        feats = jnp.full((8, 8, 3), 2.5)
        rois = jnp.asarray([[1.2, 1.7, 6.3, 6.9]])   # non-integer coords
        out = np.asarray(D.prroi_pool(feats, rois, output_size=(2, 2)))
        np.testing.assert_allclose(out, 2.5, rtol=1e-4)

    def test_prroi_differentiable_wrt_rois(self):
        rng = np.random.RandomState(0)
        feats = jnp.asarray(rng.randn(8, 8, 2).astype(np.float32))
        g = jax.grad(lambda r: D.prroi_pool(feats, r).sum())(
            jnp.asarray([[1.0, 1.0, 6.0, 6.0]]))
        assert np.abs(np.asarray(g)).sum() > 0

    def test_deformable_conv_zero_offset_equals_conv(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(1, 6, 6, 3).astype(np.float32))
        wgt = jnp.asarray(rng.randn(3, 3, 3, 4).astype(np.float32))
        off = jnp.zeros((1, 4, 4, 2 * 9))
        out = D.deformable_conv(x, off, wgt)
        ref = jax.lax.conv_general_dilated(
            x, wgt, (1, 1), "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)

    def test_deformable_conv_mask_scales(self):
        x = jnp.ones((1, 4, 4, 1))
        wgt = jnp.ones((1, 1, 1, 1))
        off = jnp.zeros((1, 4, 4, 2))
        half = 0.5 * jnp.ones((1, 4, 4, 1))
        out = D.deformable_conv(x, off, wgt, mask=half)
        np.testing.assert_allclose(np.asarray(out), 0.5, rtol=1e-6)

    def test_generate_proposal_labels(self):
        rois = jnp.asarray([[0, 0, 10, 10], [1, 1, 11, 11],
                            [50, 50, 60, 60], [49, 49, 61, 61]],
                           jnp.float32)
        valid = jnp.ones((4,), bool)
        gt = jnp.asarray([[0, 0, 10, 10]], jnp.float32)
        labels, tgt, fg, bg = D.generate_proposal_labels(
            rois, valid, gt, jnp.asarray([5]), jnp.asarray([True]),
            batch_size_per_im=4, fg_fraction=0.5)
        lab = np.asarray(labels)
        assert lab[0] == 5                 # IoU 1.0 -> fg with gt class
        assert (lab[2:] == 0).all()        # far rois -> background
        assert np.abs(np.asarray(tgt)[~np.asarray(fg)]).sum() == 0

    def test_py_func_callback(self):
        from paddle_tpu.ops.control_flow import py_func

        def host_fn(a):
            return np.asarray(a) * 2.0

        @jax.jit
        def traced(x):
            return py_func(host_fn, (x,),
                           jax.ShapeDtypeStruct((3,), jnp.float32))

        np.testing.assert_allclose(np.asarray(traced(jnp.ones(3))), 2.0)

    def test_crop_tensor(self):
        x = jnp.arange(24, dtype=jnp.float32).reshape(4, 6)
        out = np.asarray(T.crop_tensor(x, (2, 3), (1, 2)))
        np.testing.assert_allclose(out, np.arange(24).reshape(4, 6)
                                   [1:3, 2:5])


class TestReviewFixes2:
    def test_spectral_norm_default_iters(self):
        rng = np.random.RandomState(1)
        w = jnp.asarray(rng.randn(5, 3).astype(np.float32))
        u = jnp.ones((5,)) / np.sqrt(5)
        wn, _ = N.spectral_norm(w, u)          # power_iters=1 default
        assert wn.shape == w.shape

    def test_conv3d_transpose_rejects_string_padding(self):
        with pytest.raises(ValueError):
            N.conv3d_transpose(jnp.ones((1, 2, 2, 2, 1)),
                               jnp.ones((2, 2, 2, 1, 1)), padding="SAME")

    def test_detection_output_crowded_single_class(self):
        # 30 well-separated boxes of ONE class: keep_top_k=20 must return
        # 20 of them, not keep_top_k // C
        n = 30
        centers = np.arange(n) * 10.0
        anchors = np.stack([centers, centers, centers + 5.0,
                            centers + 5.0], -1).astype(np.float32)
        loc = jnp.zeros((1, n, 4))
        conf = jnp.zeros((1, n, 3)).at[:, :, 1].set(5.0)
        boxes, cls, scores, valid = D.detection_output(
            loc, conf, jnp.asarray(anchors / 300.0), keep_top_k=20)
        assert int(np.asarray(valid).sum()) == 20


class TestReviewFixes3:
    def test_sequence_reshape_flags_indivisible_rows(self):
        x = jnp.arange(12, dtype=jnp.float32).reshape(1, 4, 3)
        _, ln = S.sequence_reshape(x, jnp.asarray([1]), 2)  # 3 % 2 != 0
        assert int(ln[0]) == -1
        _, ln2 = S.sequence_reshape(x, jnp.asarray([2]), 2)
        assert int(ln2[0]) == 3

    def test_sampled_softmax_removes_accidental_hits(self):
        # 2 rows, same true label; a perfect model must reach ~0 loss
        d, c = 4, 10
        emb = jnp.asarray([[10.0, 0, 0, 0], [10.0, 0, 0, 0]])
        table = jnp.zeros((c, d)).at[3, 0].set(1.0)   # class 3 aligned
        labels = jnp.asarray([3, 3])
        loss = float(N.sampled_softmax_with_cross_entropy(
            lambda ids: emb @ table[ids].T, labels,
            jax.random.PRNGKey(0), num_samples=8, num_classes=c))
        assert loss < 0.05     # duplicate label columns masked out

    def test_op_frequency_sees_cond_branches(self):
        from paddle_tpu.debug import op_frequency

        def f(x):
            return jax.lax.cond(x.sum() > 0,
                                lambda y: jnp.sin(y),
                                lambda y: jnp.tanh(y), x)

        freq = op_frequency(f, jnp.ones((3,)))
        assert freq.get("sin", 0) >= 1 and freq.get("tanh", 0) >= 1


class TestRoiPerspective:
    def test_axis_aligned_quad_matches_resize(self):
        # axis-aligned quad == plain crop+resize of the feature map
        feats = jnp.asarray(
            np.arange(64, dtype=np.float32).reshape(8, 8, 1))
        quad = jnp.asarray([[2.0, 2.0, 5.0, 2.0, 5.0, 5.0, 2.0, 5.0]])
        out = np.asarray(D.roi_perspective_transform(
            feats, quad, output_size=(4, 4)))
        # corners of the output must hit the quad corners (up to the
        # Tikhonov guard's ~1e-6 relative perturbation)
        np.testing.assert_allclose(out[0, 0, 0, 0], feats[2, 2, 0],
                                   rtol=1e-3)
        np.testing.assert_allclose(out[0, 0, 3, 0], feats[2, 5, 0],
                                   rtol=1e-3)
        np.testing.assert_allclose(out[0, 3, 3, 0], feats[5, 5, 0],
                                   rtol=1e-3)

    def test_rotated_quad_and_grads(self):
        feats = jnp.asarray(np.random.RandomState(0).randn(10, 10, 2),
                            jnp.float32)
        quad = jnp.asarray([[5.0, 1.0, 9.0, 5.0, 5.0, 9.0, 1.0, 5.0]])
        out = D.roi_perspective_transform(feats, quad,
                                          output_size=(4, 4))
        assert out.shape == (1, 4, 4, 2)
        g = jax.grad(lambda q: D.roi_perspective_transform(
            feats, q, output_size=(4, 4)).sum())(quad)
        assert np.isfinite(np.asarray(g)).all()
        assert np.abs(np.asarray(g)).sum() > 0


class TestCTRTail:
    def test_cvm(self):
        x = jnp.asarray([[3.0, 1.0, 7.0, 8.0]])
        out = np.asarray(N.continuous_value_model(x))
        np.testing.assert_allclose(out[0, 0], np.log(4.0), rtol=1e-6)
        np.testing.assert_allclose(out[0, 1], np.log(2.0) - np.log(4.0),
                                   rtol=1e-6)
        np.testing.assert_allclose(out[0, 2:], [7.0, 8.0])
        no = np.asarray(N.continuous_value_model(x, use_cvm=False))
        np.testing.assert_allclose(no, [[7.0, 8.0]])

    def test_filter_by_instag(self):
        ins = jnp.arange(8, dtype=jnp.float32).reshape(4, 2)
        tags = jnp.asarray([[1, -1], [2, 3], [4, -1], [3, -1]])
        rows, keep, order = N.filter_by_instag(
            ins, tags, jnp.asarray([3]))
        k = np.asarray(keep)
        assert k[:2].all() and not k[2:].any()    # rows 1,3 match tag 3
        np.testing.assert_allclose(np.asarray(rows)[0],
                                   np.asarray(ins)[1])

    def test_filter_by_instag_ignores_padding_tag(self):
        ins = jnp.arange(4, dtype=jnp.float32).reshape(2, 2)
        tags = jnp.asarray([[1, -1], [2, 3]])
        _, keep, _ = N.filter_by_instag(ins, tags,
                                        jnp.asarray([3, -1]))
        k = np.asarray(keep)
        assert k.sum() == 1            # only the real tag-3 row


class TestDeformableRoiPooling:
    def test_zero_offsets_sample_bin_centers(self):
        feats = jnp.asarray(
            np.arange(64, dtype=np.float32).reshape(8, 8, 1))
        rois = jnp.asarray([[0.0, 0.0, 8.0, 8.0]])
        out0 = D.deformable_roi_pooling(feats, rois, None,
                                        output_size=(2, 2))
        outz = D.deformable_roi_pooling(
            feats, rois, jnp.zeros((1, 2, 2, 2)), output_size=(2, 2))
        np.testing.assert_allclose(np.asarray(out0), np.asarray(outz))

    def test_offsets_shift_sampling_and_grads_flow(self):
        feats = jnp.asarray(
            np.arange(64, dtype=np.float32).reshape(8, 8, 1))
        rois = jnp.asarray([[0.0, 0.0, 8.0, 8.0]])
        off = jnp.zeros((1, 2, 2, 2)).at[0, 0, 0, 1].set(0.5)
        shifted = D.deformable_roi_pooling(feats, rois, off,
                                           output_size=(2, 2),
                                           gamma=0.25)
        base = D.deformable_roi_pooling(feats, rois, None,
                                        output_size=(2, 2))
        assert float(shifted[0, 0, 0, 0]) > float(base[0, 0, 0, 0])
        g = jax.grad(lambda o: D.deformable_roi_pooling(
            feats, rois, o, output_size=(2, 2)).sum())(off)
        assert np.abs(np.asarray(g)).sum() > 0
