"""Ring attention (sequence parallelism) vs full attention parity.

Pattern follows the reference's collective tests
(test_collective_base.py:34 — compare a distributed op against the
single-process NumPy/XLA computation), on the virtual 8-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.ops import attention as A
from paddle_tpu.parallel.ring_attention import ring_attention


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshConfig(dp=2, sp=4))


def _qkv(key, b=2, h=2, s=32, d=8):
    kq, kk, kv = jax.random.split(key, 3)
    return (jax.random.normal(kq, (b, h, s, d)),
            jax.random.normal(kk, (b, h, s, d)),
            jax.random.normal(kv, (b, h, s, d)))


class TestRingAttention:
    def test_matches_full(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        ref = A.scaled_dot_product_attention(q, k, v)
        with mesh_context(sp_mesh):
            out = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, mesh=sp_mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_causal(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(1))
        ref = A.scaled_dot_product_attention(q, k, v, causal=True)
        with mesh_context(sp_mesh):
            out = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, causal=True, mesh=sp_mesh))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_padding_bias(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(2))
        mask = jnp.arange(32)[None, :] < jnp.array([20, 32])[:, None]
        bias = A.make_padding_bias(mask)
        ref = A.scaled_dot_product_attention(q, k, v, bias=bias)
        with mesh_context(sp_mesh):
            out = jax.jit(lambda q, k, v, b: ring_attention(
                q, k, v, bias=b, mesh=sp_mesh))(q, k, v, bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grads_match(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(3))

        def f_ref(q, k, v):
            return A.scaled_dot_product_attention(q, k, v, causal=True).sum()

        with mesh_context(sp_mesh):
            def f_ring(q, k, v):
                return ring_attention(q, k, v, causal=True,
                                      mesh=sp_mesh).sum()

            g_ring = jax.jit(jax.grad(f_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

class TestRingFlash:
    """Ring attention with the flash merge (impl="flash_interpret" runs
    each ring block through the shared kernel harness's lax fallback on
    CPU — paddle_tpu.kernels) vs the full-attention reference — forward
    and backward.

    Historical note: the two non-causal variants here were strict-
    xfailed for several rounds ("PartitionId not supported for SPMD
    partitioning") — the non-causal path emitted a DEAD axis_index whose
    PartitionId the partitioner refused. The shared-harness migration
    dropped the dead computation, so they pass everywhere now."""

    def test_matches_full(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(0))
        ref = A.scaled_dot_product_attention(q, k, v)
        with mesh_context(sp_mesh):
            out = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, mesh=sp_mesh, impl="flash_interpret"))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_causal_matches_full(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(1))
        ref = A.scaled_dot_product_attention(q, k, v, causal=True)
        with mesh_context(sp_mesh):
            out = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, causal=True, mesh=sp_mesh,
                impl="flash_interpret"))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_padding_bias(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(2))
        mask = jnp.arange(32)[None, :] < jnp.array([20, 32])[:, None]
        bias = A.make_padding_bias(mask)
        ref = A.scaled_dot_product_attention(q, k, v, bias=bias)
        with mesh_context(sp_mesh):
            out = jax.jit(lambda q, k, v, b: ring_attention(
                q, k, v, bias=b, mesh=sp_mesh,
                impl="flash_interpret"))(q, k, v, bias)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match(self, sp_mesh, causal):
        q, k, v = _qkv(jax.random.PRNGKey(3))

        def f_ref(q, k, v):
            return A.scaled_dot_product_attention(
                q, k, v, causal=causal).sum()

        with mesh_context(sp_mesh):
            def f_ring(q, k, v):
                return ring_attention(q, k, v, causal=causal, mesh=sp_mesh,
                                      impl="flash_interpret").sum()

            g_ring = jax.jit(jax.grad(f_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)

    def test_grads_with_padding_bias(self, sp_mesh):
        q, k, v = _qkv(jax.random.PRNGKey(4))
        mask = jnp.arange(32)[None, :] < jnp.array([24, 32])[:, None]
        bias = A.make_padding_bias(mask)

        def f_ref(q, k, v):
            return A.scaled_dot_product_attention(q, k, v, bias=bias).sum()

        with mesh_context(sp_mesh):
            def f_ring(q, k, v):
                return ring_attention(q, k, v, bias=bias, mesh=sp_mesh,
                                      impl="flash_interpret").sum()

            g_ring = jax.jit(jax.grad(f_ring, argnums=(0, 1, 2)))(q, k, v)
        g_ref = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_ring, g_ref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-4, rtol=1e-4)


class TestRingBert:
    def test_bert_with_ring_attention(self, sp_mesh):
        """End-to-end: BERT forward with attn_impl='ring' on a dp x sp mesh
        matches the same model with composed attention."""
        from paddle_tpu.models.bert import BertConfig, BertModel

        cfg = BertConfig.tiny(attn_impl="ring", dropout=0.0,
                              attn_dropout=0.0, max_position=32)
        cfg_ref = BertConfig.tiny(attn_impl="xla", dropout=0.0,
                                  attn_dropout=0.0, max_position=32)
        model = BertModel(cfg)
        model_ref = BertModel(cfg_ref)
        params = model.init(jax.random.PRNGKey(0))
        ids = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0,
                                 cfg.vocab_size, jnp.int32)
        with mesh_context(sp_mesh):
            seq, pooled = jax.jit(
                lambda p, i: model(p, i))(params, ids)
        seq_ref, pooled_ref = model_ref(params, ids)
        np.testing.assert_allclose(np.asarray(seq), np.asarray(seq_ref),
                                   atol=2e-5, rtol=2e-5)
