"""CRNN-CTC OCR recognition + DCGAN book chapter: the sequence-recognition
and adversarial-training model families."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the quick CI gate

from paddle_tpu import optimizer as opt
from paddle_tpu.train import build_train_step, make_train_state


def _text_images(n=16, img_h=32, img_w=64, vocab=6, max_len=4, seed=0):
    """Images whose column blocks encode the label tokens (learnable)."""
    rng = np.random.RandomState(seed)
    xs = np.zeros((n, img_h, img_w, 1), np.float32)
    labels = np.zeros((n, max_len), np.int64)
    lengths = np.full((n,), max_len, np.int64)
    block = img_w // max_len
    for i in range(n):
        toks = rng.randint(1, vocab, max_len)
        labels[i] = toks
        for j, t in enumerate(toks):
            # each token paints a distinct horizontal stripe pattern
            xs[i, (t * 3) % img_h:(t * 3) % img_h + 6,
               j * block:(j + 1) * block, 0] = 1.0
    xs += 0.1 * rng.randn(*xs.shape).astype(np.float32)
    return (jnp.asarray(xs), jnp.asarray(labels), jnp.asarray(lengths))


class TestCRNN:
    def test_ctc_training_and_decode(self):
        from paddle_tpu.metrics import EditDistance
        from paddle_tpu.models.ocr import CRNN

        image, label, lengths = _text_images()
        model = CRNN(vocab_size=6, width=8, hidden=16)
        optimizer = opt.Adam(learning_rate=3e-3)
        step = jax.jit(build_train_step(
            lambda p, **b: model.loss(p, **b), optimizer))
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        batch = dict(image=image, label=label, label_lengths=lengths)
        losses = []
        for _ in range(30):
            state, m = step(state, **batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.7, (losses[0], losses[-1])

        toks, out_lens = jax.jit(model.recognize)(state["params"], image)
        ed = EditDistance(normalized=True)
        ed.update(np.asarray(toks), np.asarray(label),
                  hyp_lengths=np.asarray(out_lens),
                  ref_lengths=np.asarray(lengths))
        # trained model beats the trivial all-wrong baseline decisively
        assert ed.eval()["edit_distance"] < 0.8

    def test_logits_time_axis_is_width(self):
        from paddle_tpu.models.ocr import CRNN
        model = CRNN(vocab_size=5, width=8, hidden=8)
        params = model.init(jax.random.PRNGKey(0))
        logits = model.logits(params, jnp.zeros((2, 32, 64, 1)))
        assert logits.shape == (2, 16, 5)       # W/4 timesteps


class TestDCGAN:
    def test_adversarial_updates_move_both_losses(self):
        from paddle_tpu.models.gan import (DCGANDiscriminator,
                                           DCGANGenerator, gan_step)
        rng = np.random.RandomState(0)
        gen = DCGANGenerator(zdim=16, base=8, n_up=3, out_ch=1)
        disc = DCGANDiscriminator(in_ch=1, base=8, n_down=3)
        g_opt = opt.Adam(learning_rate=2e-4, beta1=0.5)
        d_opt = opt.Adam(learning_rate=2e-4, beta1=0.5)
        g_params = gen.init(jax.random.PRNGKey(0))
        d_params = disc.init(jax.random.PRNGKey(1))
        g_state = {"params": g_params, "opt": g_opt.init(g_params)}
        d_state = {"params": d_params, "opt": d_opt.init(d_params)}
        step = jax.jit(gan_step(gen, disc, g_opt, d_opt))
        real = jnp.asarray(np.tanh(rng.randn(8, 32, 32, 1)),
                           jnp.float32)
        key = jax.random.PRNGKey(2)
        hist = []
        for i in range(6):
            key, sub = jax.random.split(key)
            g_state, d_state, m = step(g_state, d_state, real, sub)
            hist.append((float(m["d_loss"]), float(m["g_loss"])))
        d0, g0 = hist[0]
        dN, gN = hist[-1]
        assert np.isfinite([d0, g0, dN, gN]).all()
        assert dN < d0          # discriminator learns
        # generator output shape/range
        fake = gen(g_state["params"],
                   jax.random.normal(key, (2, 16)))
        assert fake.shape == (2, 32, 32, 1)
        assert float(jnp.abs(fake).max()) <= 1.0


class TestGANReviewFixes:
    def test_bn_stats_update_through_gan_step(self):
        from paddle_tpu.models.gan import (DCGANDiscriminator,
                                           DCGANGenerator, gan_step)
        gen = DCGANGenerator(zdim=8, base=8, n_up=3, out_ch=1)
        disc = DCGANDiscriminator(in_ch=1, base=8, n_down=3)
        g_opt = opt.Adam(learning_rate=1e-4)
        d_opt = opt.Adam(learning_rate=1e-4)
        gp = gen.init(jax.random.PRNGKey(0))
        dp = disc.init(jax.random.PRNGKey(1))
        mean0 = np.asarray(dp["bns"]["0"]["mean"]).copy()
        g_state = {"params": gp, "opt": g_opt.init(gp)}
        d_state = {"params": dp, "opt": d_opt.init(dp)}
        step = jax.jit(gan_step(gen, disc, g_opt, d_opt))
        real = jnp.asarray(
            np.random.RandomState(0).randn(4, 32, 32, 1), jnp.float32)
        g_state, d_state, _ = step(g_state, d_state, real,
                                   jax.random.PRNGKey(2))
        mean1 = np.asarray(d_state["params"]["bns"]["0"]["mean"])
        assert not np.allclose(mean0, mean1)   # running stats moved

    def test_discriminator_rejects_wrong_size(self):
        import pytest
        from paddle_tpu.models.gan import DCGANDiscriminator
        disc = DCGANDiscriminator(in_ch=1, base=8, n_down=3)
        params = disc.init(jax.random.PRNGKey(0))
        with pytest.raises(ValueError):
            disc(params, jnp.zeros((1, 64, 64, 1)))

    def test_d_stats_track_real_batch(self):
        from paddle_tpu.models.gan import (DCGANDiscriminator,
                                           DCGANGenerator, gan_step)
        gen = DCGANGenerator(zdim=8, base=8, n_up=3, out_ch=1)
        disc = DCGANDiscriminator(in_ch=1, base=8, n_down=3)
        g_opt = opt.Adam(learning_rate=0.0)   # freeze: isolate stats
        d_opt = opt.Adam(learning_rate=0.0)
        gp = gen.init(jax.random.PRNGKey(0))
        dp = disc.init(jax.random.PRNGKey(1))
        g_state = {"params": gp, "opt": g_opt.init(gp)}
        d_state = {"params": dp, "opt": d_opt.init(dp)}
        step = jax.jit(gan_step(gen, disc, g_opt, d_opt))
        # lr=0 keeps params fixed, so after ONE step the running stats
        # must equal a manual real-batch-only tape applied to the same
        # params — if fake-forward stats leaked in, they would differ
        from paddle_tpu.nn.module import (apply_state_updates,
                                          capture_state)
        real = jnp.full((8, 32, 32, 1), 5.0)
        with capture_state() as tape:
            disc(dp, real, training=True)
        expected = apply_state_updates(dp, tape)["bns"]["0"]["mean"]
        g_state, d_state, _ = step(g_state, d_state, real,
                                   jax.random.PRNGKey(0))
        got = d_state["params"]["bns"]["0"]["mean"]
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-6)
        assert np.abs(np.asarray(got)).max() > 1e-4   # actually moved
