"""Distributions parity tests vs scipy closed forms.

Reference behaviors under test: ``fluid.layers.distributions``
(``distributions.py:113`` Uniform, ``:246`` Normal, ``:401`` Categorical,
``:494`` MultivariateNormalDiag), checked against ``scipy.stats`` instead of
the reference's hand-written numpy oracles (``test_distributions.py`` in the
reference unittests does the same comparison-to-closed-form exercise).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.stats as st

from paddle_tpu.nn.distributions import (Categorical, MultivariateNormalDiag,
                                         Normal, Uniform, kl_divergence)


class TestUniform:
    def test_log_prob_matches_scipy(self):
        low, high = np.array([0.0, 1.0]), np.array([2.0, 5.0])
        d = Uniform(low, high)
        x = np.array([1.0, 2.5])
        np.testing.assert_allclose(
            d.log_prob(x), st.uniform(low, high - low).logpdf(x), rtol=1e-6)

    def test_log_prob_outside_support(self):
        d = Uniform(0.0, 1.0)
        assert np.isneginf(d.log_prob(2.0))
        assert np.isneginf(d.log_prob(-0.5))

    def test_entropy_matches_scipy(self):
        d = Uniform(np.array([0.0, -1.0]), np.array([4.0, 1.0]))
        np.testing.assert_allclose(
            d.entropy(), st.uniform([0.0, -1.0], [4.0, 2.0]).entropy(),
            rtol=1e-6)

    def test_sample_shape_and_range(self):
        d = Uniform(np.array([0.0, 10.0]), np.array([1.0, 20.0]))
        s = d.sample((1000,), key=jax.random.PRNGKey(0))
        assert s.shape == (1000, 2)
        assert (s[:, 0] >= 0).all() and (s[:, 0] < 1).all()
        assert (s[:, 1] >= 10).all() and (s[:, 1] < 20).all()
        # mean of U[10,20) ≈ 15
        assert abs(float(s[:, 1].mean()) - 15.0) < 0.5

    def test_kl_contained_and_not(self):
        p, q = Uniform(0.0, 1.0), Uniform(-1.0, 3.0)
        np.testing.assert_allclose(p.kl_divergence(q), np.log(4.0), rtol=1e-6)
        assert np.isposinf(q.kl_divergence(p))

    def test_broadcasting(self):
        d = Uniform(0.0, np.array([1.0, 2.0, 4.0]))
        assert d.entropy().shape == (3,)
        assert d.sample((5,), key=jax.random.PRNGKey(1)).shape == (5, 3)


class TestNormal:
    def test_log_prob_matches_scipy(self):
        loc, scale = np.array([0.0, 2.0]), np.array([1.0, 3.0])
        d = Normal(loc, scale)
        x = np.array([0.7, -1.2])
        np.testing.assert_allclose(
            d.log_prob(x), st.norm(loc, scale).logpdf(x), rtol=1e-5)

    def test_entropy_matches_scipy(self):
        loc, scale = np.array([0.0, 2.0]), np.array([1.0, 3.0])
        np.testing.assert_allclose(
            Normal(loc, scale).entropy(), st.norm(loc, scale).entropy(),
            rtol=1e-6)

    def test_kl_closed_form(self):
        p, q = Normal(0.0, 1.0), Normal(1.0, 2.0)
        # scipy has no normal-normal KL; closed form 0.5(σr+t1-1-lnσr)
        var_ratio = (1.0 / 2.0) ** 2
        expect = 0.5 * (var_ratio + (1.0 / 2.0) ** 2 - 1.0
                        - np.log(var_ratio))
        np.testing.assert_allclose(p.kl_divergence(q), expect, rtol=1e-6)
        np.testing.assert_allclose(p.kl_divergence(Normal(0.0, 1.0)), 0.0,
                                   atol=1e-7)

    def test_sample_moments(self):
        d = Normal(3.0, 0.5)
        s = d.sample((20000,), key=jax.random.PRNGKey(0))
        assert abs(float(s.mean()) - 3.0) < 0.02
        assert abs(float(s.std()) - 0.5) < 0.02

    def test_jit_and_grad(self):
        def loss(loc):
            return Normal(loc, 1.0).log_prob(0.0)
        g = jax.jit(jax.grad(loss))(2.0)
        np.testing.assert_allclose(g, -2.0, rtol=1e-6)  # d/dμ logN = (x-μ)/σ²


class TestCategorical:
    def test_entropy_matches_scipy(self):
        logits = np.array([0.1, 1.2, -0.3, 0.0], np.float32)
        d = Categorical(logits)
        p = np.exp(logits) / np.exp(logits).sum()
        np.testing.assert_allclose(d.entropy()[0], st.entropy(p), rtol=1e-5)

    def test_reference_doc_example(self):
        # reference docstring values (distributions.py:429-439)
        a = Categorical(np.array([-0.602, -0.602], np.float32))
        b = Categorical(np.array([-0.102, -0.112], np.float32))
        np.testing.assert_allclose(a.entropy(), [0.6931472], rtol=1e-5)
        np.testing.assert_allclose(b.entropy(), [0.6931347], rtol=1e-5)
        np.testing.assert_allclose(a.kl_divergence(b), [1.2516975e-05],
                                   atol=1e-8)

    def test_kl_vs_scipy(self):
        la = np.array([0.5, -0.5, 1.0], np.float32)
        lb = np.array([0.0, 0.2, -0.1], np.float32)
        pa = np.exp(la) / np.exp(la).sum()
        pb = np.exp(lb) / np.exp(lb).sum()
        np.testing.assert_allclose(
            Categorical(la).kl_divergence(Categorical(lb))[0],
            st.entropy(pa, pb), rtol=1e-5)

    def test_log_prob_and_sample(self):
        logits = np.array([[0.0, 1.0, 2.0], [2.0, 1.0, 0.0]], np.float32)
        d = Categorical(logits)
        lp = d.log_prob(np.array([2, 0]))
        expect = jax.nn.log_softmax(logits)[np.arange(2), [2, 0]]
        np.testing.assert_allclose(lp, expect, rtol=1e-6)
        s = d.sample((500,), key=jax.random.PRNGKey(0))
        assert s.shape == (500, 2)
        # class 2 dominates row 0 (softmax([0,1,2])[2] ≈ .665)
        frac = float((s[:, 0] == 2).mean())
        assert 0.58 < frac < 0.74

    def test_saturated_logits_stay_finite(self):
        # a collapsed policy underflows suppressed classes to logp=-inf;
        # entropy/KL must define p·log p = 0 at p = 0, not NaN
        logits = np.array([[0.0, -np.inf, -1e4]], np.float32)
        d = Categorical(logits)
        assert np.isfinite(d.entropy()).all()
        np.testing.assert_allclose(d.entropy(), 0.0, atol=1e-6)
        kl = d.kl_divergence(Categorical(np.zeros((1, 3), np.float32)))
        np.testing.assert_allclose(kl, np.log(3.0), rtol=1e-6)
        # grads through a saturated entropy stay finite too
        g = jax.grad(lambda lg: Categorical(lg).entropy().sum())(
            jnp.array([[60.0, -60.0, 0.0]], jnp.float32))
        assert np.isfinite(g).all()

    def test_masked_logits_grads_finite(self):
        # -inf logits are the action-masking idiom; entropy/KL grads must
        # not NaN through the masked classes (double-where)
        logits = jnp.array([[0.0, -jnp.inf, 1.0]], jnp.float32)
        g = jax.grad(lambda lg: Categorical(lg).entropy().sum())(logits)
        assert np.isfinite(np.asarray(g)[0, [0, 2]]).all()
        assert not np.isnan(np.asarray(g)).any()
        gkl = jax.grad(lambda lg: Categorical(lg).kl_divergence(
            Categorical(jnp.zeros((1, 3), jnp.float32))).sum())(logits)
        assert not np.isnan(np.asarray(gkl)).any()

    def test_batched_entropy_shape(self):
        d = Categorical(np.zeros((4, 7), np.float32))
        assert d.entropy().shape == (4, 1)  # keepdims like the reference


class TestMultivariateNormalDiag:
    def _pair(self):
        a = MultivariateNormalDiag(np.array([0.3, 0.5], np.float32),
                                   np.diag([0.4, 0.5]).astype(np.float32))
        b = MultivariateNormalDiag(np.array([0.2, 0.4], np.float32),
                                   np.diag([0.3, 0.4]).astype(np.float32))
        return a, b

    def test_reference_doc_example(self):
        # reference docstring values (distributions.py:538-543)
        a, b = self._pair()
        np.testing.assert_allclose(a.entropy(), 2.033158, rtol=1e-5)
        np.testing.assert_allclose(b.entropy(), 1.7777451, rtol=1e-5)
        np.testing.assert_allclose(a.kl_divergence(b), 0.06542051, rtol=1e-4)

    def test_entropy_matches_scipy(self):
        a, _ = self._pair()
        ref = st.multivariate_normal([0.3, 0.5], np.diag([0.4, 0.5])).entropy()
        np.testing.assert_allclose(a.entropy(), ref, rtol=1e-5)

    def test_log_prob_matches_scipy(self):
        a, _ = self._pair()
        x = np.array([0.1, 0.9])
        ref = st.multivariate_normal([0.3, 0.5], np.diag([0.4, 0.5])).logpdf(x)
        np.testing.assert_allclose(a.log_prob(x), ref, rtol=1e-5)

    def test_sample_moments(self):
        a, _ = self._pair()
        s = a.sample((20000,), key=jax.random.PRNGKey(0))
        assert s.shape == (20000, 2)
        np.testing.assert_allclose(s.mean(0), [0.3, 0.5], atol=0.02)
        np.testing.assert_allclose(s.var(0), [0.4, 0.5], atol=0.02)

    def test_rejects_nonsquare_scale(self):
        with pytest.raises(ValueError):
            MultivariateNormalDiag(np.zeros(2), np.zeros((2, 3)))


def test_default_sample_is_fresh():
    # no key/seed -> a fresh draw per call (reference seed=0 semantics);
    # identical repeated draws would silently break Monte Carlo loops
    a = Normal(0.0, 1.0).sample((4,))
    b = Normal(0.0, 1.0).sample((4,))
    assert not np.allclose(a, b)
    # explicit seed stays reproducible
    s1 = Normal(0.0, 1.0).sample((4,), seed=7)
    s2 = Normal(0.0, 1.0).sample((4,), seed=7)
    np.testing.assert_array_equal(s1, s2)


def test_keyless_sample_under_trace_raises():
    """sample() without key/seed inside jit would bake ONE draw into the
    compiled function (ADVICE round 5) — it must refuse loudly instead."""
    d = Normal(0.0, 1.0)

    with pytest.raises(ValueError, match="trace"):
        jax.jit(lambda: d.sample((2,)))()
    # explicit key and explicit seed both stay legal under jit
    out = jax.jit(lambda k: d.sample((2,), key=k))(jax.random.PRNGKey(0))
    assert out.shape == (2,)
    out = jax.jit(lambda: d.sample((2,), seed=3))()
    assert out.shape == (2,)


def test_uniform_own_sample_in_support():
    # jax.random.uniform includes 0.0 -> sample can be exactly `low`;
    # log_prob of a self-drawn sample must be finite
    d = Uniform(2.0, 3.0)
    assert np.isfinite(d.log_prob(2.0))
    assert np.isneginf(d.log_prob(3.0))


def test_functional_kl():
    p, q = Normal(0.0, 1.0), Normal(0.5, 1.5)
    np.testing.assert_allclose(kl_divergence(p, q), p.kl_divergence(q))


def test_type_errors():
    with pytest.raises(TypeError):
        Normal(0.0, 1.0).kl_divergence(Uniform(0.0, 1.0))
    with pytest.raises(TypeError):
        Categorical(np.zeros(3)).kl_divergence(Normal(0.0, 1.0))
