"""CV model-zoo breadth: MobileNetV1/V2, VGG, SE-ResNeXt, SSD detector —
PaddleCV zoo parity (reference dist test model dist_se_resnext.py,
image_classification/{mobilenet,vgg}.py, object_detection SSD). Tiny
configs; train-smoke asserts loss decreases (book-test convention)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
pytestmark = pytest.mark.slow  # excluded from the quick CI gate


from paddle_tpu import optimizer as opt
from paddle_tpu.train import build_train_step, make_train_state


def _train_smoke(model, batch, steps=4, lr=1e-2, loss_kw=None,
                 optimizer=None):
    optimizer = optimizer or opt.Momentum(learning_rate=lr, momentum=0.9)
    loss_kw = loss_kw or {}

    def loss_fn(params, **b):
        return model.loss(params, training=True, **b, **loss_kw)

    step = jax.jit(build_train_step(loss_fn, optimizer))
    state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
    losses = []
    for _ in range(steps):
        state, metrics = step(state, **batch)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(losses)), losses
    assert losses[-1] < losses[0], losses
    return losses


def _images(b=4, s=32, c=3, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    return dict(
        image=jnp.asarray(rng.randn(b, s, s, c).astype(np.float32)),
        label=jnp.asarray(rng.randint(0, classes, (b,))))


class TestMobileNet:
    def test_v1_forward_and_train(self):
        from paddle_tpu.models.mobilenet import MobileNetV1
        model = MobileNetV1(num_classes=4, scale=0.125)
        batch = _images()
        params = model.init(jax.random.PRNGKey(0))
        logits = model(params, batch["image"])
        assert logits.shape == (4, 4)
        _train_smoke(model, batch)

    def test_v1_feature_endpoints(self):
        from paddle_tpu.models.mobilenet import MobileNetV1
        model = MobileNetV1(num_classes=2, scale=0.125)
        params = model.init(jax.random.PRNGKey(0))
        out, feats = model.features(params, jnp.zeros((1, 32, 32, 3)),
                                    endpoints=(5, 12))
        assert set(feats) == {5, 12}
        # stride schedule: stem /2, blocks 1,3,5 stride 2 -> /16 after 5
        assert feats[5].shape[1] == 2
        assert out.shape[1] == 1                      # /32 final

    def test_v2_forward_and_train(self):
        from paddle_tpu.models.mobilenet import MobileNetV2
        model = MobileNetV2(num_classes=4, scale=0.125)
        batch = _images()
        params = model.init(jax.random.PRNGKey(0))
        assert model(params, batch["image"]).shape == (4, 4)
        # deep trunk + BN on batch 4: momentum oscillates; Adam descends
        _train_smoke(model, batch, steps=8,
                     optimizer=opt.Adam(learning_rate=1e-3))

    def test_v2_residual_wiring(self):
        from paddle_tpu.models.mobilenet import _InvertedResidual
        blk = _InvertedResidual(8, 8, stride=1, expand=6)
        assert blk.residual
        blk2 = _InvertedResidual(8, 16, stride=2, expand=6)
        assert not blk2.residual


class TestVGG:
    def test_forward_and_train(self):
        from paddle_tpu.models.vgg import VGG
        model = VGG(11, num_classes=4, width=8, fc_dim=16)
        batch = _images()
        _train_smoke(model, batch,
                     loss_kw={"key": jax.random.PRNGKey(1)})

    def test_depth_validation(self):
        from paddle_tpu.models.vgg import VGG
        with pytest.raises(ValueError):
            VGG(15)


class TestSEResNeXt:
    def test_forward_and_train(self):
        from paddle_tpu.models.se_resnext import SEResNeXt
        model = SEResNeXt(50, num_classes=4, width=8, cardinality=4,
                          ratio=4)
        batch = _images()
        params = model.init(jax.random.PRNGKey(0))
        assert model(params, batch["image"]).shape == (4, 4)
        _train_smoke(model, batch, steps=3)

    def test_se_gating_bounded(self):
        from paddle_tpu.models.se_resnext import SEBlock
        se = SEBlock(8, ratio=4)
        params = se.init(jax.random.PRNGKey(0))
        x = jnp.ones((2, 4, 4, 8)) * 3.0
        y = se(params, x)
        # sigmoid gate: output magnitude bounded by input magnitude
        assert float(jnp.abs(y).max()) <= float(jnp.abs(x).max()) + 1e-6


class TestSSD:
    def _batch(self, b=2, g=3, classes=4, size=64, seed=0):
        rng = np.random.RandomState(seed)
        ctr = rng.rand(b, g, 2) * 0.6 + 0.2
        wh = rng.rand(b, g, 2) * 0.2 + 0.15
        boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], -1)
        return dict(
            image=jnp.asarray(rng.randn(b, size, size, 3).astype(
                np.float32)),
            gt_boxes=jnp.asarray(boxes.astype(np.float32)),
            gt_labels=jnp.asarray(rng.randint(1, classes, (b, g))),
            gt_mask=jnp.asarray(np.array([[True] * g, [True, True,
                                                       False]])))

    def test_train_smoke(self):
        from paddle_tpu.models.ssd import SSD, SSDConfig
        model = SSD(SSDConfig.tiny())
        _train_smoke(model, self._batch(), steps=4, lr=5e-3)

    def test_detect_shapes_and_validity(self):
        from paddle_tpu.models.ssd import SSD, SSDConfig
        cfg = SSDConfig.tiny()
        model = SSD(cfg)
        params = model.init(jax.random.PRNGKey(0))
        batch = self._batch()
        boxes, cls, scores, valid = model.detect(params, batch["image"])
        b = batch["image"].shape[0]
        assert boxes.shape[0] == b and boxes.shape[2] == 4
        assert cls.shape == scores.shape == valid.shape
        cl = np.asarray(cls)[np.asarray(valid)]
        assert ((cl >= 1) & (cl < cfg.num_classes)).all()

    def test_anchor_count_matches_heads(self):
        from paddle_tpu.models.ssd import SSD, SSDConfig
        model = SSD(SSDConfig.tiny())
        params = model.init(jax.random.PRNGKey(0))
        loc, conf = model.forward(params, jnp.zeros((1, 64, 64, 3)))
        anchors = model.anchors()
        assert loc.shape[1] == anchors.shape[0] == conf.shape[1]


class TestDetectionMetrics:
    def test_detection_map_perfect(self):
        from paddle_tpu.metrics import DetectionMAP
        m = DetectionMAP()
        gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
        m.update(pred_boxes=gt, pred_scores=np.array([0.9, 0.8]),
                 pred_classes=np.array([1, 2]),
                 pred_valid=np.array([True, True]),
                 gt_boxes=gt, gt_classes=np.array([1, 2]),
                 gt_mask=np.array([True, True]))
        assert m.eval() == pytest.approx(1.0)

    def test_detection_map_misses_and_fps(self):
        from paddle_tpu.metrics import DetectionMAP
        m = DetectionMAP(ap_version="integral")
        gt = np.array([[0, 0, 10, 10], [20, 20, 30, 30]], np.float32)
        pred = np.array([[0, 0, 10, 10], [50, 50, 60, 60]], np.float32)
        m.update(pred_boxes=pred, pred_scores=np.array([0.9, 0.8]),
                 pred_classes=np.array([1, 1]),
                 pred_valid=np.array([True, True]),
                 gt_boxes=gt, gt_classes=np.array([1, 1]),
                 gt_mask=np.array([True, True]))
        # one of two gts found, one fp -> AP = 0.5 (integral)
        assert m.eval() == pytest.approx(0.5, abs=1e-6)

    def test_edit_distance(self):
        from paddle_tpu.metrics import EditDistance
        m = EditDistance(normalized=False)
        m.update([[1, 2, 3]], [[1, 3]])
        assert m.eval()["edit_distance"] == pytest.approx(1.0)
        m2 = EditDistance(normalized=True)
        m2.update([[1, 2, 3], [5]], [[1, 2, 3], [4, 5]])
        out = m2.eval()
        assert out["edit_distance"] == pytest.approx(0.25)
        assert out["instance_error"] == pytest.approx(0.5)

    def test_composite(self):
        from paddle_tpu.metrics import Accuracy, CompositeMetric
        cm = CompositeMetric(Accuracy(), Accuracy())
        cm.update(np.array([1, 0]), np.array([1, 1]))
        assert cm.eval() == [0.5, 0.5]


class TestVideoModels:
    def _video(self, b=2, frames=8, s=16, classes=4, seed=0):
        rng = np.random.RandomState(seed)
        return dict(
            video=jnp.asarray(rng.randn(b, frames, s, s, 3).astype(
                np.float32)),
            label=jnp.asarray(rng.randint(0, classes, (b,))))

    def test_tsn_consensus_and_train(self):
        from paddle_tpu.models.video import TSN
        model = TSN(num_classes=4, num_segments=3, scale=0.125)
        batch = dict(self._video(frames=3))
        params = model.init(jax.random.PRNGKey(0))
        logits = model(params, batch["video"])
        assert logits.shape == (2, 4)
        _train_smoke(model, batch, steps=6,
                     optimizer=opt.Adam(learning_rate=1e-3))

    def test_tsn_consensus_is_segment_mean(self):
        from paddle_tpu.models.video import TSN
        model = TSN(num_classes=3, num_segments=2, scale=0.125)
        params = model.init(jax.random.PRNGKey(0))
        v = jnp.asarray(np.random.RandomState(1).randn(1, 2, 16, 16, 3),
                        jnp.float32)
        full = model(params, v)
        per = [model.backbone(params["backbone"], v[:, i])
               for i in range(2)]
        np.testing.assert_allclose(np.asarray(full),
                                   np.asarray((per[0] + per[1]) / 2),
                                   rtol=1e-4, atol=1e-5)

    def test_c3d_train(self):
        from paddle_tpu.models.video import C3D
        model = C3D(num_classes=4, width_scale=0.125)
        batch = self._video(frames=8)
        params = model.init(jax.random.PRNGKey(0))
        assert model(params, batch["video"]).shape == (2, 4)
        _train_smoke(model, batch, steps=6,
                     optimizer=opt.Adam(learning_rate=1e-3))


class TestLegacyCVZoo:
    """AlexNet / GoogLeNet / ShuffleNetV2 — the classic PaddleCV
    image_classification tail."""

    def _train_steps(self, model, hw, n=8):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.train import build_train_step, make_train_state

        rng = np.random.RandomState(0)
        batch = dict(
            image=jnp.asarray(rng.randn(4, hw, hw, 3), jnp.float32),
            label=jnp.asarray(rng.randint(0, 5, (4,))))
        # SGD avoids Adam's zero-second-moment overshoot on the huge
        # AlexNet fc layers at step 1
        optimizer = opt.Momentum(learning_rate=2e-3, momentum=0.9)
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

        def loss_fn(params, image, label, key):
            # the REAL training path: dropout live, BN batch stats
            return model.loss(params, image, label, training=True,
                              key=key)

        step = jax.jit(build_train_step(loss_fn, optimizer))
        losses = []
        for i in range(n):
            state, m = step(state, image=batch["image"],
                            label=batch["label"],
                            key=jax.random.PRNGKey(100 + i))
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0], losses
        # default call path (training=True, no key): dropout skipped,
        # must not crash
        l, _ = model.loss(state["params"], batch["image"], batch["label"])
        assert np.isfinite(float(l))

    def test_alexnet_trains(self):
        from paddle_tpu.models.legacy_cv import AlexNet
        self._train_steps(AlexNet(num_classes=5), hw=64)

    def test_googlenet_trains(self):
        from paddle_tpu.models.legacy_cv import GoogLeNet
        self._train_steps(GoogLeNet(num_classes=5), hw=64)

    def test_shufflenet_trains_and_shuffle_op(self):
        from paddle_tpu.models.legacy_cv import (ShuffleNetV2,
                                                 channel_shuffle)
        x = jnp.arange(8.0).reshape(1, 1, 1, 8)
        got = np.asarray(channel_shuffle(x, 2))[0, 0, 0]
        np.testing.assert_array_equal(got, [0, 4, 1, 5, 2, 6, 3, 7])
        self._train_steps(ShuffleNetV2(num_classes=5), hw=64)

    def test_alexnet_dropout_path(self):
        from paddle_tpu.models.legacy_cv import AlexNet
        m = AlexNet(num_classes=5)
        p = m.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 64, 3))
        l1 = m.forward(p, x, training=True, key=jax.random.PRNGKey(1))
        l2 = m.forward(p, x, training=True, key=jax.random.PRNGKey(2))
        assert not np.allclose(np.asarray(l1), np.asarray(l2))

    def test_squeezenet_trains(self):
        from paddle_tpu.models.legacy_cv import SqueezeNet
        self._train_steps(SqueezeNet(num_classes=5), hw=64)

    def test_densenet_trains(self):
        from paddle_tpu.models.legacy_cv import DenseNet121
        self._train_steps(DenseNet121(num_classes=5, growth=8), hw=64)
