"""Faster R-CNN two-stage detector: the full RCNN op stack composed into
a trainable model (anchor gen -> rpn assign -> proposals -> proposal
labels -> roi_align -> box head), static shapes throughout."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.slow  # excluded from the quick CI gate


def _batch(b=2, g=2, classes=4, size=64, seed=0):
    rng = np.random.RandomState(seed)
    ctr = rng.rand(b, g, 2) * 0.5 + 0.25
    wh = rng.rand(b, g, 2) * 0.25 + 0.2
    boxes = np.concatenate([ctr - wh / 2, ctr + wh / 2], -1) * size
    return dict(
        image=jnp.asarray(rng.randn(b, size, size, 3).astype(np.float32)),
        gt_boxes=jnp.asarray(boxes.astype(np.float32)),
        gt_labels=jnp.asarray(rng.randint(1, classes, (b, g))),
        gt_mask=jnp.asarray(np.array([[True] * g, [True, False]])))


class TestFasterRCNN:
    def test_loss_finite_and_trains(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.models.faster_rcnn import (FasterRCNN,
                                                   FasterRCNNConfig)
        from paddle_tpu.train import build_train_step, make_train_state

        model = FasterRCNN(FasterRCNNConfig.tiny())
        batch = _batch()
        optimizer = opt.Adam(learning_rate=1e-3)
        step = jax.jit(build_train_step(
            lambda p, **b: model.loss(p, **b), optimizer))
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        losses = []
        for _ in range(6):
            state, m = step(state, **batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0], losses

    def test_detect_static_shapes(self):
        from paddle_tpu.models.faster_rcnn import (FasterRCNN,
                                                   FasterRCNNConfig)
        cfg = FasterRCNNConfig.tiny()
        model = FasterRCNN(cfg)
        params = model.init(jax.random.PRNGKey(0))
        batch = _batch()
        boxes, cls, scores, valid = jax.jit(model.detect)(
            params, batch["image"])
        b = batch["image"].shape[0]
        assert boxes.shape[0] == b and boxes.shape[-1] == 4
        assert cls.shape == scores.shape == valid.shape
        v = np.asarray(valid)
        if v.any():
            cl = np.asarray(cls)[v]
            assert ((cl >= 1) & (cl < cfg.num_classes)).all()
            bx = np.asarray(boxes)[v]
            assert (bx[:, 2] >= bx[:, 0] - 1e-3).all()

    def test_gt_boxes_become_foreground_rois(self):
        # with gt mixed into proposals, the sampler must find foregrounds
        from paddle_tpu.models.faster_rcnn import (FasterRCNN,
                                                   FasterRCNNConfig)
        from paddle_tpu.ops import detection as D
        cfg = FasterRCNNConfig.tiny()
        gt = jnp.asarray([[10.0, 10.0, 40.0, 40.0]])
        rois = jnp.concatenate([jnp.zeros((4, 4)), gt])
        valid = jnp.asarray([False, False, False, False, True])
        labels, tgt, fg, bg = D.generate_proposal_labels(
            rois, valid, gt, jnp.asarray([2]), jnp.asarray([True]),
            batch_size_per_im=4)
        assert int(np.asarray(fg).sum()) == 1
        assert int(np.asarray(labels)[4]) == 2


class TestDetectPerClass:
    def test_overlapping_different_classes_both_survive(self):
        # per-class NMS: two classes on the same box must BOTH come out
        from paddle_tpu.ops import detection as D
        boxes = jnp.asarray([[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5]],
                            jnp.float32)
        scores = jnp.asarray([[0.9, 0.05], [0.05, 0.8]])
        cls_ids, idxs, valid = D.multiclass_nms(
            boxes, scores, iou_threshold=0.5, score_threshold=0.1,
            max_per_class=2)
        kept = set(zip(np.asarray(cls_ids)[np.asarray(valid)].tolist(),
                       np.asarray(idxs)[np.asarray(valid)].tolist()))
        assert (0, 0) in kept and (1, 1) in kept

    def test_degenerate_quad_no_nan(self):
        from paddle_tpu.ops import detection as D
        feats = jnp.ones((8, 8, 1))
        quad = jnp.zeros((1, 8))           # all corners identical
        out = D.roi_perspective_transform(feats, quad,
                                          output_size=(2, 2))
        assert np.isfinite(np.asarray(out)).all()


class TestYOLOv3:
    def _batch(self, b=2, g=2, classes=4, seed=0):
        rng = np.random.RandomState(seed)
        ctr = rng.rand(b, g, 2) * 0.5 + 0.25
        wh = rng.rand(b, g, 2) * 0.3 + 0.2
        return dict(
            image=jnp.asarray(rng.randn(b, 64, 64, 3).astype(np.float32)),
            gt_boxes=jnp.asarray(
                np.concatenate([ctr, wh], -1).astype(np.float32)),
            gt_labels=jnp.asarray(rng.randint(0, classes, (b, g))),
            gt_mask=jnp.ones((b, g), bool))

    def test_trains(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.models.yolov3 import YOLOv3, YOLOv3Config
        from paddle_tpu.train import build_train_step, make_train_state

        model = YOLOv3(YOLOv3Config.tiny())
        batch = self._batch()
        optimizer = opt.Adam(learning_rate=1e-3)
        step = jax.jit(build_train_step(
            lambda p, **b: model.loss(p, **b), optimizer))
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        losses = []
        for _ in range(6):
            state, m = step(state, **batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0], losses

    def test_detect_shapes(self):
        from paddle_tpu.models.yolov3 import YOLOv3, YOLOv3Config
        cfg = YOLOv3Config.tiny()
        model = YOLOv3(cfg)
        params = model.init(jax.random.PRNGKey(0))
        batch = self._batch()
        boxes, cls, scores, valid = jax.jit(model.detect)(
            params, batch["image"])
        assert boxes.shape[0] == 2 and boxes.shape[-1] == 4
        v = np.asarray(valid)
        if v.any():
            assert (np.asarray(cls)[v] < cfg.num_classes).all()

    def test_head_count_matches_masks(self):
        from paddle_tpu.models.yolov3 import YOLOv3, YOLOv3Config
        cfg = YOLOv3Config.tiny()
        model = YOLOv3(cfg)
        params = model.init(jax.random.PRNGKey(0))
        heads = model.forward(params, jnp.zeros((1, 64, 64, 3)))
        assert len(heads) == len(cfg.anchor_masks)
        for lvl, h in enumerate(heads):
            a = len(cfg.anchor_masks[lvl])
            assert h.shape[1] == a * (5 + cfg.num_classes)


class TestMaskLabels:
    def test_full_box_roi_recovers_mask(self):
        from paddle_tpu.ops import detection as D
        # gt mask: left half of a 32x32 image is 1
        m = np.zeros((1, 32, 32), np.float32)
        m[0, :, :16] = 1.0
        rois = jnp.asarray([[0.0, 0.0, 32.0, 32.0]])
        targets, w = D.generate_mask_labels(
            rois, jnp.asarray([0]), jnp.asarray([True]),
            jnp.asarray(m), resolution=8, im_size=32)
        t = np.asarray(targets)[0]
        assert t[:, :3].mean() > 0.9      # left side on
        assert t[:, 5:].mean() < 0.1      # right side off
        assert float(w[0]) == 1.0

    def test_non_fg_rois_zeroed(self):
        from paddle_tpu.ops import detection as D
        m = np.ones((1, 16, 16), np.float32)
        targets, w = D.generate_mask_labels(
            jnp.asarray([[0.0, 0.0, 16.0, 16.0]]), jnp.asarray([0]),
            jnp.asarray([False]), jnp.asarray(m), resolution=4,
            im_size=16)
        assert np.asarray(targets).sum() == 0 and float(w[0]) == 0.0


class TestMaskRCNN:
    def _mask_batch(self, b=2, g=2, classes=4, size=64, mres=32, seed=0):
        batch = _batch(b, g, classes, size, seed)
        boxes = np.asarray(batch["gt_boxes"])
        # square rasters: fill each gt box's rectangle
        masks = np.zeros((b, g, mres, mres), np.float32)
        s = mres / size
        for i in range(b):
            for j in range(g):
                x1, y1, x2, y2 = (boxes[i, j] * s).astype(int)
                masks[i, j, y1:y2, x1:x2] = 1.0
        return (batch["image"], batch["gt_boxes"], batch["gt_labels"],
                batch["gt_mask"], jnp.asarray(masks))

    def test_loss_finite_and_mask_branch_learns(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.models.faster_rcnn import (FasterRCNNConfig,
                                                   MaskRCNN)

        cfg = FasterRCNNConfig.tiny()
        model = MaskRCNN(cfg)
        params = model.init(jax.random.PRNGKey(0))
        img, boxes, labels, valid, masks = self._mask_batch()

        @jax.jit
        def step(params, ostate):
            def loss(p):
                l, aux = model.loss(p, img, boxes, labels, valid, masks)
                return l, aux
            (l, aux), g = jax.value_and_grad(loss, has_aux=True)(params)
            params, ostate = tx.update(g, ostate, params)
            return params, ostate, l, aux["mask_loss"]

        tx = opt.Adam(learning_rate=2e-3)
        ostate = tx.init(params)
        ml = []
        for _ in range(8):
            params, ostate, l, m = step(params, ostate)
            assert np.isfinite(float(l))
            ml.append(float(m))
        assert ml[-1] < ml[0], ml   # the mask branch trains

    def test_segment_shapes_and_mask_gating(self):
        from paddle_tpu.models.faster_rcnn import (FasterRCNNConfig,
                                                   MaskRCNN)

        cfg = FasterRCNNConfig.tiny()
        model = MaskRCNN(cfg)
        params = model.init(jax.random.PRNGKey(0))
        img = jax.random.normal(jax.random.PRNGKey(2), (1, 64, 64, 3))
        boxes, classes, scores, ok, masks = model.segment(
            params, img, score_threshold=0.0)
        k = boxes.shape[1]
        res = model.mask_resolution
        assert masks.shape == (1, k, res, res)
        ok_np = np.asarray(ok)[0]
        m_np = np.asarray(masks)[0]
        # masks only where detections are valid; binary values
        assert set(np.unique(m_np)) <= {0.0, 1.0}
        if (~ok_np).any():
            assert m_np[~ok_np].sum() == 0.0


class TestDarkNetYOLO:
    def test_darknet53_features_strides(self):
        from paddle_tpu.models.legacy_cv import DarkNet53
        m = DarkNet53(num_classes=3, scale=0.25)
        p = m.init(jax.random.PRNGKey(0))
        x = jnp.zeros((1, 64, 64, 3))
        out, feats = m.features(p, x, endpoints=(13, 22, 27))
        assert feats[13].shape[1] == 8     # stride 8
        assert feats[22].shape[1] == 4     # stride 16
        assert feats[27].shape[1] == 2     # stride 32
        logits = m.forward(p, x)
        assert logits.shape == (1, 3)

    def test_yolov3_darknet_backbone_trains(self):
        from paddle_tpu import optimizer as opt
        from paddle_tpu.models.yolov3 import YOLOv3, YOLOv3Config
        from paddle_tpu.train import build_train_step, make_train_state

        cfg = YOLOv3Config(
            num_classes=4, image_size=64, backbone="darknet53",
            backbone_scale=0.125,
            anchors=((8, 8), (16, 16), (32, 32), (48, 48)),
            anchor_masks=((2, 3), (0, 1)),
            endpoints=(-1, 22))
        model = YOLOv3(cfg)
        rng = np.random.RandomState(0)
        ctr = rng.rand(2, 2, 2) * 0.5 + 0.25
        wh = rng.rand(2, 2, 2) * 0.3 + 0.2
        batch = dict(
            image=jnp.asarray(rng.randn(2, 64, 64, 3).astype(np.float32)),
            gt_boxes=jnp.asarray(
                np.concatenate([ctr, wh], -1).astype(np.float32)),
            gt_labels=jnp.asarray(rng.randint(0, 4, (2, 2))),
            gt_mask=jnp.ones((2, 2), bool))
        optimizer = opt.Adam(learning_rate=1e-3)
        step = jax.jit(build_train_step(
            lambda p, **b: model.loss(p, **b), optimizer))
        state = make_train_state(model, optimizer, jax.random.PRNGKey(0))
        losses = []
        for _ in range(5):
            state, m = step(state, **batch)
            losses.append(float(m["loss"]))
        assert np.isfinite(losses).all(), losses
        assert losses[-1] < losses[0], losses
