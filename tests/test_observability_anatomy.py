"""Step-time anatomy, resource headroom, crash flight recorder (ISSUE 16).

Three surfaces under test:

- :class:`StepAnatomy`: per-jitted-step wall-time decomposition (host
  gap / phase-split device busy / host assembly / sampled
  collective-exposed time) with a bounded ring, schema validators, and
  the metrics/trace fan-out;
- the resource-headroom plane: ``engine.health()["headroom"]`` (flops /
  pages / slots / HBM), separable across prefill-heavy vs decode-heavy
  workloads, aggregated fleet-wide by :class:`FleetMonitor` (which must
  also DROP a vanished replica's labeled series — the stale-gauge
  regression);
- :class:`FlightRecorder`: the bounded black box whose postmortem
  bundles the router dumps on eject / breaker-open, trace-id-linked to
  the victim requests and schema-validated end to end (CLI included).
"""

import json
import os
import sys
import time
import urllib.request

import jax
import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.observability import anatomy as anat
from paddle_tpu.observability import flight as flt
from paddle_tpu.serving import fleet
from paddle_tpu.serving.fleet.router import FleetMonitor
from paddle_tpu.models.gpt import GPT, GPTConfig

VOCAB = 64

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))


@pytest.fixture(scope="module")
def model_params():
    cfg = GPTConfig.tiny(vocab_size=VOCAB, hidden_size=16, num_layers=2,
                         num_heads=2, ffn_size=32, max_position=96,
                         dropout=0.0, attn_impl="xla")
    model = GPT(cfg)
    return model, model.init(jax.random.PRNGKey(0))


def _engine(model_params, tracer=None, **kw):
    model, params = model_params
    kw.setdefault("num_slots", 2)
    kw.setdefault("page_size", 4)
    # small on purpose: warmup compiles every reachable signature, and
    # this file builds four engines — keep the bucket set minimal
    kw.setdefault("max_tokens_per_slot", 16)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("decode_block", 2)
    return serving.ServingEngine(model, params, attn_impl="lax",
                                 registry=obs.MetricsRegistry(),
                                 tracer=tracer, **kw)


# ---------------------------------------------------------------------------
# StepAnatomy: pure host-side unit surface
# ---------------------------------------------------------------------------

class TestStepAnatomy:
    def test_record_schema_metrics_and_spans(self):
        reg = obs.MetricsRegistry()
        tracer = obs.Tracer(enabled=True)
        a = obs.StepAnatomy(registry=reg, tracer=tracer)
        a.begin_step(1)
        t = a.now()
        a.add_phase("prefill", t - 0.004, t - 0.003)
        a.add_phase("decode", t - 0.002, t - 0.0005)
        a.set_collective(0.0015, 0.0009)
        time.sleep(0.005)       # wall must cover the claimed phases
        rec = a.end_step(tokens=3)
        anat.validate_anatomy_record(rec)
        assert rec["step"] == 1 and rec["tokens"] == 3
        assert rec["phases"]["decode"] == pytest.approx(0.0015)
        assert rec["collective_exposed_s"] == pytest.approx(0.0006)
        assert reg.counter("anatomy_steps_total").value() == 1
        assert reg.histogram("anatomy_phase_seconds").summary(
            phase="decode")["count"] == 1
        names = {s.name for s in tracer.spans()}
        assert "anatomy.step" in names and "anatomy.decode" in names

    def test_ring_bounded_under_10k_steps(self):
        """The black-box discipline: 10k steps leave the ring at its
        capacity, the flight recorder's snapshot ring at its capacity,
        and the whole-run summary still exact."""
        a = obs.StepAnatomy(capacity=256)
        fr = obs.FlightRecorder("r", anatomy=a, capacity=64,
                                snapshot_every=8)
        for i in range(10_000):
            a.begin_step(i + 1)
            t = a.now()
            a.add_phase("decode", t, t)     # zero-width: wall-safe
            a.end_step(tokens=1)
            fr.note({"queue_depth": i})
        assert len(a) == 256
        recs = a.records()
        assert anat.validate_anatomy_records(recs) == 256
        assert recs[-1]["step"] == 10_000
        s = a.summary()
        assert s["steps"] == 10_000 and s["tokens"] == 10_000
        assert len(fr.snapshots()) == 64
        # the bundle ring is bounded too
        for _ in range(3 * flt.MAX_BUNDLES_KEPT):
            fr.dump("test")
        assert len(fr.bundles()) == flt.MAX_BUNDLES_KEPT

    def test_cancel_step_keeps_host_gap_honest(self):
        """Idle engine ticks (begin then cancel) must not count the
        idle wait as host gap on the next real step."""
        a = obs.StepAnatomy()
        a.begin_step()
        a.end_step()
        for _ in range(5):      # idle ticks
            a.begin_step()
            time.sleep(0.002)
            a.cancel_step()
        a.begin_step()
        rec = a.end_step()
        assert rec["host_gap_s"] < 0.002
        assert a.summary()["steps"] == 2

    def test_validators_reject_malformed(self, tmp_path):
        a = obs.StepAnatomy()
        a.begin_step(5)
        good = a.end_step()
        bad_kind = dict(good, kind="step")
        with pytest.raises(ValueError, match="kind"):
            anat.validate_anatomy_record(bad_kind)
        with pytest.raises(ValueError, match="monotonic"):
            anat.validate_anatomy_record(good, prev_step=7)
        overfull = dict(good, phases={"decode": good["wall_s"] + 1.0})
        with pytest.raises(ValueError, match="exceeds wall"):
            anat.validate_anatomy_record(overfull)
        with pytest.raises(ValueError, match="negative|nonneg|>= 0"):
            anat.validate_anatomy_record(dict(good, host_gap_s=-1.0))
        p = tmp_path / "anat.jsonl"
        a.export_jsonl(str(p))
        assert anat.validate_anatomy_log(str(p), require_steps=1) == 1
        with pytest.raises(ValueError):
            anat.validate_anatomy_log(str(p), require_steps=2)


# ---------------------------------------------------------------------------
# FlightRecorder: bundles, files, CLI
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def _bundle(self):
        reg = obs.MetricsRegistry()
        tracer = obs.Tracer(enabled=True)
        a = obs.StepAnatomy(registry=reg, tracer=tracer)
        fr = obs.FlightRecorder("rX", anatomy=a, registry=reg,
                                tracer=tracer, snapshot_every=1)
        for i in range(4):
            a.begin_step(i + 1)
            a.end_step(tokens=2)
            fr.note({"queue_depth": i, "requests_in_flight": 1,
                     "slot_occupancy": 0.5,
                     "headroom": {"flops": 0.5, "pages": 0.5,
                                  "slots": 0.5, "hbm": 0.5}})
        return fr.dump("eject", trace_ids=(7, 3, 7),
                       extra={"cause": "crashed"})

    def test_dump_roundtrip_and_validation(self, tmp_path):
        b = self._bundle()
        obs.validate_postmortem_bundle(b)
        assert b["schema"] == obs.POSTMORTEM_SCHEMA
        assert b["replica"] == "rX" and b["reason"] == "eject"
        assert b["trace_ids"] == [3, 7]         # deduped, sorted
        assert len(b["snapshots"]) == 4
        assert anat.validate_anatomy_records(b["anatomy"]) == 4
        p = str(tmp_path / "pm.json")
        obs.write_bundle(b, p)
        got = obs.validate_postmortem_file(p)
        assert got["trace_ids"] == [3, 7]
        with pytest.raises(ValueError, match="schema"):
            obs.validate_postmortem_bundle(dict(b, schema="nope"))
        with pytest.raises(ValueError, match="reason"):
            obs.validate_postmortem_bundle(dict(b, reason=""))

    def test_cli_anatomy_and_postmortem_modes(self, tmp_path):
        from check_metrics_log import main as check_main
        a = obs.StepAnatomy()
        for i in range(3):
            a.begin_step(i + 1)
            a.end_step()
        alog = str(tmp_path / "a.jsonl")
        a.export_jsonl(alog)
        assert check_main([alog, "--anatomy", "--require-steps", "3"]) == 0
        assert check_main([alog, "--anatomy", "--require-steps", "9"]) == 1
        p = str(tmp_path / "pm.json")
        obs.write_bundle(self._bundle(), p)
        assert check_main([p, "--postmortem"]) == 0
        with pytest.raises(SystemExit):    # exclusive modes fail fast
            check_main([p, "--postmortem", "--anatomy"])
        with pytest.raises(SystemExit):
            check_main([p, "--postmortem", "--require-steps", "1"])

    def test_offline_renderer(self, tmp_path, capsys):
        from postmortem import main as pm_main
        p = str(tmp_path / "pm.json")
        obs.write_bundle(self._bundle(), p)
        # NOT .json: directory mode below globs *.json as bundles
        trace_out = str(tmp_path / "trace.out")
        assert pm_main([p, "--trace-out", trace_out]) == 0
        out = capsys.readouterr().out
        assert "reason=eject" in out and "trace ids [3, 7]" in out
        obs.chrome_trace_valid(json.load(open(trace_out)))
        # a directory of bundles renders too; an invalid one fails
        assert pm_main([str(tmp_path)]) == 0
        with open(str(tmp_path / "bad.json"), "w") as f:
            json.dump({"schema": "nope"}, f)
        assert pm_main([str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# registry series removal (the FleetMonitor stale-gauge contract)
# ---------------------------------------------------------------------------

class TestSeriesRemoval:
    def test_remove_and_remove_matching(self):
        reg = obs.MetricsRegistry()
        g = reg.gauge("g", "h")
        g.set(1.0, replica="a", resource="pages")
        g.set(2.0, replica="a", resource="flops")
        g.set(3.0, replica="b", resource="pages")
        assert g.remove(replica="a", resource="flops") is True
        assert g.remove(replica="a", resource="flops") is False
        assert g.remove_matching(replica="a") == 1
        assert [dict(k)["replica"] for k in g.labels_seen()] == ["b"]
        assert g.remove_matching(replica="zzz") == 0


class TestAutoscalerHeadroomFloor:
    def _auto(self, floor, pages):
        a = fleet.FleetAutoscaler(lambda i: None, headroom_floor=floor,
                                  registry=obs.MetricsRegistry())

        class _R:
            replicas = [object()]

            @staticmethod
            def health():
                return {"queue_depth_total": 0,
                        "slot_occupancy_mean": 0.0,
                        "per_replica": {"r0": {"headroom": {
                            "pages": pages, "slots": 1.0, "hbm": 1.0}}}}

        a.bind(_R())
        return a

    def test_floor_vetoes_idle_scale_in(self):
        """A replica still pinning KV pages is not idle, however empty
        its occupancy reads — but only when the operator opted into the
        floor (default 0.0 keeps pure-occupancy scale-in timing)."""
        assert self._auto(0.5, pages=0.2)._fleet_idle() is False
        assert self._auto(0.5, pages=0.9)._fleet_idle() is True
        assert self._auto(0.0, pages=0.2)._fleet_idle() is True


# ---------------------------------------------------------------------------
# engine integration: anatomy + headroom on the real serving loop
# ---------------------------------------------------------------------------

class TestEngineAnatomy:
    @pytest.fixture(scope="class")
    def eng(self, model_params):
        e = _engine(model_params)
        e.warmup()              # cost gauges on: the flops plane is live
        return e

    def test_anatomy_records_and_report(self, model_params, eng):
        rng = np.random.default_rng(0)
        prompts = [rng.integers(1, VOCAB, n).astype(np.int32)
                   for n in (5, 9, 7)]
        outs = eng.generate_many(prompts, 6, eos_id=None)
        assert all(len(np.asarray(o)) == 6 for o in outs)
        recs = eng.anatomy.records()
        assert recs and anat.validate_anatomy_records(recs) >= 1
        s = eng.anatomy.summary()
        assert s["phase_s"].get("prefill", 0) > 0
        assert s["phase_s"].get("decode", 0) > 0
        assert 0.0 <= s["host_gap_frac"] <= 1.0
        assert eng.recompile_detector.recompiles == 0
        eng.health()        # refreshes the headroom gauges the report reads
        text = obs.report(eng._reg, eng.tracer)
        assert "-- anatomy --" in text
        assert "phase_split" in text and "headroom" in text

    def test_headroom_idle_vs_mid_flight(self, eng):
        """Mid-decode the page/slot/HBM headroom must read the held
        resources; at idle everything returns to 1.0 while the flops
        plane keeps its utilization estimate."""
        h = eng.health()["headroom"]
        assert h["pages"] == 1.0 and h["slots"] == 1.0 and h["hbm"] == 1.0
        assert h["flops_utilization"] > 0.0     # the busy run above
        assert 0.0 <= h["flops"] < 1.0
        rng = np.random.default_rng(1)
        rids = [eng.submit(rng.integers(1, VOCAB, 6).astype(np.int32), 8,
                           eos_id=None) for _ in range(2)]
        collected = {}
        for _ in range(200):
            collected.update(eng.step())
            if eng.scheduler.decode_slots():
                break
        mid = eng.health()["headroom"]
        assert mid["slots"] == 0.0              # both slots held
        assert mid["pages"] < 1.0 and mid["hbm"] < 1.0
        assert mid["hbm_live_bytes"] > 0
        assert mid["hbm_capacity_bytes"] == \
            eng.cache.capacity_bytes()
        reg_val = eng._reg.get("serving_headroom").value(resource="pages")
        assert reg_val == mid["pages"]
        while not eng.scheduler.idle():
            collected.update(eng.step())
        assert set(rids) <= set(collected)
        end = eng.health()["headroom"]
        assert end["pages"] == 1.0 and end["slots"] == 1.0 \
            and end["hbm"] == 1.0

    def test_phase_split_separates_workloads(self, eng):
        """Prefill-heavy traffic (long prompts, 1 new token) moves the
        phase split toward prefill; decode-heavy traffic (short prompt,
        long generation) moves it toward decode — the anatomy must make
        the two regimes distinguishable from the totals alone."""
        rng = np.random.default_rng(2)
        base = dict(eng.anatomy.summary()["phase_s"])

        def delta(prev):
            cur = eng.anatomy.summary()["phase_s"]
            return {p: cur.get(p, 0.0) - prev.get(p, 0.0) for p in cur}

        long_prompts = [rng.integers(1, VOCAB, 12).astype(np.int32)
                        for _ in range(4)]
        eng.generate_many(long_prompts, 1, eos_id=None)
        d_pre = delta(base)
        assert d_pre["prefill"] > d_pre.get("decode", 0.0)

        base2 = dict(eng.anatomy.summary()["phase_s"])
        short = [rng.integers(1, VOCAB, 4).astype(np.int32)
                 for _ in range(2)]
        eng.generate_many(short, 12, eos_id=None)
        d_dec = delta(base2)
        assert d_dec["decode"] > d_dec.get("prefill", 0.0)


# ---------------------------------------------------------------------------
# tp=2: the collective-exposed probe (zero-recompile discipline)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(len(jax.devices()) < 4,
                    reason="tp tests need >= 4 (virtual) devices")
class TestTpCollectiveProbe:
    def test_probe_samples_without_recompiles(self, model_params):
        eng = _engine(model_params, tp=2, anatomy_probe_every=2)
        # the probe signatures are first-class citizens of the warmup
        # contract: planned AND reachable (the set-equality invariant)
        plan = set(eng.warmup_plan())
        assert plan == set(eng.reachable_signatures())
        assert any(sig[0] == "decode_probe" for sig in plan)
        eng.warmup()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, VOCAB, n).astype(np.int32)
                   for n in (5, 9)]
        outs = eng.generate_many(prompts, 6, eos_id=None)
        assert all(len(np.asarray(o)) == 6 for o in outs)
        s = eng.anatomy.summary()
        assert s["probe_samples"] >= 1
        assert s["collective_exposed_s"] >= 0.0
        assert 0.0 <= s["collective_exposed_frac"] <= 1.0
        assert eng.recompile_detector.recompiles == 0
        h = eng.health()["headroom"]
        assert set(h) >= {"flops", "pages", "slots", "hbm"}

    def test_probe_off_for_unsharded_engines(self, model_params):
        eng = _engine(model_params)
        assert eng.anatomy_probe_every == 0
        assert not any(sig[0] == "decode_probe"
                       for sig in eng.warmup_plan())


# ---------------------------------------------------------------------------
# fleet: crash -> postmortem with victim trace ids; stale series dropped;
# headroom aggregated; /debug/postmortem served
# ---------------------------------------------------------------------------

class TestFleetFlightRecorder:
    @pytest.fixture(scope="class")
    def crashed_fleet(self, model_params, tmp_path_factory):
        tracer = obs.Tracer(enabled=True)
        reps = [fleet.LocalReplica(
            _engine(model_params, tracer=tracer), name=f"r{i}").warmup()
            for i in range(2)]
        assert reps[0].engine.flight.name == "r0"
        chaos = fleet.ChaosReplica(reps[1], crash_on_step=3)
        reg = obs.MetricsRegistry()
        pm_dir = str(tmp_path_factory.mktemp("pm"))
        router = fleet.FleetRouter(
            [reps[0], chaos], registry=reg, tracer=tracer, seed=0,
            faults=fleet.FaultPolicy(max_consecutive_failures=1,
                                     probe_timeout_s=30.0),
            postmortem_dir=pm_dir)
        mon = FleetMonitor(router)
        rng = np.random.default_rng(4)
        frids = [router.submit(rng.integers(1, VOCAB, 6).astype(np.int32),
                               8) for _ in range(6)]
        tids = {router.trace_id(f) for f in frids}
        steps = 0
        while not router.idle():
            router.step()
            mon.collect()
            steps += 1
            assert steps < 5000, "fleet did not converge"
        return router, mon, reg, frids, tids, pm_dir

    def test_eject_ships_linked_postmortem(self, crashed_fleet):
        router, _mon, _reg, frids, tids, pm_dir = crashed_fleet
        assert router.ejected_total == 1
        bundles = router.postmortems()
        assert len(bundles) == 1
        b = bundles[0]
        obs.validate_postmortem_bundle(b)
        assert b["reason"] == "eject" and b["replica"] == "r1"
        assert b["extra"]["cause"].startswith("crashed")
        # the bundle's trace ids ARE the victims': every one was minted
        # by the router for a request that was on board at the crash
        assert b["trace_ids"] and set(b["trace_ids"]) <= tids
        # and the on-disk artifact validates standalone
        files = sorted(os.listdir(pm_dir))
        assert len(files) == 1 and "r1" in files[0]
        obs.validate_postmortem_file(os.path.join(pm_dir, files[0]))
        # no silent loss alongside: every request ends with a result
        for f in frids:
            assert router.result(f) is not None \
                or router.reject_reason(f) is not None

    def test_stale_replica_series_dropped(self, crashed_fleet):
        """The regression: after an eject the monitor must REMOVE the
        dead replica's labeled series, not freeze them at their last
        values."""
        _router, mon, reg, *_ = crashed_fleet
        mon.collect()
        for mname in FleetMonitor._PER_REPLICA_METRICS:
            m = reg.get(mname)
            if m is None:
                continue
            names = {dict(k).get("replica") for k in m.labels_seen()}
            assert "r1" not in names, (mname, names)
        # the survivor's series stay live
        occ = reg.get("fleet_replica_slot_occupancy")
        assert {dict(k)["replica"] for k in occ.labels_seen()} == {"r0"}

    def test_headroom_aggregated_and_served(self, crashed_fleet):
        router, mon, reg, *_ = crashed_fleet
        h = mon.collect()
        assert set(h["headroom"]) == {"flops", "pages", "slots", "hbm",
                                      "spill"}
        assert h["headroom"]["pages"] == 1.0        # fleet is idle now
        g = reg.get("fleet_headroom_min")
        assert g.value(resource="slots") == h["headroom"]["slots"]
        pr = reg.get("fleet_replica_headroom")
        assert pr.value(replica="r0", resource="pages") == 1.0
        assert router.health()["postmortems"] == 1
        srv = mon.start_exposition()
        try:
            payload = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/debug/postmortem",
                timeout=10).read())
            assert payload["count"] == 1
            obs.validate_postmortem_bundle(payload["bundles"][0])
            # ?replica filters by PROVIDER name (the fleet registers one
            # provider for the whole router)
            one = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}"
                "/debug/postmortem?replica=fleet&limit=1",
                timeout=10).read())
            assert one["count"] == 1
            none = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}"
                "/debug/postmortem?replica=nope",
                timeout=10).read())
            assert none["count"] == 0
        finally:
            srv.stop()
