"""Root-op breadth: long-tail ops without numpy registry references
(norms, spatial rearrangers, STN pair, random ops, PS id localization)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import nn as N
from paddle_tpu.ops import tensor as T

RNG = np.random.RandomState(7)


def randn(*s):
    return RNG.randn(*s).astype(np.float32)


class TestNorms:
    def test_group_norm_matches_manual(self):
        x = randn(2, 4, 4, 8)
        out = N.group_norm(jnp.asarray(x), groups=4)
        g = x.reshape(2, 4, 4, 4, 2)
        mean = g.mean(axis=(1, 2, 4), keepdims=True)
        var = g.var(axis=(1, 2, 4), keepdims=True)
        ref = ((g - mean) / np.sqrt(var + 1e-5)).reshape(2, 4, 4, 8)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                                   atol=1e-4)

    def test_instance_norm_zero_mean_unit_var(self):
        x = randn(2, 6, 6, 3) * 5 + 2
        out = np.asarray(N.instance_norm(jnp.asarray(x)))
        np.testing.assert_allclose(out.mean(axis=(1, 2)), 0.0, atol=1e-4)
        np.testing.assert_allclose(out.var(axis=(1, 2)), 1.0, atol=1e-2)

    def test_group_norm_nchw_roundtrip(self):
        x = randn(2, 8, 4, 4)
        out = N.group_norm(jnp.asarray(x), groups=2, data_format="NCHW")
        ref = N.group_norm(jnp.asarray(x.transpose(0, 2, 3, 1)), groups=2)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(ref).transpose(0, 3, 1, 2),
                                   rtol=1e-5)

    def test_lrn_matches_manual(self):
        x = randn(1, 2, 2, 6)
        out = np.asarray(N.lrn(jnp.asarray(x), n=3, k=1.0, alpha=0.1,
                               beta=0.5))
        sq = np.pad(x * x, [(0, 0)] * 3 + [(1, 1)])
        win = sum(sq[..., i:i + 6] for i in range(3))
        ref = x / np.power(1.0 + 0.1 * win, 0.5)
        np.testing.assert_allclose(out, ref, rtol=1e-5)


class TestSpatial:
    def test_maxout(self):
        x = jnp.asarray([[1.0, 5.0, 2.0, 8.0]])
        np.testing.assert_allclose(
            np.asarray(N.maxout(x, groups=2)), [[5.0, 8.0]])

    def test_pad2d_modes(self):
        x = jnp.asarray(randn(1, 2, 2, 1))
        c = N.pad2d(x, (1, 1, 1, 1), mode="constant", pad_value=9.0)
        assert c.shape == (1, 4, 4, 1) and float(c[0, 0, 0, 0]) == 9.0
        r = N.pad2d(x, (1, 0, 0, 0), mode="reflect")
        np.testing.assert_allclose(np.asarray(r[0, 0]),
                                   np.asarray(x[0, 1]))
        e = N.pad2d(x, (1, 0, 0, 0), mode="edge")
        np.testing.assert_allclose(np.asarray(e[0, 0]),
                                   np.asarray(x[0, 0]))

    def test_pixel_shuffle_inverts_space_to_depth(self):
        x = randn(2, 12, 4, 4)
        out = np.asarray(T.pixel_shuffle(jnp.asarray(x), 2))
        assert out.shape == (2, 3, 8, 8)
        # element mapping: out[n, c, h*r+i, w*r+j] == x[n, c*r^2 + i*r + j, h, w]
        assert out[0, 1, 2 * 2 + 1, 3 * 2] == pytest.approx(
            x[0, 1 * 4 + 1 * 2 + 0, 2, 3])

    def test_shuffle_channel_roundtrip(self):
        x = randn(1, 6, 2, 2)
        once = T.shuffle_channel(jnp.asarray(x), 2)
        back = T.shuffle_channel(once, 3)   # inverse group count
        np.testing.assert_allclose(np.asarray(back), x)

    def test_temporal_shift_moves_frames(self):
        x = randn(4, 4, 2, 2)  # n=2 t=2 c=4
        out = np.asarray(T.temporal_shift(jnp.asarray(x), seg_num=2,
                                          shift_ratio=0.25))
        xs = x.reshape(2, 2, 4, 2, 2)
        os_ = out.reshape(2, 2, 4, 2, 2)
        # channel 0 shifted backward: frame 0 sees frame 1
        np.testing.assert_allclose(os_[:, 0, 0], xs[:, 1, 0])
        np.testing.assert_allclose(os_[:, 1, 0], 0.0)
        # channel 1 shifted forward
        np.testing.assert_allclose(os_[:, 1, 1], xs[:, 0, 1])
        # remaining channels unchanged
        np.testing.assert_allclose(os_[:, :, 2:], xs[:, :, 2:])

    def test_unfold_reassembles_patches(self):
        x = randn(1, 2, 4, 4)
        out = np.asarray(T.unfold(jnp.asarray(x), kernel_size=2, stride=2))
        assert out.shape == (1, 2 * 4, 4)
        # first output column = top-left 2x2 patch, channel-major
        patch = x[0, :, 0:2, 0:2].reshape(2, 4)  # (C, kh*kw)
        np.testing.assert_allclose(out[0, :, 0], patch.reshape(-1))

    def test_crop(self):
        x = jnp.asarray(np.arange(16.0).reshape(4, 4))
        out = T.crop(x, (1, 2), (2, 2))
        np.testing.assert_allclose(np.asarray(out), [[6, 7], [10, 11]])


class TestSTN:
    def test_affine_grid_identity_plus_sampler(self):
        """Identity theta -> grid_sampler reproduces the input (the STN
        composition affine_grid + grid_sampler end to end)."""
        theta = jnp.asarray([[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]]])
        x = jnp.asarray(randn(1, 3, 5, 5))
        grid = N.affine_grid(theta, (1, 3, 5, 5))
        out = N.grid_sampler(x, grid)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x),
                                   rtol=1e-5, atol=1e-5)

    def test_affine_grid_translation(self):
        # shift +2/(W-1)*2 in normalized x = one pixel right sample
        theta = jnp.asarray([[[1.0, 0.0, 0.5], [0.0, 1.0, 0.0]]])
        x = jnp.asarray(randn(1, 1, 4, 5))
        out = N.grid_sampler(x, N.affine_grid(theta, (1, 1, 4, 5)))
        np.testing.assert_allclose(np.asarray(out[0, 0, :, 0]),
                                   np.asarray(x[0, 0, :, 1]), rtol=1e-5)


class TestMisc:
    def test_cos_sim(self):
        x, y = randn(3, 4), randn(3, 4)
        out = np.asarray(N.cos_sim(jnp.asarray(x), jnp.asarray(y)))
        ref = (x * y).sum(-1) / (np.linalg.norm(x, axis=-1)
                                 * np.linalg.norm(y, axis=-1))
        np.testing.assert_allclose(out[:, 0], ref, rtol=1e-5)

    def test_bilinear_tensor_product(self):
        x, y = randn(2, 3), randn(2, 4)
        w = randn(5, 3, 4)
        out = np.asarray(N.bilinear_tensor_product(
            jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)))
        ref = np.einsum("bm,kmn,bn->bk", x, w, y)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_shard_index(self):
        ids = jnp.asarray([0, 5, 10, 15])
        out = T.shard_index(ids, index_num=16, nshards=4, shard_id=1)
        np.testing.assert_array_equal(np.asarray(out), [-1, 1, -1, -1])

    def test_unique_nonzero_meshgrid(self):
        u, c = T.unique(jnp.asarray([3, 1, 3, 2]), return_counts=True)
        np.testing.assert_array_equal(np.asarray(u), [1, 2, 3])
        np.testing.assert_array_equal(np.asarray(c), [1, 1, 2])
        nz = T.nonzero(jnp.asarray([[0, 1], [2, 0]]))
        np.testing.assert_array_equal(np.asarray(nz), [[0, 1], [1, 0]])
        gx, gy = T.meshgrid(jnp.arange(2), jnp.arange(3))
        assert gx.shape == (2, 3)

    def test_random_ops_functional(self):
        k = jax.random.PRNGKey(0)
        g = T.gaussian_random(k, (1000,), mean=2.0, std=0.5)
        assert abs(float(g.mean()) - 2.0) < 0.1
        u = T.uniform_random(k, (1000,), min=0.0, max=1.0)
        assert 0.0 <= float(u.min()) and float(u.max()) <= 1.0
        r = T.randint(k, 0, 10, (100,))
        assert 0 <= int(r.min()) and int(r.max()) < 10
        p = np.asarray(T.randperm(k, 10))
        np.testing.assert_array_equal(np.sort(p), np.arange(10))
