"""Sequence-op long tail + WMT loader tests (operators/sequence_ops/
breadth; python/paddle/dataset/wmt16 parse path)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.ops import sequence as S


class TestSequenceConv:
    def test_matches_numpy(self):
        rng = np.random.default_rng(0)
        b, t, d, f, ctx = 3, 6, 4, 5, 3
        x = rng.normal(size=(b, t, d)).astype(np.float32)
        lengths = np.array([6, 4, 2])
        w = rng.normal(size=(ctx * d, f)).astype(np.float32)
        start = -1

        ref = np.zeros((b, t, f), np.float32)
        for bi in range(b):
            for ti in range(lengths[bi]):
                cat = []
                for j in range(ctx):
                    src = ti + start + j
                    if 0 <= src < lengths[bi]:
                        cat.append(x[bi, src])
                    else:
                        cat.append(np.zeros(d, np.float32))
                ref[bi, ti] = np.concatenate(cat) @ w
        out = S.sequence_conv(jnp.asarray(x), jnp.asarray(lengths),
                              jnp.asarray(w), context_start=start)
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5,
                                   atol=1e-5)


class TestSequenceSlice:
    def test_2d(self):
        x = jnp.asarray([[1, 2, 3, 4, 5], [6, 7, 8, 0, 0]])
        lengths = jnp.asarray([5, 3])
        out, nl = S.sequence_slice(x, lengths, jnp.asarray([1, 0]),
                                   jnp.asarray([3, 2]))
        np.testing.assert_array_equal(np.asarray(out),
                                      [[2, 3, 4, 0, 0], [6, 7, 0, 0, 0]])
        np.testing.assert_array_equal(np.asarray(nl), [3, 2])

    def test_clamps_to_row_length(self):
        x = jnp.asarray([[1, 2, 3, 0]])
        out, nl = S.sequence_slice(x, jnp.asarray([3]), jnp.asarray([2]),
                                   jnp.asarray([4]))
        np.testing.assert_array_equal(np.asarray(out), [[3, 0, 0, 0]])
        assert int(nl[0]) == 1

    def test_3d(self):
        x = jnp.arange(24.0).reshape(2, 3, 4)
        out, nl = S.sequence_slice(x, jnp.asarray([3, 3]),
                                   jnp.asarray([1, 0]),
                                   jnp.asarray([2, 1]))
        np.testing.assert_allclose(np.asarray(out)[0, 0], np.asarray(x)[0, 1])
        assert np.allclose(np.asarray(out)[0, 2], 0.0)


class TestSequenceErase:
    def test_erase_and_compact(self):
        x = jnp.asarray([[2, 1, 2, 3, 0], [5, 5, 5, 0, 0]])
        lengths = jnp.asarray([4, 3])
        out, nl = S.sequence_erase(x, lengths, [2, 5])
        np.testing.assert_array_equal(np.asarray(out),
                                      [[1, 3, 0, 0, 0], [0, 0, 0, 0, 0]])
        np.testing.assert_array_equal(np.asarray(nl), [2, 0])

    def test_padding_not_counted(self):
        # pad value 0 is outside every valid prefix; erasing 0 is a no-op
        x = jnp.asarray([[1, 2, 0, 0]])
        out, nl = S.sequence_erase(x, jnp.asarray([2]), [0])
        np.testing.assert_array_equal(np.asarray(out), [[1, 2, 0, 0]])
        assert int(nl[0]) == 2


class TestSequenceEnumerate:
    def test_windows(self):
        x = jnp.asarray([[1, 2, 3, 4]])
        out = S.sequence_enumerate(x, jnp.asarray([3]), 2, pad_value=9)
        np.testing.assert_array_equal(
            np.asarray(out)[0], [[1, 2], [2, 3], [3, 9], [9, 9]])


class TestSequenceConcat:
    def test_ragged_concat(self):
        x = jnp.asarray([[1, 2, 0], [3, 0, 0]])
        y = jnp.asarray([[7, 8], [9, 0]])
        out, nl = S.sequence_concat(x, jnp.asarray([2, 1]), y,
                                    jnp.asarray([2, 1]))
        np.testing.assert_array_equal(np.asarray(out),
                                      [[1, 2, 7, 8, 0], [3, 9, 0, 0, 0]])
        np.testing.assert_array_equal(np.asarray(nl), [4, 2])


class TestWmtLoader:
    def test_parallel_reader(self, tmp_path):
        from paddle_tpu.data.datasets import wmt_build_dict, wmt_parallel

        (tmp_path / "train.en").write_text("a b c\nb c\n")
        (tmp_path / "train.de").write_text("x y\ny z w\n")
        reader = wmt_parallel(str(tmp_path))
        pairs = list(reader())
        assert len(pairs) == 2
        s0, t0 = pairs[0]
        assert s0.dtype == np.int64 and len(s0) == 3 and len(t0) == 2
        # vocab is frequency-sorted: 'b'/'c' (2x) before 'a' (1x)
        d = wmt_build_dict([str(tmp_path / "train.en")])
        assert d["b"] < d["a"] and d["c"] < d["a"]
        assert "<unk>" in d

    def test_missing_files(self, tmp_path):
        from paddle_tpu.data.datasets import wmt_parallel

        with pytest.raises(FileNotFoundError, match="stage"):
            wmt_parallel(str(tmp_path))
