"""Benchmark: BERT-base pretraining throughput on the attached device.

Prints ONE JSON line:
  {"metric": "bert_base_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": MFU/0.35, ...}

The baseline is the driver-set north star (BASELINE.json): BERT-base at
>=35% MFU. ``vs_baseline`` therefore reports achieved-MFU / 0.35 so that
1.0 == target met. MFU uses the standard 6N + 12*L*S*d transformer
FLOPs-per-token estimate against the device's peak matmul FLOPs.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


# peak bf16 matmul FLOPs per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}


def device_peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak
    if dev.platform == "tpu":
        return 197e12  # conservative default: v5e-class
    return 1e12  # CPU smoke-run placeholder


def count_params(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def main():
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import dtypes
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.train import build_train_step, make_train_state

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    cfg = BertConfig.base(dropout=0.0, attn_dropout=0.0)
    seq = 512
    batch_size = 48 if on_tpu else 2  # swept: 48 > 32 > 8 on v5e
    steps = 20 if on_tpu else 3
    if not on_tpu:  # CPU smoke config: keep the same code path, tiny model
        cfg = BertConfig.tiny(dropout=0.0, attn_dropout=0.0, attn_impl="xla")
        seq = 64

    model = BertForPretraining(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4)
    state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

    def loss_fn(params, **batch):
        return model.loss(params, training=False, **batch)

    policy = dtypes.get_policy("bf16") if on_tpu else None
    step = jax.jit(build_train_step(loss_fn, optimizer, policy=policy),
                   donate_argnums=(0,))

    key = jax.random.PRNGKey(1)
    batch = dict(
        input_ids=jax.random.randint(key, (batch_size, seq), 0,
                                     cfg.vocab_size, jnp.int32),
        token_type_ids=jnp.zeros((batch_size, seq), jnp.int32),
        attention_mask=jnp.ones((batch_size, seq), bool),
        mlm_labels=jax.random.randint(key, (batch_size, seq), 0,
                                      cfg.vocab_size, jnp.int32),
        mlm_mask=(jax.random.uniform(key, (batch_size, seq)) < 0.15
                  ).astype(jnp.float32),
        nsp_labels=jnp.zeros((batch_size,), jnp.int32),
    )

    # warmup (compile). Sync via host transfer of the loss — NOT
    # block_until_ready, which does not wait through proxied-device
    # transports (observed on the axon TPU tunnel).
    state, metrics = step(state, **batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, **batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * seq
    tokens_per_sec = tokens_per_step * steps / dt

    n_params = count_params(state["params"])
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * seq * cfg.hidden_size
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / device_peak_flops(dev)

    print(json.dumps({
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu": round(mfu, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "batch_size": batch_size,
        "seq_len": seq,
        "params": n_params,
        "loss": round(final_loss, 4),
    }))


if __name__ == "__main__":
    main()
