"""Benchmark: BERT-base pretraining throughput on the attached device.

Prints ONE JSON line:
  {"metric": "bert_base_tokens_per_sec_per_chip", "value": N,
   "unit": "tokens/s/chip", "vs_baseline": MFU/0.35, ...}

The baseline is the driver-set north star (BASELINE.json): BERT-base at
>=35% MFU. ``vs_baseline`` therefore reports achieved-MFU / 0.35 so that
1.0 == target met. MFU uses the standard 6N + 12*L*S*d transformer
FLOPs-per-token estimate against the device's peak matmul FLOPs.

Resilience: the axon TPU tunnel can be transiently UNAVAILABLE (observed
round 1: backend init failed and the bench recorded rc=1, nothing else).
Backend acquisition is therefore a bounded retry/backoff loop, falling back
to a CPU smoke run, and ANY failure still emits a JSON line with an
"error" field and exits 0.
"""

from __future__ import annotations

import json
import os
import sys
import time

# the serving_tp bench shards over virtual CPU devices. Gate the flag on
# that model: the other benches' committed numbers were measured on the
# default single-device CPU topology, and a global 8-virtual-device
# split would silently change what they run on
if "serving_tp" in sys.argv and \
        "xla_force_host_platform_device_count" not in os.environ.get(
            "XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp


# peak bf16 matmul FLOPs per chip by device kind (public spec sheets)
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5e": 197e12,
    "TPU v5 lite": 197e12,
    "TPU v5p": 459e12,
    "TPU v6e": 918e12,
    "TPU v6 lite": 918e12,
}

# retry schedule for backend init (seconds between attempts; ~2.5 min total)
BACKOFFS = [2, 5, 10, 20, 40, 60]


def device_peak_flops(dev) -> float:
    kind = getattr(dev, "device_kind", "")
    for name, peak in PEAK_FLOPS.items():
        if kind.lower().startswith(name.lower()):
            return peak
    if dev.platform == "tpu":
        return 197e12  # conservative default: v5e-class
    return 1e12  # CPU smoke-run placeholder


def count_params(tree) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def metrics_log_path() -> str:
    """Where this bench run's JSONL telemetry goes (observability.runlog
    schema). Overridable so CI/scrapers can collect it."""
    import os
    return os.environ.get("PADDLE_TPU_METRICS_LOG",
                          "/tmp/paddle_tpu_bench_metrics.jsonl")


def write_bench_telemetry(result: dict) -> str | None:
    """Emit the bench run through the observability subsystem: one JSONL
    step record per timed step (same numbers as the stdout JSON), a
    summary record, registry gauges, and a Prometheus exposition dump
    next to the log. Then schema-validate the log by INVOKING
    tools/check_metrics_log.py — malformed telemetry fails the bench
    (an 'error' field in the JSON line) instead of polluting BENCH_*.

    Returns the log path, or None when the bench produced no telemetry
    (error runs)."""
    import os
    import subprocess

    from paddle_tpu import observability as obs

    tel = result.pop("_telemetry", None)
    if tel is None:
        return None
    path = metrics_log_path()
    try:
        if os.path.exists(path):
            os.remove(path)  # one bench run == one log
    except OSError:
        pass
    steps = max(int(tel["steps"]), 1)
    dt = float(tel["dt"])
    per_step = dt / steps
    ex = float(tel.get("examples_per_step", 0.0))
    tok = tel.get("tokens_per_step")
    with obs.RunLogWriter(path, meta={"bench": result.get("metric")}) as w:
        for i in range(steps):
            rec = {"step": i + 1,
                   "step_time_s": round(per_step, 6),
                   "examples_per_sec": round(ex / per_step, 3),
                   "compiles_cum": obs.compile_count()}
            if tok:
                rec["tokens_per_sec"] = round(tok / per_step, 3)
            w.write(rec)
        w.write({"kind": "summary", "metric": result.get("metric"),
                 "value": result.get("value"),
                 "vs_baseline": result.get("vs_baseline")})
    g = obs.gauge("bench_value", "headline bench metric value")
    g.set(float(result.get("value") or 0.0),
          metric=str(result.get("metric")))
    obs.gauge("bench_vs_baseline").set(
        float(result.get("vs_baseline") or 0.0),
        metric=str(result.get("metric")))
    with open(path + ".prom", "w") as f:
        f.write(obs.render_prometheus())
    check = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools", "check_metrics_log.py")
    proc = subprocess.run(
        [sys.executable, check, path, "--require-steps", str(steps)],
        capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"bench telemetry failed validation: {proc.stderr.strip()}")
    return path


def _probe_backend(timeout: float) -> str | None:
    """Try TPU backend init in a SUBPROCESS with a hard timeout.

    jax.devices() can HANG (not raise) when the axon tunnel is down, and a
    blocked C call can't be interrupted in-process — so probe out-of-process
    first. Returns None on success, error string on failure.
    """
    import subprocess
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform == 'tpu'"],
            timeout=timeout, capture_output=True, text=True)
        if r.returncode == 0:
            return None
        return f"probe rc={r.returncode}: {(r.stderr or '').strip()[-300:]}"
    except subprocess.TimeoutExpired:
        return f"probe hung >{timeout:.0f}s (axon tunnel unresponsive)"


def acquire_device():
    """Get a device with bounded retry/backoff; CPU fallback as last resort.

    Returns (device, error_string_or_None). error is set when the TPU never
    came up and we degraded to CPU.

    An explicit JAX_PLATFORMS=cpu skips the TPU probe entirely (local
    smoke runs shouldn't wait out the tunnel-retry schedule).
    """
    import os
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        jax.config.update("jax_platforms", "cpu")
        return jax.devices("cpu")[0], None
    last_err = None
    for i, backoff in enumerate([0] + BACKOFFS):
        if backoff:
            print(f"[bench] backend init retry {i}/{len(BACKOFFS)} "
                  f"in {backoff}s: {last_err}", file=sys.stderr)
            time.sleep(backoff)
        last_err = _probe_backend(timeout=180 if i == 0 else 90)
        if last_err is None:
            try:  # probe succeeded out-of-process; init here should be fast
                return jax.devices()[0], None
            except Exception as e:
                last_err = f"{type(e).__name__}: {e}"
                try:  # reset the cached failed-backend state before retrying
                    from jax._src import xla_bridge
                    xla_bridge._clear_backends()
                except Exception:
                    pass
    # degrade to CPU so the run still records a number + the error.
    # jax backends were never initialized in this process on the hang path,
    # so the platform switch is still allowed.
    try:
        jax.config.update("jax_platforms", "cpu")
        from jax._src import xla_bridge
        xla_bridge._clear_backends()
        return jax.devices("cpu")[0], f"tpu unavailable, cpu fallback: {last_err}"
    except Exception as e:
        raise RuntimeError(f"no backend at all: {last_err} / {e}") from e


def run_bench_resnet(dev):
    """ResNet-50 training throughput (BASELINE config[1]): images/s/chip
    + MFU. FLOPs per step come from XLA's own cost analysis of the
    compiled train step (conv-appropriate by construction: every conv's
    2*H*W*Cin*Cout*k^2 MACs are counted by the compiler, fwd+bwd+opt),
    with the published 3 x 4.09 GFLOP/img estimate as fallback."""
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import dtypes
    from paddle_tpu.models.resnet import ResNet50
    from paddle_tpu.train import build_train_step, make_train_state

    on_tpu = dev.platform == "tpu"
    batch_size = 128 if on_tpu else 2  # swept: 128 ~= 256 > 64 on v5e
    hw = 224 if on_tpu else 32
    steps = 20 if on_tpu else 2
    num_classes = 1000 if on_tpu else 10

    # s2d: the 7x7/s2 stem re-expressed as a blocked 4x4/s1 conv (same
    # function — models/resnet.py stem_weights_to_s2d); never slower on
    # v5e, +4% at batch 256
    model = ResNet50(num_classes=num_classes,
                     stem="s2d" if on_tpu else "conv7")
    optimizer = opt.Momentum(learning_rate=0.1, momentum=0.9)
    state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

    def loss_fn(params, **batch):
        return model.loss(params, training=True, **batch)

    policy = dtypes.get_policy("bf16") if on_tpu else None
    step = jax.jit(build_train_step(loss_fn, optimizer, policy=policy),
                   donate_argnums=(0,))

    key = jax.random.PRNGKey(1)
    batch = dict(
        image=jax.random.normal(key, (batch_size, hw, hw, 3), jnp.float32),
        label=jax.random.randint(key, (batch_size,), 0, num_classes,
                                 jnp.int32),
    )

    try:  # XLA's flop count for the whole compiled step
        cost = step.lower(state, **batch).compile().cost_analysis()
        flops_per_step = float(cost["flops"])
    except Exception:
        flops_per_step = 3 * 4.09e9 * batch_size  # fwd+bwd approx

    # two warmup steps: step 0 compiles; a state-signature change on
    # step 1 (e.g. a dtype drift bug) would otherwise put a silent
    # recompile inside the timed window
    for _ in range(2):
        state, metrics = step(state, **batch)
        float(metrics["loss"])  # sync (see run_bench note)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, **batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    images_per_sec = batch_size * steps / dt
    mfu = flops_per_step * steps / dt / device_peak_flops(dev)
    return {
        "metric": "resnet50_images_per_sec_per_chip",
        "value": round(images_per_sec, 2),
        "unit": "images/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu": round(mfu, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "batch_size": batch_size,
        "image_size": hw,
        "flops_per_step": flops_per_step,
        "loss": round(final_loss, 4),
        "_telemetry": {"steps": steps, "dt": dt,
                       "examples_per_step": batch_size},
    }


def run_bench(dev):
    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import dtypes
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.train import build_train_step, make_train_state

    on_tpu = dev.platform == "tpu"

    cfg = BertConfig.base(dropout=0.0, attn_dropout=0.0)
    seq = 512
    batch_size = 48 if on_tpu else 2  # swept: 48 > 32 > 8 on v5e
    steps = 20 if on_tpu else 3
    if not on_tpu:  # CPU smoke config: keep the same code path, tiny model
        cfg = BertConfig.tiny(dropout=0.0, attn_dropout=0.0, attn_impl="xla")
        seq = 64

    model = BertForPretraining(cfg)
    optimizer = opt.AdamW(learning_rate=1e-4)
    state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

    def loss_fn(params, **batch):
        # training=True: bench the real training path (dropout=0 here, but
        # keep the graph the one training uses)
        return model.loss(params, training=True, **batch)

    policy = dtypes.get_policy("bf16") if on_tpu else None
    step = jax.jit(build_train_step(loss_fn, optimizer, policy=policy),
                   donate_argnums=(0,))

    key = jax.random.PRNGKey(1)
    batch = dict(
        input_ids=jax.random.randint(key, (batch_size, seq), 0,
                                     cfg.vocab_size, jnp.int32),
        token_type_ids=jnp.zeros((batch_size, seq), jnp.int32),
        attention_mask=jnp.ones((batch_size, seq), bool),
        mlm_labels=jax.random.randint(key, (batch_size, seq), 0,
                                      cfg.vocab_size, jnp.int32),
        mlm_mask=(jax.random.uniform(key, (batch_size, seq)) < 0.15
                  ).astype(jnp.float32),
        nsp_labels=jnp.zeros((batch_size,), jnp.int32),
    )

    # warmup (compile). Sync via host transfer of the loss — NOT
    # block_until_ready, which does not wait through proxied-device
    # transports (observed on the axon TPU tunnel).
    state, metrics = step(state, **batch)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, **batch)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * seq
    tokens_per_sec = tokens_per_step * steps / dt

    n_params = count_params(state["params"])
    flops_per_token = 6 * n_params + 12 * cfg.num_layers * seq * cfg.hidden_size
    achieved = tokens_per_sec * flops_per_token
    mfu = achieved / device_peak_flops(dev)

    return {
        "metric": "bert_base_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4),
        "mfu": round(mfu, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "batch_size": batch_size,
        "seq_len": seq,
        "params": n_params,
        "loss": round(final_loss, 4),
        "_telemetry": {"steps": steps, "dt": dt,
                       "examples_per_step": batch_size,
                       "tokens_per_step": tokens_per_step},
    }


def run_bench_transformer(dev):
    """Transformer-big WMT en-de, packed variable-length training
    (BASELINE config[3]): REAL (non-pad) tokens/s/chip through the packed
    path, with the padded one-sequence-per-row layout timed on the same
    compiled shapes as the contrast — ``packed_vs_padded`` is the
    measured win of data/packing.py (same step wall-clock, more real
    tokens per slab). MFU from XLA's cost analysis of the packed step."""
    import numpy as np

    from paddle_tpu import optimizer as opt
    from paddle_tpu.core import dtypes
    from paddle_tpu.data import packing
    from paddle_tpu.models.transformer import Transformer, TransformerConfig
    from paddle_tpu.train import build_train_step, make_train_state

    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = TransformerConfig.big(dropout=0.0, attn_dropout=0.0,
                                    vocab_size=32768, max_len=256)
        src_len = tgt_len = 256
        rows = 16
        steps = 12
        n_pairs = 1500
    else:
        cfg = TransformerConfig.tiny(dropout=0.0, attn_dropout=0.0,
                                     max_len=32, attn_impl="xla")
        src_len = tgt_len = 32
        rows = 2
        steps = 2
        n_pairs = 40

    model = Transformer(cfg)
    optimizer = opt.Adam(learning_rate=1e-4)
    state = make_train_state(model, optimizer, jax.random.PRNGKey(0))

    # WMT-like ragged lengths: lognormal, clipped to the bucket
    rng = np.random.default_rng(0)
    lens = np.clip(rng.lognormal(3.0, 0.6, n_pairs).astype(np.int64),
                   4, src_len - 1)
    srcs = [rng.integers(3, cfg.vocab_size, n).astype(np.int32)
            for n in lens]
    tins = [np.concatenate([[cfg.bos_id], s]).astype(np.int32)[:tgt_len]
            for s in srcs]
    touts = [np.concatenate([s, [cfg.eos_id]]).astype(np.int32)[:tgt_len]
             for s in srcs]

    def loss_fn(params, **b):
        return model.loss_packed(
            params, b["src"], b["src_seg"], b["src_pos"], b["tgt"],
            b["tgt_out"], b["tgt_seg"], b["tgt_pos"], training=True)

    policy = dtypes.get_policy("bf16") if on_tpu else None
    step = jax.jit(build_train_step(loss_fn, optimizer, policy=policy),
                   donate_argnums=(0,))

    def batch_stream(packed: bool):
        if packed:
            it = packing.packed_batches(
                srcs, tins, rows_per_batch=rows, src_len=src_len,
                tgt_len=tgt_len, tgt_extras={"tgt_out": touts})
        else:
            # one sequence per row, same compiled shapes (the LoD-free
            # padded layout the reference trains on)
            def padded():
                for lo in range(0, len(srcs), rows):
                    chunk = list(range(lo, min(lo + rows, len(srcs))))
                    b = {k: np.zeros((rows, src_len if "src" in k
                                      else tgt_len), np.int32)
                         for k in ("src", "src_seg", "src_pos", "tgt",
                                   "tgt_seg", "tgt_pos", "tgt_out")}
                    for ri, i in enumerate(chunk):
                        s, ti, to = srcs[i], tins[i], touts[i]
                        b["src"][ri, :len(s)] = s
                        b["src_seg"][ri, :len(s)] = 1
                        b["src_pos"][ri, :len(s)] = np.arange(len(s))
                        b["tgt"][ri, :len(ti)] = ti
                        b["tgt_seg"][ri, :len(ti)] = 1
                        b["tgt_pos"][ri, :len(ti)] = np.arange(len(ti))
                        b["tgt_out"][ri, :len(to)] = to
                    yield b
            it = padded()
        for b in it:
            yield {k: jnp.asarray(v) for k, v in b.items()}

    def timed(packed: bool, st):
        import itertools
        batches = list(itertools.islice(batch_stream(packed), steps + 1))
        real = sum(int((np.asarray(b["tgt_seg"]) > 0).sum())
                   for b in batches[1:])
        slots = sum(b["tgt_seg"].size for b in batches[1:])
        st, m = step(st, **batches[0])     # warmup/compile
        float(m["loss"])
        t0 = time.perf_counter()
        for b in batches[1:]:
            st, m = step(st, **b)
        loss = float(m["loss"])
        dt = time.perf_counter() - t0
        return real / dt, dt / len(batches[1:]), real / slots, loss, st

    try:
        first = next(batch_stream(False))   # shapes only; avoids a
        cost = step.lower(state, **first).compile().cost_analysis()  # full
        flops_per_step = float(cost["flops"])                 # pack pass
    except Exception:
        flops_per_step = 0.0

    packed_tps, step_s, eff, loss, state = timed(True, state)
    padded_tps, _, _, _, _ = timed(False, state)

    mfu = (flops_per_step / step_s / device_peak_flops(dev)
           if flops_per_step else 0.0)
    return {
        "metric": "transformer_big_packed_tokens_per_sec_per_chip",
        "value": round(packed_tps, 2),
        "unit": "real tokens/s/chip",
        "vs_baseline": round(mfu / 0.35, 4) if mfu else 0.0,
        "mfu": round(mfu, 4),
        "packed_vs_padded": round(packed_tps / max(padded_tps, 1e-9), 4),
        "padded_tokens_per_sec": round(padded_tps, 2),
        "packing_efficiency": round(eff, 4),
        "device": getattr(dev, "device_kind", dev.platform),
        "rows_per_batch": rows,
        "src_len": src_len,
        "loss": round(loss, 4),
        "_telemetry": {"steps": steps, "dt": step_s * steps,
                       "examples_per_step": rows,
                       "tokens_per_step": packed_tps * step_s},
    }


def run_bench_deepfm(dev):
    """DeepFM CTR with the host-resident KV embedding engine (BASELINE
    config[4]): examples/s/chip with pull/push PREFETCH overlap on, and
    the same stream with overlap off — ``vs_baseline`` is the measured
    prefetch speedup, the number behind parallel/host_kv.py's "prefetch
    overlaps the device step" design claim.

    Honest-number notes (ISSUE 7 satellite): the original loop issued
    the next batch's dedup (np.unique over B*F ids) BEFORE dispatching
    the device step, putting it on the critical path — prefetch then
    measured ~0.73-0.96x (slower than sync). run_kv_epoch now issues
    the prefetch after step dispatch, which removes the regression; on
    an N-core CPU box with the XLA step already using every core the
    remaining overlap is structurally ~neutral (pull threads timeshare
    with the step — there is no idle resource to hide the pull behind,
    unlike TPU where the device step frees the host), so the CPU
    expectation is ~1.0x and the bench takes best-of-2 per mode to keep
    ambient load spikes from masquerading as regressions."""
    import numpy as np

    from paddle_tpu import optimizer as opt
    from paddle_tpu.models.deepfm import DeepFMHostKV
    from paddle_tpu.parallel.host_kv import (HostKVEmbedding, HostKVStore,
                                             build_kv_train_step,
                                             run_kv_epoch)

    on_tpu = dev.platform == "tpu"
    fields = 26                           # criteo-style sparse fields
    dim = 16 if on_tpu else 8
    # CPU smoke needs non-trivial work per batch too: when the "device"
    # step is near-instant the prefetch thread's sync overhead swamps the
    # overlap and the ratio is meaningless
    batch = 4096 if on_tpu else 2048
    n_batches = 24 if on_tpu else 8
    vocab = 2_000_000 if on_tpu else 500_000

    model = DeepFMHostKV(num_fields=fields, embed_dim=dim,
                         hidden=(400, 400) if on_tpu else (64, 64))
    optimizer = opt.Adam(learning_rate=1e-3)
    params = model.init(jax.random.PRNGKey(0))
    state0 = {"params": params, "opt": optimizer.init(params),
              "step": jnp.zeros((), jnp.int32)}
    step = jax.jit(build_kv_train_step(
        lambda p, rows, inv, label: model.loss(p, rows, inv, label),
        optimizer))

    rng = np.random.default_rng(0)
    all_batches = []
    for _ in range(n_batches):
        # zipf-ish skew: a hot head + a heavy uniform tail, like CTR logs
        hot = rng.integers(0, 1000, size=(batch, fields // 2))
        tail = rng.integers(1000, vocab,
                            size=(batch, fields - fields // 2))
        ids = np.concatenate([hot, tail], 1).astype(np.int64)
        label = (rng.random(batch) < 0.2).astype(np.float32)
        all_batches.append(dict(feat_ids=ids, label=jnp.asarray(label)))

    def timed(prefetch: bool):
        store = HostKVStore(1 + dim, optimizer="adagrad", seed=0)
        emb = HostKVEmbedding(store, lr=0.05, min_bucket=1 << 12)
        state = jax.tree_util.tree_map(jnp.copy, state0)
        # warmup (compile + touch the hot rows once)
        state, _ = run_kv_epoch(step, state, emb, iter(all_batches[:1]),
                                ids_key="feat_ids", prefetch=prefetch)
        t0 = time.perf_counter()
        state, hist = run_kv_epoch(step, state, emb, iter(all_batches),
                                   ids_key="feat_ids", prefetch=prefetch)
        dt = time.perf_counter() - t0
        loss = float(np.mean([float(m["loss"]) for m in hist]))
        return batch * n_batches / dt, loss

    # best-of-2 per mode: a 2-core CI box sees ambient load spikes
    eps_on, loss = max((timed(prefetch=True) for _ in range(2)),
                       key=lambda r: r[0])
    eps_off, _ = max((timed(prefetch=False) for _ in range(2)),
                     key=lambda r: r[0])
    return {
        "metric": "deepfm_examples_per_sec_per_chip",
        "value": round(eps_on, 2),
        "unit": "examples/s/chip",
        # the overlap claim, quantified: >1.0 == prefetch hides KV time
        "vs_baseline": round(eps_on / max(eps_off, 1e-9), 4),
        "prefetch_speedup": round(eps_on / max(eps_off, 1e-9), 4),
        "examples_per_sec_no_prefetch": round(eps_off, 2),
        "prefetch_note": ("cpu: step already saturates every core, so "
                          "overlap is ~neutral by construction; the "
                          "<1.0x regression (dedup on the critical "
                          "path) is fixed in run_kv_epoch"
                          if dev.platform != "tpu" else ""),
        "device": getattr(dev, "device_kind", dev.platform),
        "batch_size": batch,
        "fields": fields,
        "embed_dim": dim,
        "loss": round(loss, 4),
        "_telemetry": {"steps": n_batches,
                       "dt": batch * n_batches / max(eps_on, 1e-9),
                       "examples_per_step": batch},
    }


EMBED_SERVE_SCHEMA = ("metric", "value", "unit", "vs_baseline",
                      "qps_cached", "qps_cold", "speedup_vs_cold",
                      "lookup_p50_s", "lookup_p99_s", "cold_batch_p99_s",
                      "miss_pull_p99_s", "hit_rate", "evictions",
                      "streaming_rows_applied", "staleness_seconds",
                      "recompiles_after_warmup", "capacity", "vocab_size",
                      "batch_size", "fields", "embed_dim", "num_batches",
                      "device")


def embed_serve_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_EMBED_SERVE",
                              "/tmp/BENCH_EMBED_SERVE.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_EMBED_SERVE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_EMBED_SERVE.json"))


def run_bench_embedding_serving(dev, dryrun=False):
    """Online embedding serving (ISSUE 7 acceptance): DeepFM inference
    QPS + p99 lookup latency through the device-cached
    ``EmbeddingServingEngine`` versus the COLD full-table path — every
    batch re-pulls the whole (vocab, dim) table from the host KV store
    and ``device_put``s it before the forward (the no-cache way to
    serve the same freshness guarantee when the table lives beyond
    HBM). Traffic is zipf-ish CTR: a hot head covering most lookups
    (the stated hit-rate regime) plus a uniform cold tail that churns
    the LRU. A trainer thread streams row updates through the
    StreamingUpdateChannel WHILE the cached pass serves — the online-
    learning mix the subsystem exists for — and both paths read the
    same store, so neither side serves stale rows beyond the engine's
    bound. Zero steady-state recompiles is RecompileDetector-ASSERTED
    (any retrace fails the bench), and the hit-rate / staleness gauges
    must come out populated. ``vs_baseline`` is speedup/2.0 — 1.0 ==
    the >=2x acceptance target. Emits BENCH_EMBED_SERVE.json (schema
    self-validated) next to this file (dryrun: /tmp)."""
    import numpy as np

    from paddle_tpu import embedding_serving as es
    from paddle_tpu import observability as obs
    from paddle_tpu.models.deepfm import DeepFMHostKV
    from paddle_tpu.parallel.host_kv import HostKVStore

    on_tpu = dev.platform == "tpu"
    if on_tpu:
        vocab, fields, dim, batch = 2_000_000, 26, 16, 2048
        head, capacity, n_batches, min_bucket = 8192, 1 << 16, 48, 8192
        hidden = (400, 400)
    elif dryrun:
        vocab, fields, dim, batch = 50_000, 8, 8, 256
        head, capacity, n_batches, min_bucket = 512, 2048, 6, 256
        hidden = (32,)
    else:
        vocab, fields, dim, batch = 200_000, 26, 8, 1024
        head, capacity, n_batches, min_bucket = 4096, 1 << 15, 24, 4096
        hidden = (64, 64)

    model = DeepFMHostKV(num_fields=fields, embed_dim=dim, hidden=hidden)
    params = model.init(jax.random.PRNGKey(0))
    store = HostKVStore(1 + dim, optimizer="adagrad", init_scale=0.01,
                        seed=0)
    reg = obs.MetricsRegistry()
    channel = es.StreamingUpdateChannel(store, registry=reg)
    eng = es.EmbeddingServingEngine(
        store, model, params, capacity=capacity, policy="lru",
        min_bucket=min_bucket, max_pending=4, channel=channel,
        max_staleness_s=5.0, registry=reg)

    rng = np.random.default_rng(0)

    def make_batch():
        # 80% of lookups hit the hot head, 20% the uniform cold tail —
        # the zipf-ish CTR mix the stated hit rate comes from
        hot = rng.integers(0, head, size=(batch, fields))
        tail = rng.integers(head, vocab, size=(batch, fields))
        pick = rng.random((batch, fields)) < 0.8
        return np.where(pick, hot, tail).astype(np.int64)

    batches = [make_batch() for _ in range(n_batches)]

    # startup compiles: every cache gather/install bucket + the DeepFM
    # forward per gather width; everything timed below is steady state
    eng.warmup((batch, fields))
    for b in batches[:2]:           # populate the hot head
        eng.serve(b)
    det = obs.RecompileDetector("embed_serve_bench", warmup=0,
                                registry=reg)

    def push_updates(n_rows=64):
        ids = rng.integers(0, head, size=(n_rows,)).astype(np.int64)
        rows = rng.normal(0, 0.01, size=(n_rows, 1 + dim)).astype(
            np.float32)
        channel.push_rows(ids, rows)

    # --- cached pass: pipelined submit/step (miss pulls overlap the
    # previous batch's device work), trainer pushes streaming in.
    # Best-of-2 passes over FRESH same-distribution batches: a 2-core
    # CI box sees ambient load spikes that would otherwise masquerade
    # as engine regressions
    def cached_pass():
        # returns wall time AND this pass's own latency/staleness
        # numbers, so the reported percentiles come from the SAME pass
        # as the reported QPS (best-of-2 exists because ambient CI load
        # can hit one pass — mixing pass-1 QPS with pass-2 latencies
        # would make the artifact internally inconsistent)
        reg.unregister("embedding_serving_lookup_seconds")
        bs = [make_batch() for _ in range(n_batches)]
        t0 = time.perf_counter()
        for i, b in enumerate(bs):
            if i % 4 == 3:
                push_updates()
            eng.submit(b)
            while eng.pending() >= 2:
                eng.step()
        while eng.pending():
            eng.step()
        dt = time.perf_counter() - t0
        lk = reg.histogram("embedding_serving_lookup_seconds")
        return (dt, lk.quantile(0.5), lk.quantile(0.99),
                reg.gauge("embedding_serving_staleness_seconds").value())

    dt_cached, lk_p50, lk_p99, staleness = min(
        (cached_pass() for _ in range(2)), key=lambda r: r[0])
    det.check()
    qps_cached = batch * n_batches / dt_cached
    hit_rate = reg.gauge("embedding_serving_hit_rate").value()

    # --- cold pass: per batch, pull the FULL table from the store,
    # device_put it, and run the same jitted forward with feat_ids
    # indexing the whole table (compile excluded by a warm call)
    all_ids = np.arange(vocab, dtype=np.int64)
    cold_fwd = jax.jit(lambda p, tbl, inv: model.predict_proba(
        p, tbl, inv))
    table_np = store.pull(all_ids)
    np.asarray(cold_fwd(params, jax.device_put(table_np),
                        jnp.asarray(batches[0].astype(np.int32))))

    def cold_pass():
        times = []
        t0 = time.perf_counter()
        for b in batches:
            tb = time.perf_counter()
            tbl = jax.device_put(store.pull(all_ids))
            out = cold_fwd(params, tbl, jnp.asarray(b.astype(np.int32)))
            np.asarray(out)
            times.append(time.perf_counter() - tb)
        return time.perf_counter() - t0, times

    dt_cold, cold_times = min((cold_pass() for _ in range(2)),
                              key=lambda r: r[0])
    qps_cold = batch * n_batches / dt_cold

    channel.flush()
    speedup = qps_cached / max(qps_cold, 1e-9)
    result = {
        "metric": "embedding_serving_examples_per_sec",
        "value": round(qps_cached, 2),
        "unit": "examples/s",
        "vs_baseline": round(speedup / 2.0, 4),  # 1.0 == the 2x target
        "qps_cached": round(qps_cached, 2),
        "qps_cold": round(qps_cold, 2),
        "speedup_vs_cold": round(speedup, 4),
        "lookup_p50_s": round(lk_p50, 6),
        "lookup_p99_s": round(lk_p99, 6),
        "cold_batch_p99_s": round(float(np.percentile(cold_times, 99)),
                                  6),
        "miss_pull_p99_s": round(reg.histogram(
            "embedding_serving_miss_latency_seconds").quantile(0.99), 6),
        "hit_rate": round(hit_rate, 4),
        "evictions": int(reg.counter(
            "embedding_cache_evictions_total").value()),
        "streaming_rows_applied": int(reg.counter(
            "embedding_stream_rows_applied_total").value()),
        "staleness_seconds": round(staleness, 6),
        "recompiles_after_warmup": det.recompiles,
        "capacity": capacity,
        "vocab_size": vocab,
        "batch_size": batch,
        "fields": fields,
        "embed_dim": dim,
        "num_batches": n_batches,
        "device": getattr(dev, "device_kind", dev.platform),
        "dryrun": bool(dryrun),
        "_telemetry": {"steps": n_batches, "dt": dt_cached,
                       "examples_per_step": batch},
    }
    missing = [k for k in EMBED_SERVE_SCHEMA if k not in result]
    if missing:
        raise RuntimeError(f"BENCH_EMBED_SERVE schema self-check "
                           f"failed: missing {missing}")
    if result["recompiles_after_warmup"] != 0:
        raise RuntimeError(
            f"steady-state embedding serving recompiled "
            f"{det.recompiles}x — fixed-shape invariant broken (a "
            "gather/install/forward bucket missed by warmup)")
    if not 0.0 < result["hit_rate"] <= 1.0:
        raise RuntimeError(
            f"hit-rate gauge not populated: {result['hit_rate']}")
    if result["streaming_rows_applied"] <= 0:
        raise RuntimeError("streaming channel applied no rows — the "
                           "online-update half of the bench is dead")
    path = embed_serve_json_path(dryrun)
    with open(path, "w") as f:
        json.dump({k: v for k, v in result.items()
                   if k != "_telemetry"}, f, indent=2)
    result["bench_json"] = path
    return result


ROUTER_SCHEMA = ("metric", "value", "unit", "vs_baseline",
                 "aggregate_tokens_per_sec", "replica_scaling",
                 "scaling_2x", "scaling_4x",
                 "ttft_interactive_p99_s", "ttft_budget_s",
                 "ttft_slo_met", "migrations", "migration_parity_ok",
                 "affinity_routed", "balance_routed",
                 "prefix_tokens_shared",
                 "recompiles_after_warmup", "num_requests",
                 "replica_slots", "decode_cap",
                 "trace_json", "trace_spans", "device", "chaos",
                 "headroom", "postmortem_dir")

# the chaos variant's sub-schema (ISSUE 14) — shared with
# tools/check_metrics_log.py:validate_chaos_section so CI and the bench
# pin the same contract
CHAOS_SCHEMA = ("lost_requests", "redrive_parity", "redrives",
                "redriven_requests", "shed_structured", "ejected",
                "goodput_tokens_per_sec", "goodput_no_chaos",
                "goodput_ratio", "breaker_cycle_ok",
                "breaker_transitions", "recompiles",
                "postmortems", "postmortem_reasons",
                "postmortem_valid", "postmortem_files")


def router_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_ROUTER",
                              "/tmp/BENCH_ROUTER.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_ROUTER",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_ROUTER.json"))


def run_bench_router(dev, dryrun=False):
    """Multi-replica serving fleet (ISSUE 11 acceptance): N paged
    ServingEngine replicas behind the prefix-affinity FleetRouter.

    Replicas are stepped round-robin on ONE host here, so wall-clock
    cannot show fleet scaling; instead each replica's BUSY time (wall
    seconds inside its own step calls) is measured and the fleet's
    aggregate tokens/s is ``total tokens / max per-replica busy`` —
    the critical path if every replica had its own accelerator, which
    is exactly what the router controls: a balance miss concentrates
    busy time on one replica and the scaling number drops. Legs:

    - scaling: the same burst (fresh random prompts, same length mix)
      through 1/2/4-replica fleets; ``scaling_2x = agg2/agg1`` with
      the >=1.6x acceptance target;
    - SLO probes: interactive-lane probes trickled in while a burst
      that saturates a single engine runs on the 2-replica fleet —
      probe TTFT p99 vs the stated budget;
    - affinity: shared-system-prompt traffic after one publisher wave;
      the router must place followers where the prefix pages are hot
      (prefix_tokens_shared counts the skipped prefill);
    - migration: the same burst run twice on 2 replicas, once clean and
      once with a mid-decode drain of one replica (live migration of
      queued + in-flight requests) — greedy outputs must be
      byte-identical and the whole bench must stay at ZERO recompiles
      fleet-wide (every replica fully warmed up front, migration page
      IO included).

    Emits BENCH_ROUTER.json (schema self-validated) next to this file
    (dryrun: /tmp) plus a Perfetto trace whose router.route /
    serving.request / router.migrate spans share trace ids across the
    fleet."""
    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.serving import fleet
    from paddle_tpu.models.gpt import GPT, GPTConfig

    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = GPTConfig(vocab_size=32768, hidden_size=1024, num_layers=12,
                        num_heads=16, ffn_size=4096, max_position=512,
                        dropout=0.0)
        n_req, slots, page_size, chunk, cap = 48, 8, 16, 64, 64
        len_set = (16, 32, 64, 128, 192)
        attn_impl = "pallas"
        ttft_budget = 1.0
        sysp_len = 4 * page_size + 2
        decode_block = 8
    elif dryrun:
        cfg = GPTConfig.tiny(vocab_size=128, hidden_size=32, num_layers=2,
                             num_heads=2, ffn_size=64, max_position=64,
                             dropout=0.0, attn_impl="xla")
        n_req, slots, page_size, chunk, cap = 8, 2, 4, 8, 8
        len_set = (4, 9, 12)
        attn_impl = "lax"
        ttft_budget = 30.0   # smoke box: schema/plumbing, not latency
        sysp_len = page_size + 2   # fits the tiny per-slot limit
        decode_block = 4     # < cap so a mid-decode drain window exists
    else:
        # CPU measurement config: weight-heavy so batching amortizes
        # weight reads; small enough that 4 replicas' warmups fit a CI
        # box. A single replica (4 slots) is saturated 8x over by the
        # 32-request burst.
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, ffn_size=1024, max_position=192,
                        dropout=0.0, attn_impl="xla")
        n_req, slots, page_size, chunk, cap = 32, 4, 16, 32, 32
        len_set = (16, 32, 48, 64)
        attn_impl = "lax"
        ttft_budget = 4.0
        sysp_len = 4 * page_size + 2
        decode_block = 8
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    lens = rng.choice(len_set, n_req)
    hi = max(len_set)
    cache_dtype = jnp.bfloat16 if not on_tpu else None

    reg = obs.MetricsRegistry()
    tracer = obs.Tracer(capacity=65536)

    def make_replica(i):
        eng = serving.ServingEngine(
            model, params, num_slots=slots, page_size=page_size,
            max_tokens_per_slot=hi + cap, prefill_chunk=chunk,
            decode_block=decode_block, attn_impl=attn_impl,
            cache_dtype=cache_dtype, registry=obs.MetricsRegistry(),
            tracer=tracer, ttft_budget_s=ttft_budget)
        return fleet.LocalReplica(eng, name=f"replica{i}")

    # every replica fully warmed (decode + prefill buckets + migration
    # page IO) BEFORE the detector arms: the whole bench below must
    # stay at zero compiles — the fleet-wide fixed-shape invariant
    replicas = [make_replica(i).warmup() for i in range(4)]
    det = obs.RecompileDetector("router_bench", warmup=0, registry=reg)

    def fresh_prompts():
        # same length mix every leg, fresh content (no cross-leg
        # prefix sharing skewing a scaling comparison)
        return [rng.integers(1, cfg.vocab_size, int(n)).astype(np.int32)
                for n in lens]

    leg_tel = {"steps": 0, "dt": 0.0}

    def burst(router, prompts, probes=0, probe_interval=3):
        """Submit everything up front, run to idle; returns (results
        by submission index, probe TTFTs). Records the leg's step
        count + wall time into ``leg_tel`` for the run log."""
        for rep in router.replicas:
            rep.busy_s = 0.0
        frids = [router.submit(p, cap) for p in prompts]
        probe_ids = []
        steps = 0
        t0 = time.perf_counter()
        while not router.idle():
            router.step()
            steps += 1
            if len(probe_ids) < probes and steps % probe_interval == 0:
                pr = rng.integers(1, cfg.vocab_size,
                                  min(len_set)).astype(np.int32)
                probe_ids.append(router.submit(pr, 8,
                                               lane="interactive"))
            if steps > 1_000_000:
                raise RuntimeError("fleet burst did not converge")
        leg_tel["steps"] = steps
        leg_tel["dt"] = time.perf_counter() - t0
        outs = [router.result(f) for f in frids]
        ttfts = [router.request_stats(f)["ttft_s"] for f in probe_ids]
        return outs, ttfts

    # --- scaling legs: 1 / 2 / 4 replicas over the same burst shape
    scaling = {}
    for n in (1, 2, 4):
        router = fleet.FleetRouter(replicas[:n], registry=reg,
                                   tracer=tracer, seed=n)
        outs, _ = burst(router, fresh_prompts())
        assert all(o is not None and len(o) == cap for o in outs), \
            "scaling leg lost requests"
        busy = max(rep.busy_s for rep in replicas[:n])
        scaling[str(n)] = round(n_req * cap / max(busy, 1e-9), 2)
    scaling_2x = scaling["2"] / max(scaling["1"], 1e-9)
    scaling_4x = scaling["4"] / max(scaling["1"], 1e-9)

    # --- SLO probe leg: interactive probes against the 2-replica fleet
    # under the single-engine-saturating burst
    router2 = fleet.FleetRouter(replicas[:2], registry=reg,
                                tracer=tracer, seed=7)
    _, probe_ttfts = burst(router2, fresh_prompts(),
                           probes=max(4, slots),
                           probe_interval=2 if dryrun else 3)
    interactive_p99 = float(np.percentile(probe_ttfts, 99))

    # --- affinity leg: one publisher wave, then shared-prefix traffic;
    # the router must keep followers on the publishing replica
    shared_before = sum(int(r.engine._reg.counter(
        "serving_prefix_shared_tokens_total").value())
        for r in replicas[:2])
    router_a = fleet.FleetRouter(replicas[:2], registry=reg,
                                 tracer=tracer, seed=9)
    sysp = rng.integers(1, cfg.vocab_size, sysp_len).astype(np.int32)
    def shared_prompt():
        return np.concatenate([sysp, rng.integers(
            1, cfg.vocab_size, int(min(len_set))).astype(np.int32)])
    router_a.submit(shared_prompt(), 8)
    router_a.run_until_idle(max_steps=1_000_000)
    for _ in range(n_req // 2):
        router_a.submit(shared_prompt(), 8)
    router_a.run_until_idle(max_steps=1_000_000)
    shared_after = sum(int(r.engine._reg.counter(
        "serving_prefix_shared_tokens_total").value())
        for r in replicas[:2])
    prefix_tokens_shared = shared_after - shared_before
    affinity_routed = router_a.routed_affinity_total

    # --- migration leg: same traffic twice on 2 replicas; the second
    # run drains replica1 mid-decode (queued requests re-routed,
    # in-flight slots live-migrated) — byte-identical greedy outputs
    # required. Sized to ONE replica's slots so the survivor has free
    # capacity to restore into (a drain into a saturated peer rightly
    # aborts — that is the no-request-lost contract, not the bench).
    mig_prompts = fresh_prompts()[:slots]
    router_m = fleet.FleetRouter(replicas[:2], registry=reg,
                                 tracer=tracer, seed=13)
    ref_outs, _ = burst(router_m, mig_prompts)
    router_m2 = fleet.FleetRouter(replicas[:2], registry=reg,
                                  tracer=tracer, seed=13)
    for rep in replicas[:2]:
        rep.busy_s = 0.0
    frids = [router_m2.submit(p, cap) for p in mig_prompts]
    # step until replica1 holds a MID-decode request (some tokens out,
    # more to go) so the drain exercises a genuine in-flight migration
    eng1 = replicas[1].engine
    for _ in range(1_000):
        router_m2.step()
        mid = [i for i in eng1.scheduler.decode_slots()
               if 0 < len(eng1.scheduler.slots[i].generated) < cap]
        if mid:
            break
    else:
        raise RuntimeError("no mid-decode drain window found")
    migrations = router_m2.drain_replica(replicas[1], remove=False)
    while not router_m2.idle():
        router_m2.step()
    replicas[1].draining = False        # hand the replica back
    mig_outs = [router_m2.result(f) for f in frids]
    parity_ok = all(
        m is not None and r is not None and np.array_equal(r, m)
        for r, m in zip(ref_outs, mig_outs))

    # --- chaos leg (ISSUE 14): involuntary failure on the 4-replica
    # fleet — one replica CRASHES mid-burst (ejected, requests
    # redriven exactly-once), another's transport flakes (circuit
    # breaker opens, half-open probes, closes). Gates: 0 requests
    # silently lost, redriven greedy outputs byte-identical to the
    # failure-free run, the breaker completes a visible full cycle,
    # and the whole leg stays at zero recompiles with detection +
    # breakers armed.
    chaos_prompts = fresh_prompts()
    router_cr = fleet.FleetRouter(replicas, registry=reg,
                                  tracer=tracer, seed=17)
    ref_chaos, _ = burst(router_cr, chaos_prompts)
    chaos_clean_busy = max(rep.busy_s for rep in replicas)
    chaos_clean_tokens = sum(len(o) for o in ref_chaos)
    goodput_clean = chaos_clean_tokens / max(chaos_clean_busy, 1e-9)

    crash_step = 4 if dryrun else 6
    c_crash = fleet.ChaosReplica(replicas[1], crash_on_step=crash_step)
    c_flaky = fleet.ChaosReplica(replicas[2], submit_failures=2)
    # breaker trips at 2 failures, well under the death threshold: the
    # flaky replica must CYCLE (open -> half-open -> closed), not eject
    fpol = fleet.FaultPolicy(max_consecutive_failures=6,
                             probe_timeout_s=120.0,
                             breaker_threshold=2,
                             breaker_cooldown_s=0.2, max_redrives=4)
    # flight recorder (ISSUE 16): the crash ejection must ship a
    # schema-validated postmortem bundle next to BENCH_ROUTER.json
    import os
    import shutil
    jpath = router_json_path(dryrun)
    pm_dir = (jpath[:-5] if jpath.endswith(".json") else jpath) \
        + ".postmortems"
    shutil.rmtree(pm_dir, ignore_errors=True)   # this run's bundles only
    router_x = fleet.FleetRouter(
        [replicas[0], c_crash, c_flaky, replicas[3]],
        registry=reg, tracer=tracer, seed=17, faults=fpol,
        postmortem_dir=pm_dir)
    for rep in replicas:
        rep.busy_s = 0.0

    def tiny_prompt():
        return rng.integers(1, cfg.vocab_size,
                            min(len_set)).astype(np.int32)

    # deterministically trip the flaky transport before the burst: keep
    # feeding tiny requests until its breaker opens (p2c favors the
    # always-empty flaky replica, so this converges in a few submits;
    # the failed submits retry on peers — the caller never loses one)
    pre_frids = []
    for _ in range(64):
        pre_frids.append(router_x.submit(tiny_prompt(), 4))
        if (c_flaky.name, "closed", "open") in router_x.breaker_transitions:
            break
    else:
        raise RuntimeError("chaos leg: flaky breaker never opened")
    frids_x = [router_x.submit(p, cap) for p in chaos_prompts]
    steps = 0
    while not router_x.idle():
        router_x.step()
        steps += 1
        if steps > 1_000_000:
            raise RuntimeError("chaos burst did not converge")
    # recovery wave: let the breaker cooldown elapse (a dryrun burst
    # can finish inside it), then the router routes the next submit as
    # the deliberate half-open probe; the healed transport answers and
    # the breaker closes
    time.sleep(fpol.breaker_cooldown_s + 0.05)
    probe_frids = [router_x.submit(tiny_prompt(), 4) for _ in range(2)]
    while not router_x.idle():
        router_x.step()
    chaos_busy = max(rep.busy_s for rep in replicas)
    chaos_outs, chaos_shed, chaos_lost = [], 0, 0
    for f in frids_x:
        o = router_x.result(f)
        chaos_outs.append(o)
        if o is None:
            if router_x.reject_reason(f) is not None:
                chaos_shed += 1
            else:
                chaos_lost += 1
    for f in pre_frids + probe_frids:       # no-silent-loss covers ALL
        if router_x.result(f) is None \
                and router_x.reject_reason(f) is None:
            chaos_lost += 1
    chaos_parity = all(
        o is not None and np.array_equal(r, o)
        for r, o in zip(ref_chaos, chaos_outs))
    chaos_tokens = sum(len(o) for o in chaos_outs if o is not None)
    goodput_chaos = chaos_tokens / max(chaos_busy, 1e-9)
    flaky_trans = [(old, new) for (nm, old, new)
                   in router_x.breaker_transitions
                   if nm == c_flaky.name]
    cycle = [("closed", "open"), ("open", "half_open"),
             ("half_open", "closed")]
    it = iter(flaky_trans)
    breaker_cycle_ok = all(t in it for t in cycle)   # ordered subseq
    # postmortem artifact gate: every ejection (and the flaky breaker
    # opening) pulled a black box; each bundle must validate and the
    # eject bundle's trace ids must join the redrive spans' timeline
    bundles = router_x.postmortems()
    redrive_tids = {s.trace_id for s in tracer.spans()
                    if s.name == "router.redrive" and s.trace_id}
    eject_bundles = [b for b in bundles if b["reason"] == "eject"]
    if not eject_bundles:
        raise RuntimeError("chaos leg: crash ejection shipped no "
                           "postmortem bundle")
    for b in bundles:
        obs.validate_postmortem_bundle(b)
    if not set(eject_bundles[0]["trace_ids"]) & redrive_tids:
        raise RuntimeError(
            "chaos leg: eject postmortem trace ids "
            f"{eject_bundles[0]['trace_ids']} join no router.redrive "
            "span — the bundle cannot be linked to its victims")
    pm_files = sorted(os.listdir(pm_dir)) if os.path.isdir(pm_dir) else []
    if not pm_files:
        raise RuntimeError(f"chaos leg: no postmortem dumped to {pm_dir}")
    for fn in pm_files:
        obs.validate_postmortem_file(os.path.join(pm_dir, fn))
    chaos = {
        "lost_requests": int(chaos_lost),
        "redrive_parity": bool(chaos_parity),
        "redrives": int(router_x.redrives_total),
        # distinct requests redriven (an unlucky request can redrive
        # more than once): unique trace ids on the redrive spans
        "redriven_requests": len({s.trace_id for s in tracer.spans()
                                  if s.name == "router.redrive"}),
        "shed_structured": int(chaos_shed),
        "ejected": int(router_x.ejected_total),
        "goodput_tokens_per_sec": round(goodput_chaos, 2),
        "goodput_no_chaos": round(goodput_clean, 2),
        "goodput_ratio": round(goodput_chaos
                               / max(goodput_clean, 1e-9), 4),
        "breaker_cycle_ok": bool(breaker_cycle_ok),
        "breaker_transitions": [f"{nm}:{old}->{new}" for (nm, old, new)
                                in router_x.breaker_transitions],
        "recompiles": 0,        # re-pinned below after det.check()
        "postmortems": len(bundles),
        "postmortem_reasons": sorted({b["reason"] for b in bundles}),
        "postmortem_valid": True,           # validated above, or raised
        "postmortem_files": pm_files,
    }

    det.check()
    chaos["recompiles"] = det.recompiles

    # --- headroom plane (ISSUE 16): the fleet monitor aggregates the
    # surviving replicas' resource headroom (min across replicas = the
    # fleet bottleneck) — pinned in the committed JSON so a regression
    # in the gauge plumbing fails the bench, not a dashboard
    monitor = fleet.FleetMonitor(router_x, registry=reg)
    mon_h = monitor.collect()
    headroom = mon_h["headroom"]
    if set(headroom) != {"flops", "pages", "slots", "hbm", "spill"}:
        raise RuntimeError(f"fleet headroom plane incomplete: {headroom}")
    if any(not (0.0 <= float(v) <= 1.0) for v in headroom.values()):
        raise RuntimeError(f"fleet headroom out of range: {headroom}")

    # --- trace artifact: the cross-replica timeline (ISSUE acceptance:
    # one trace shows a request crossing the fleet through a migration)
    spans = tracer.spans()
    by_name = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s)
    for needed in ("router.route", "serving.request", "router.migrate"):
        if needed not in by_name:
            raise RuntimeError(f"trace self-check: no {needed!r} spans")
    req_tids = {s.trace_id for s in by_name["serving.request"]}
    crossing = [s for s in by_name["router.migrate"]
                if s.trace_id in req_tids]
    if not crossing:
        raise RuntimeError("trace self-check: no migration trace joins "
                           "router.migrate to its request spans")
    chrome = tracer.to_chrome()
    obs.chrome_trace_valid(chrome, require_events=len(crossing))
    trace_path = (jpath[:-5] if jpath.endswith(".json") else jpath) \
        + ".trace.json"
    with open(trace_path, "w") as f:
        json.dump(chrome, f)

    result = {
        "metric": "router_aggregate_tokens_per_sec",
        "value": scaling["2"],
        "unit": "tokens/s",
        # 1.0 == the >=1.6x two-replica scaling target
        "vs_baseline": round(scaling_2x / 1.6, 4),
        "aggregate_tokens_per_sec": scaling["2"],
        "replica_scaling": scaling,
        "scaling_2x": round(scaling_2x, 4),
        "scaling_4x": round(scaling_4x, 4),
        "ttft_interactive_p99_s": round(interactive_p99, 6),
        "ttft_budget_s": ttft_budget,
        "ttft_slo_met": bool(interactive_p99 <= ttft_budget),
        "migrations": int(migrations),
        "migration_parity_ok": bool(parity_ok),
        "affinity_routed": int(affinity_routed),
        "balance_routed": int(router_a.routed_balance_total),
        "prefix_tokens_shared": int(prefix_tokens_shared),
        "recompiles_after_warmup": det.recompiles,
        "chaos": chaos,
        "headroom": headroom,
        "postmortem_dir": os.path.basename(pm_dir),
        "num_requests": n_req,
        "replica_slots": slots,
        "decode_cap": cap,
        "trace_json": trace_path,
        "trace_spans": len(spans),
        "device": getattr(dev, "device_kind", dev.platform),
        "dryrun": bool(dryrun),
        "_telemetry": {"steps": leg_tel["steps"], "dt": leg_tel["dt"],
                       "examples_per_step": slots,
                       "tokens_per_step": n_req * cap
                       / max(leg_tel["steps"], 1)},
    }
    missing = [k for k in ROUTER_SCHEMA if k not in result]
    if missing:
        raise RuntimeError(f"BENCH_ROUTER schema self-check failed: "
                           f"missing {missing}")
    missing_chaos = [k for k in CHAOS_SCHEMA if k not in chaos]
    if missing_chaos:
        raise RuntimeError(f"BENCH_ROUTER chaos section self-check "
                           f"failed: missing {missing_chaos}")
    if chaos["lost_requests"] != 0:
        raise RuntimeError(
            f"chaos leg lost {chaos['lost_requests']} requests "
            "silently — the no-silent-loss contract broke")
    if not chaos["redrive_parity"]:
        raise RuntimeError("chaos redrive parity broken: redriven "
                           "outputs differ from the failure-free run")
    if chaos["ejected"] < 1 or chaos["redrives"] < 1:
        raise RuntimeError("chaos leg ejected/redrove nothing — the "
                           "crash injection is dead")
    if not chaos["breaker_cycle_ok"]:
        raise RuntimeError(
            f"breaker never completed open->half_open->closed "
            f"(saw {chaos['breaker_transitions']})")
    if not parity_ok:
        raise RuntimeError("migration parity broken: drained run's "
                           "greedy outputs differ from the clean run")
    if migrations < 1:
        raise RuntimeError("drain migrated nothing — the migration leg "
                           "is dead")
    if result["recompiles_after_warmup"] != 0:
        raise RuntimeError(
            f"fleet recompiled {det.recompiles}x after warmup — the "
            "fleet-wide fixed-shape invariant broke (scaling numbers "
            "untrustworthy)")
    import os
    committed = {k: v for k, v in result.items() if k != "_telemetry"}
    committed["trace_json"] = os.path.basename(trace_path)
    with open(jpath, "w") as f:
        json.dump(committed, f, indent=2)
    result["bench_json"] = jpath
    return result


NET_SCHEMA = ("metric", "value", "unit", "vs_baseline",
              "net_tokens_per_sec", "local_tokens_per_sec",
              "transport_overhead_ms_per_token", "transport_parity_ok",
              "wire_codec", "rpc_calls_total",
              "stream_requests", "stream_partials_min",
              "stream_ttft_p99_s", "ttft_budget_s", "ttft_slo_met",
              "netlog", "netlog_valid", "steady_state_recompiles",
              "chaos", "num_requests", "replica_slots", "decode_cap",
              "device", "dryrun")

# socket-chaos sub-schema (ISSUE 17): the PR 12 chaos battery run over
# REAL processes and a real dead socket
NET_CHAOS_SCHEMA = ("lost_requests", "redrive_parity", "redrives",
                    "ejected", "shed_structured", "breaker_cycle_ok",
                    "breaker_transitions", "postmortems",
                    "postmortem_reasons", "postmortem_valid")


def net_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_NET",
                              "/tmp/BENCH_NET.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_NET",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_NET.json"))


def run_bench_net_router(dev, dryrun=False):
    """Network serving (ISSUE 17 acceptance): the fleet split across
    REAL processes behind the wire protocol, against the in-process
    LocalReplica fleet as baseline. Legs:

    - transport: the same burst through a 2-process NetReplica fleet
      and a 2-replica in-process fleet — bit-identical greedy outputs
      (the ReplicaHandle contract across a socket) and the transport
      overhead per generated token (RPC framing + checksums + syscalls);
      each replica process must hold ZERO steady-state recompiles
      across the burst (warmup happens server-side before the replica
      announces itself).
    - streaming: a FrontDoor over the net fleet; clients must observe
      >=2 partial token deliveries per request (incremental streaming,
      not buffer-then-flush), streamed TTFT p99 vs the stated budget,
      and the front door's crash-safe netlog must validate (every
      accepted rid terminated exactly once).
    - socket chaos: the PR 12 battery over real sockets — one replica
      process SIGSTOPped until its breaker opens, SIGCONT + cooldown
      and the deliberate half-open probe close it (full
      open→half_open→closed cycle); another replica process is
      ``kill -9``'ed mid-burst — ejected on consecutive transport
      failures, its in-flight requests redriven exactly-once with
      bit-identical outputs, 0 requests lost, and the eject postmortem
      dumped from the CLIENT-side flight recorder (the process that
      could have testified is gone).

    Emits BENCH_NET.json (schema self-validated) next to this file
    (dryrun: /tmp) plus the netlog JSONL the CI validator replays."""
    import os
    import signal

    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.serving import fleet
    from paddle_tpu.serving.fleet import net
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.resilience.retry import RetryPolicy

    if dryrun:
        config = dict(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=2, ffn_size=64, max_position=64,
                      dropout=0.0, attn_impl="xla")
        n_req, slots, page_size, chunk, cap = 8, 2, 4, 8, 8
        len_set = (4, 9, 12)
        ttft_budget = 30.0   # smoke box: schema/plumbing, not latency
        decode_block = 4
    else:
        # CPU measurement config: sized so THREE subprocess warmups fit
        # a CI box; a single replica is saturated by the burst
        config = dict(vocab_size=1024, hidden_size=256, num_layers=4,
                      num_heads=8, ffn_size=1024, max_position=192,
                      dropout=0.0, attn_impl="xla")
        n_req, slots, page_size, chunk, cap = 12, 4, 16, 32, 24
        len_set = (16, 32, 48)
        ttft_budget = 15.0
        decode_block = 8
    hi = max(len_set)
    cap_stream = 2 * cap          # long decode: >=2 partial deliveries
    engine_kwargs = dict(num_slots=slots, page_size=page_size,
                         max_tokens_per_slot=hi + cap_stream,
                         prefill_chunk=chunk, decode_block=decode_block,
                         attn_impl="lax", ttft_budget_s=ttft_budget)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(1, config["vocab_size"],
                            int(n)).astype(np.int32)
               for n in rng.choice(len_set, n_req)]

    reg = obs.MetricsRegistry()
    tracer = obs.Tracer(capacity=65536)
    leg_tel = {"steps": 0, "dt": 0.0}

    def burst(router):
        frids = [router.submit(p, cap) for p in prompts]
        steps = 0
        t0 = time.perf_counter()
        while not router.idle():
            router.step()
            steps += 1
            if steps > 1_000_000:
                raise RuntimeError("net burst did not converge")
        dt = time.perf_counter() - t0
        leg_tel["steps"], leg_tel["dt"] = steps, dt
        outs = [router.result(f) for f in frids]
        if any(o is None for o in outs):
            raise RuntimeError("net burst lost requests")
        return outs, dt

    # --- local baseline: the SAME weights/config, in-process ----------
    cfg = GPTConfig.tiny(**config)
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))

    def local_replica(i):
        eng = serving.ServingEngine(model, params,
                                    registry=obs.MetricsRegistry(),
                                    tracer=tracer, **engine_kwargs)
        return fleet.LocalReplica(eng, name=f"local{i}").warmup()

    router_local = fleet.FleetRouter([local_replica(i) for i in (0, 1)],
                                     registry=reg, tracer=tracer, seed=3)
    ref_outs, local_dt = burst(router_local)
    total_tokens = sum(len(o) for o in ref_outs)
    local_tps = total_tokens / max(local_dt, 1e-9)

    # --- spawn the replica processes (in parallel: warmup dominates) --
    from concurrent.futures import ThreadPoolExecutor
    names = ("netA", "netB", "netC")
    with ThreadPoolExecutor(len(names)) as ex:
        spawned = list(ex.map(
            lambda nm: net.spawn_replica_server(
                config=config, engine=engine_kwargs, seed=0, name=nm),
            names))
    procs = {nm: proc for nm, (proc, _a) in zip(names, spawned)}
    addrs = {nm: addr for nm, (_p, addr) in zip(names, spawned)}
    try:
        # --- transport leg: 2-process fleet, bit-identical outputs ----
        reps_net = [net.NetReplica(addrs[nm], name=nm, registry=reg)
                    for nm in ("netA", "netB")]
        router_net = fleet.FleetRouter(reps_net, registry=reg,
                                       tracer=tracer, seed=3)
        rc0 = [int(r.health().get("recompiles", 0)) for r in reps_net]
        net_outs, net_dt = burst(router_net)
        rc1 = [int(r.health().get("recompiles", 0)) for r in reps_net]
        steady_recompiles = sum(b - a for a, b in zip(rc0, rc1))
        parity_ok = all(np.array_equal(r, o)
                        for r, o in zip(ref_outs, net_outs))
        net_tps = total_tokens / max(net_dt, 1e-9)
        overhead_ms = (net_dt - local_dt) / max(total_tokens, 1) * 1e3
        rpc_calls = sum(r.calls_total for r in reps_net)

        # --- streaming leg: FrontDoor over the net fleet --------------
        jpath = net_json_path(dryrun)
        netlog = (jpath[:-5] if jpath.endswith(".json") else jpath) \
            + ".netlog.jsonl"
        if os.path.exists(netlog):
            os.remove(netlog)       # this run's ledger only
        door = net.FrontDoor(router_net, netlog_path=netlog,
                             registry=reg).start()
        stream_n = 4
        partials, ttfts = [], []
        try:
            for i in range(stream_n):
                cli = net.FrontDoorClient(door.address)
                try:
                    r = cli.generate(prompts[i % len(prompts)],
                                     cap_stream, tag=f"s{i}",
                                     timeout_s=600.0)
                finally:
                    cli.close()
                if r["tokens"] is None:
                    raise RuntimeError(
                        f"stream request {i} rejected: {r['reject']}")
                if r["streamed"] != r["tokens"][:len(r["streamed"])]:
                    raise RuntimeError(
                        "streamed tokens diverge from the final result")
                partials.append(r["partials"])
                ttfts.append(r["ttft_s"])
        finally:
            door.close()            # terminal-logs anything live
        stream_p99 = float(np.percentile(ttfts, 99))
        netlog_summary = net.validate_netlog_file(
            netlog, require_requests=stream_n)

        # --- socket chaos: breaker cycle (SIGSTOP) + kill -9 ----------
        fast_retry = RetryPolicy(max_attempts=2, base_delay_s=0.05,
                                 max_delay_s=0.2, deadline_s=2.0,
                                 retry_on=(OSError, TimeoutError))
        chaos_reps = {nm: net.NetReplica(
            addrs[nm], name=nm, call_timeout_s=0.75, retry=fast_retry,
            registry=reg) for nm in names}
        fpol = fleet.FaultPolicy(max_consecutive_failures=8,
                                 probe_timeout_s=120.0,
                                 breaker_threshold=2,
                                 breaker_cooldown_s=0.3, max_redrives=4)
        router_x = fleet.FleetRouter(list(chaos_reps.values()),
                                     registry=reg, tracer=tracer,
                                     seed=17, faults=fpol)

        def transitions_of(nm):
            return [(old, new) for (n, old, new)
                    in router_x.breaker_transitions if n == nm]

        # phase 1: stop netC's process; router probes time out (a hung
        # host IS a transport failure), breaker opens well under the
        # death threshold; resume + cooldown + the deliberate half-open
        # probe close it again — the full cycle over a real socket
        os.kill(procs["netC"].pid, signal.SIGSTOP)
        for _ in range(6):
            router_x.step()
            if ("closed", "open") in transitions_of("netC"):
                break
        else:
            raise RuntimeError("chaos: netC breaker never opened")
        os.kill(procs["netC"].pid, signal.SIGCONT)
        time.sleep(fpol.breaker_cooldown_s + 0.05)
        probe_frids = [router_x.submit(rng.integers(
            1, config["vocab_size"], min(len_set)).astype(np.int32), 4)
            for _ in range(3)]
        router_x.run_until_idle(max_steps=1_000_000)
        cycle = [("closed", "open"), ("open", "half_open"),
                 ("half_open", "closed")]
        it = iter(transitions_of("netC"))
        breaker_cycle_ok = all(t in it for t in cycle)  # ordered subseq

        # phase 2: kill -9 netB mid-burst — ejected on consecutive
        # transport failures, requests redriven, outputs bit-identical
        frids_x = [router_x.submit(p, cap) for p in prompts]
        victim_live = [frid for frid, (rep, _l)
                       in router_x._where.items()
                       if rep is chaos_reps["netB"]]
        for _ in range(200):        # let netB emit some tokens first
            router_x.step()
            if any(router_x.progress(f) for f in victim_live):
                break
        procs["netB"].kill()        # SIGKILL: the real dead socket
        procs["netB"].wait()
        steps = 0
        while not router_x.idle():
            router_x.step()
            steps += 1
            if steps > 1_000_000:
                raise RuntimeError("chaos burst did not converge")
        chaos_outs, chaos_shed, chaos_lost = [], 0, 0
        for f in frids_x:
            o = router_x.result(f)
            chaos_outs.append(o)
            if o is None:
                if router_x.reject_reason(f) is not None:
                    chaos_shed += 1
                else:
                    chaos_lost += 1
        for f in probe_frids:       # no-silent-loss covers ALL
            if router_x.result(f) is None \
                    and router_x.reject_reason(f) is None:
                chaos_lost += 1
        chaos_parity = all(
            o is not None and np.array_equal(r, o)
            for r, o in zip(net_outs, chaos_outs))
        bundles = router_x.postmortems()
        for b in bundles:
            obs.validate_postmortem_bundle(b)
        pm_reasons = sorted({b["reason"] for b in bundles})
        if "eject" not in pm_reasons:
            raise RuntimeError("chaos: kill -9 shipped no eject "
                               f"postmortem (saw {pm_reasons})")
        chaos = {
            "lost_requests": int(chaos_lost),
            "redrive_parity": bool(chaos_parity),
            "redrives": int(router_x.redrives_total),
            "ejected": int(router_x.ejected_total),
            "shed_structured": int(chaos_shed),
            "breaker_cycle_ok": bool(breaker_cycle_ok),
            "breaker_transitions": [
                f"{nm}:{old}->{new}" for (nm, old, new)
                in router_x.breaker_transitions],
            "postmortems": len(bundles),
            "postmortem_reasons": pm_reasons,
            "postmortem_valid": True,       # validated above, or raised
        }
        for r in list(chaos_reps.values()) + reps_net:
            r.close()
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                try:
                    os.kill(proc.pid, signal.SIGCONT)  # if still stopped
                except OSError:
                    pass
                proc.kill()
                proc.wait()

    result = {
        "metric": "net_router_tokens_per_sec",
        "value": round(net_tps, 2),
        "unit": "tokens/s",
        # 1.0 == transport costs nothing vs in-process; the gates that
        # actually bind are parity / chaos / streaming, asserted below
        "vs_baseline": round(net_tps / max(local_tps, 1e-9), 4),
        "net_tokens_per_sec": round(net_tps, 2),
        "local_tokens_per_sec": round(local_tps, 2),
        "transport_overhead_ms_per_token": round(overhead_ms, 4),
        "transport_parity_ok": bool(parity_ok),
        "wire_codec": net.default_codec(),
        "rpc_calls_total": int(rpc_calls),
        "stream_requests": stream_n,
        "stream_partials_min": int(min(partials)),
        "stream_ttft_p99_s": round(stream_p99, 6),
        "ttft_budget_s": ttft_budget,
        "ttft_slo_met": bool(stream_p99 <= ttft_budget),
        "netlog": os.path.basename(netlog),
        "netlog_valid": netlog_summary,
        "steady_state_recompiles": int(steady_recompiles),
        "chaos": chaos,
        "num_requests": n_req,
        "replica_slots": slots,
        "decode_cap": cap,
        "device": getattr(dev, "device_kind", dev.platform),
        "dryrun": bool(dryrun),
        "_telemetry": {"steps": leg_tel["steps"], "dt": leg_tel["dt"],
                       "examples_per_step": slots,
                       "tokens_per_step": total_tokens
                       / max(leg_tel["steps"], 1)},
    }
    missing = [k for k in NET_SCHEMA if k not in result]
    if missing:
        raise RuntimeError(f"BENCH_NET schema self-check failed: "
                           f"missing {missing}")
    missing_chaos = [k for k in NET_CHAOS_SCHEMA if k not in chaos]
    if missing_chaos:
        raise RuntimeError(f"BENCH_NET chaos section self-check "
                           f"failed: missing {missing_chaos}")
    if not parity_ok:
        raise RuntimeError("transport parity broken: the net fleet's "
                           "greedy outputs differ from in-process")
    if steady_recompiles != 0:
        raise RuntimeError(
            f"replica processes recompiled {steady_recompiles}x in "
            "steady state — server-side warmup is not covering the "
            "serving shapes")
    if min(partials) < 2:
        raise RuntimeError(
            f"streaming leg delivered min {min(partials)} partial "
            "frames — the front door is buffering, not streaming")
    if chaos["lost_requests"] != 0:
        raise RuntimeError(
            f"socket chaos lost {chaos['lost_requests']} requests "
            "silently — the no-silent-loss contract broke")
    if not chaos["redrive_parity"]:
        raise RuntimeError("socket-chaos redrive parity broken: "
                           "redriven outputs differ")
    if chaos["ejected"] < 1 or chaos["redrives"] < 1:
        raise RuntimeError("socket chaos ejected/redrove nothing — "
                           "the kill -9 injection is dead")
    if not chaos["breaker_cycle_ok"]:
        raise RuntimeError(
            f"breaker never completed open->half_open->closed over "
            f"the socket (saw {chaos['breaker_transitions']})")
    committed = {k: v for k, v in result.items() if k != "_telemetry"}
    with open(jpath, "w") as f:
        json.dump(committed, f, indent=2)
    result["bench_json"] = jpath
    return result


SERVING_SCHEMA = ("metric", "value", "unit", "vs_baseline",
                  "decode_tokens_per_sec", "baseline_tokens_per_sec",
                  "speedup_vs_dense_loop", "end_to_end_tokens_per_sec",
                  "end_to_end_speedup", "decode_seconds_engine",
                  "decode_seconds_dense", "prefill_seconds_engine",
                  "prefill_seconds_dense", "ttft_mean_s", "ttft_max_s",
                  "ttft_p50_s", "ttft_p90_s", "ttft_p99_s",
                  "ttft_interactive_p99_s",
                  "ttft_budget_s", "ttft_slo_met",
                  "queue_wait_p50_s", "queue_wait_p90_s",
                  "queue_wait_p99_s", "admit_to_first_token_p99_s",
                  "slo_burn_rate", "slo_alerts_total",
                  "trace_json", "trace_spans",
                  "prefix_variant",
                  "tokens_per_hbm_byte", "tokens_per_hbm_byte_bf16",
                  "quant_static_bytes_ratio", "quant_speedup",
                  "quant_variant", "spec_accept_rate", "spec_variant",
                  "mean_slot_occupancy", "page_utilization_peak",
                  "decode_recompiles_after_warmup", "num_requests",
                  "num_slots", "page_size", "device")


def serving_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_SERVING",
                              "/tmp/BENCH_SERVING.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_SERVING",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_SERVING.json"))


def run_bench_serving(dev, dryrun=False):
    """Continuous-batching serving throughput (ISSUE 4 acceptance): the
    paged ServingEngine versus looping ``GPT.generate(use_cache=True)``
    over the SAME requests — mixed prompt lengths, shared decode cap,
    early-EOS mix. The engine evicts a sequence the step EOS lands and
    backfills the slot; ``generate``'s fixed-trip device loop cannot
    stop early (the lock-step waste the ISSUE motivates paging with),
    so the dense loop burns the full cap on every request. Throughput
    counts USEFUL tokens (up to EOS — both sides emit identical greedy
    streams, so useful counts are identical by construction). Random
    init has no trained stop behavior, so per-request EOS ids are
    derived from reference rollouts (first occurrence of a real emitted
    token near a target stop position); ~1/6 of requests get no EOS and
    run to cap — the long tail. Both sides are warmed (compiles
    excluded). ``vs_baseline`` is speedup/2.0 — 1.0 == the >=2x target.
    ISSUE 6 additions: TTFT/queue-wait p50/p90/p99 percentiles against a
    stated ``ttft_budget_s`` (the machine-checkable SLO), split queue/
    prefill latency accounting, and a shared-prefix variant proving
    prefix/page sharing (prefill tokens computed < prompt tokens
    submitted). Emits BENCH_SERVING.json (schema self-validated, hard-
    fails on any steady-state recompile in either variant) next to this
    file (dryrun: /tmp)."""
    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPT, GPTConfig

    on_tpu = dev.platform == "tpu"
    if on_tpu:
        cfg = GPTConfig(vocab_size=32768, hidden_size=1024, num_layers=12,
                        num_heads=16, ffn_size=4096, max_position=512,
                        dropout=0.0)
        n_req, num_slots, page_size, chunk, cap = 48, 16, 16, 64, 96
        len_set = (16, 32, 48, 64, 96, 128, 192, 256)
        attn_impl = "pallas"
        ttft_budget = 1.0
        # 8 full pages + an 8-token tail: sharing is page-aligned, so
        # followers map the 8 full pages and recompute the tail (a
        # prefix's partial page is completed by the publisher's own
        # suffix before publication, so it never tail-shares)
        shared_prefix_len, shared_tails = 136, (16, 32, 64)
    elif dryrun:
        cfg = GPTConfig.tiny(vocab_size=128, hidden_size=32, num_layers=2,
                             num_heads=2, ffn_size=64, max_position=64,
                             dropout=0.0, attn_impl="xla")
        n_req, num_slots, page_size, chunk, cap = 6, 4, 4, 8, 8
        len_set = (4, 9, 17, 24)
        attn_impl = "lax"
        ttft_budget = 30.0   # smoke box: schema/plumbing, not latency
        shared_prefix_len, shared_tails = 10, (2, 3, 4)   # 2 pages + tail
    else:
        # CPU measurement config: weight-heavy (LLM decode is weight-
        # bound — params >> per-step KV traffic) so batching amortizes
        # weight reads the way real serving does; bf16 KV pages on both
        # sides (generate gets cache_dtype too). Prompt lengths come
        # from a small bucket set, as a shape-bucketing front end would
        # deliver them.
        cfg = GPTConfig(vocab_size=1024, hidden_size=512, num_layers=6,
                        num_heads=8, ffn_size=2048, max_position=320,
                        dropout=0.0, attn_impl="xla")
        n_req, num_slots, page_size, chunk, cap = 32, 8, 16, 64, 64
        len_set = (16, 32, 48, 64, 96, 128, 192, 256)
        attn_impl = "lax"
        ttft_budget = 4.0    # stated CPU SLO: interactive-lane p99 TTFT
        # 8 full pages + an 8-token tail: sharing is page-aligned, so
        # followers map the 8 full pages and recompute the tail (a
        # prefix's partial page is completed by the publisher's own
        # suffix before publication, so it never tail-shares)
        shared_prefix_len, shared_tails = 136, (16, 32, 64)

    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    lens = rng.choice(len_set, n_req)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).astype(np.int32)
               for n in lens]
    lo, hi = min(len_set), max(len_set)
    cache_dtype = jnp.bfloat16 if not on_tpu else None

    reg = obs.MetricsRegistry()
    # request-lifecycle tracing is ON for the whole bench (ISSUE 10
    # acceptance: the bench emits a Perfetto-loadable .trace.json in
    # which a request's spans reconstruct its full lifecycle) — tracing
    # is host-side only, so the zero-recompile assertions below also
    # prove the invariant holds WITH tracing enabled
    tracer = obs.Tracer(capacity=32768)
    # main mix runs WITHOUT prefix sharing: the prompts are distinct, and
    # the engine-vs-dense comparison must not quietly reuse pages across
    # the two timing passes; sharing is measured by the prefix variant.
    # ttft_budget_s arms the SLO burn-rate monitor over the same budget
    # the percentile keys are judged against.
    eng = serving.ServingEngine(
        model, params, num_slots=num_slots, page_size=page_size,
        max_tokens_per_slot=hi + cap, prefill_chunk=chunk,
        attn_impl=attn_impl, cache_dtype=cache_dtype, registry=reg,
        prefix_sharing=False, tracer=tracer, ttft_budget_s=ttft_budget)
    # startup compiles happen here (every gather bucket + the prefill
    # chunk), so everything timed below is steady-state serving
    eng.warmup()

    # reference prefixes (also an engine warm pass): early stops land in
    # the first few tokens of a greedy stream, so a short prefix rollout
    # is enough to pick each request's EOS id
    ref_new = min(16, cap)
    streams = eng.generate_many(prompts, ref_new, max_steps=1_000_000)
    eos_ids = []
    useful = []
    for i, t in enumerate(streams):
        if i % 6 == 0:          # the no-EOS long tail: run to cap
            eos_ids.append(None)
            useful.append(cap)
            continue
        target = int(rng.integers(2, ref_new))
        first = {}              # token -> first-occurrence index
        for j, tok in enumerate(t.tolist()):
            first.setdefault(tok, j)
        tok, j = min(first.items(), key=lambda kv: abs(kv[1] - target))
        eos_ids.append(int(tok))
        useful.append(j + 1)
    total_useful = int(sum(useful))

    det = obs.RecompileDetector("serving_bench", warmup=0, registry=reg)

    def engine_pass():
        for m in ("serving_ttft_seconds", "serving_queue_wait_seconds",
                  "serving_admit_to_first_token_seconds",
                  "serving_decode_step_seconds",
                  "serving_prefill_step_seconds"):
            reg.unregister(m)   # this pass's samples only
        occ = []
        peak_util = 0.0
        rids = [eng.submit(p, cap, eos_id=e)
                for p, e in zip(prompts, eos_ids)]
        t0 = time.perf_counter()
        while not eng.scheduler.idle():
            eng.step()
            # the gauges hold occupancy/utilization as the decode batch
            # ran (pre-eviction); the cache itself is already drained
            occ.append(reg.gauge("serving_slot_occupancy").value())
            peak_util = max(peak_util,
                            reg.gauge("serving_page_utilization").value())
        dt = time.perf_counter() - t0
        streams = []
        for r, u in zip(rids, useful):
            got = eng.result(r)
            assert got is not None and len(got) == u, \
                "engine/ref divergence"
            streams.append(got)
        ttft_h = reg.histogram("serving_ttft_seconds")
        qw_h = reg.histogram("serving_queue_wait_seconds")
        return {
            "streams": streams,
            "dt": dt,
            "decode_s": reg.histogram("serving_decode_step_seconds"
                                      ).summary()["sum"],
            "prefill_s": reg.histogram("serving_prefill_step_seconds"
                                       ).summary()["sum"],
            "ttft": ttft_h.summary(),
            # TTFT/queue-wait tails (p50/p90/p99): the machine-checkable
            # SLO surface (bucket-interpolated, clamped to observed
            # min/max)
            "ttft_q": {q: ttft_h.quantile(q) for q in (0.5, 0.9, 0.99)},
            "qw_q": {q: qw_h.quantile(q) for q in (0.5, 0.9, 0.99)},
            "a2f_p99": reg.histogram(
                "serving_admit_to_first_token_seconds").quantile(0.99),
            "occ": occ, "peak_util": peak_util,
        }

    # two passes, best wall-clock kept: a 2-core CI box sees ambient
    # load spikes that would otherwise masquerade as engine regressions
    ep = min((engine_pass() for _ in range(2)), key=lambda r: r["dt"])

    # --- SLO probe pass: the same batch burst on the "batch" lane, with
    # interactive probes trickled in WHILE the engine is saturated. The
    # SLO scheduler's priority lanes put a probe at the queue head, so
    # its TTFT is slot-turnover + one prefill chunk — not the whole
    # backlog. ttft_slo_met is judged on the interactive lane: that is
    # the traffic the budget exists for (the batch burst's own TTFT is
    # backlog-dominated by construction and reported separately above).
    probe_interval = 2 if dryrun else 3
    n_probe = max(4, num_slots)
    probe_rids = []
    for p, e in zip(prompts, eos_ids):
        eng.submit(p, cap, eos_id=e, lane="batch")
    steps = 0
    while not eng.scheduler.idle():
        eng.step()
        steps += 1
        if len(probe_rids) < n_probe and steps % probe_interval == 0:
            pr = rng.integers(1, cfg.vocab_size, int(lo)).astype(np.int32)
            probe_rids.append(eng.submit(pr, 8, lane="interactive"))
    probe_ttfts = [eng.request_stats(r)["ttft_s"] for r in probe_rids]
    interactive_p99 = float(np.percentile(probe_ttfts, 99))
    det.check()
    occ, peak_util, ttft = ep["occ"], ep["peak_util"], ep["ttft"]
    dt_engine = ep["dt"]
    eng_decode_s = ep["decode_s"]
    eng_prefill_s = ep["prefill_s"]
    engine_tps = total_useful / max(eng_decode_s, 1e-9)
    engine_e2e = total_useful / dt_engine

    # --- dense loop: same requests through generate(use_cache=True),
    # one call per request (mixed prompt lengths cannot batch correctly
    # through a padded lock-step generate). generate has no EOS exit,
    # so every request decodes the full cap; compile time excluded by a
    # warmup pass over every shape.
    def dense_fn(mnew):
        return jax.jit(lambda pp, ids: model.generate(
            pp, ids, max_new_tokens=mnew, use_cache=True,
            cache_dtype=cache_dtype))

    fns, pf_times = {}, {}
    full = dense_fn(cap)
    pf = dense_fn(1)   # prefill + one token: the dense prefill cost
    for p in prompts:
        if len(p) in fns:
            continue
        x = jnp.asarray(p)[None]
        full(params, x).block_until_ready()         # compile cap graph
        pf(params, x).block_until_ready()           # compile prefill probe
        t0 = time.perf_counter()
        pf(params, x).block_until_ready()
        pf_times[len(p)] = time.perf_counter() - t0
        fns[len(p)] = True
    def dense_pass():
        t0 = time.perf_counter()
        for p in prompts:
            full(params, jnp.asarray(p)[None]).block_until_ready()
        return time.perf_counter() - t0

    dt_dense = min(dense_pass() for _ in range(2))
    # decode-phase split: prefill measured per unique prompt length via
    # the max_new=1 probe (slightly OVERcounts dense prefill — one
    # decode step rides along — so the reported speedup is conservative)
    dense_prefill_s = sum(pf_times[len(p)] for p in prompts)
    dense_decode_s = max(dt_dense - dense_prefill_s, 1e-9)
    dense_tps = total_useful / dense_decode_s
    dense_e2e = total_useful / dt_dense

    speedup = engine_tps / max(dense_tps, 1e-9)
    e2e_speedup = engine_e2e / max(dense_e2e, 1e-9)

    # --- shared-prefix variant: every request carries the same system
    # prompt; prefix sharing must prefill it once (well, once per slot
    # wave — slots admitted before the first publisher finishes cannot
    # share yet) and map the published pages into every follower, so
    # prefill tokens COMPUTED land well under prompt tokens SUBMITTED.
    reg2 = obs.MetricsRegistry()
    eng2 = serving.ServingEngine(
        model, params, num_slots=num_slots, page_size=page_size,
        max_tokens_per_slot=hi + cap, prefill_chunk=chunk,
        attn_impl=attn_impl, cache_dtype=cache_dtype, registry=reg2,
        prefix_sharing=True, tracer=tracer)
    eng2.warmup()
    det2 = obs.RecompileDetector("serving_bench_prefix", warmup=0,
                                 registry=reg2)
    sys_prompt = rng.integers(1, cfg.vocab_size,
                              shared_prefix_len).astype(np.int32)
    # every 4th request repeats an earlier prompt verbatim (regenerate /
    # retry traffic) — THIS is what exercises copy-on-write: once the
    # original has finished and published its final partial page as a
    # tail, the duplicate maps it and must CoW before appending its
    # first decode token. Duplicates prefer a non-page-aligned source
    # (an aligned prompt publishes only full pages — nothing to CoW);
    # an original still in flight when its duplicate is admitted shares
    # full pages only, so cow_copies is demonstrative, not asserted.
    prompts2 = []
    for i, t in enumerate(rng.choice(shared_tails, n_req)):
        if i % 4 == 3:
            cands = [q for q in prompts2 if len(q) % page_size]
            pool = cands or prompts2
            prompts2.append(pool[int(rng.integers(len(pool)))].copy())
        else:
            prompts2.append(np.concatenate(
                [sys_prompt, rng.integers(1, cfg.vocab_size, int(t))
                 .astype(np.int32)]))
    variant_new = min(8, cap)
    t0 = time.perf_counter()
    eng2.generate_many(prompts2, variant_new, max_steps=1_000_000)
    dt_prefix = time.perf_counter() - t0
    det2.check()
    submitted2 = int(sum(len(p) for p in prompts2))
    computed2 = int(reg2.counter("serving_prefill_tokens_total").value())
    shared2 = int(reg2.counter("serving_prefix_shared_tokens_total"
                               ).value())
    ttft2 = reg2.histogram("serving_ttft_seconds")
    prefix_variant = {
        "num_requests": n_req,
        "shared_prefix_len": int(shared_prefix_len),
        "prompt_tokens_submitted": submitted2,
        "prefill_tokens_computed": computed2,
        "prefix_tokens_shared": shared2,
        "prefill_saved_frac": round(1.0 - computed2 / max(submitted2, 1),
                                    4),
        "cow_copies": int(eng2.cache.cow_copies_total),
        "wall_seconds": round(dt_prefix, 3),
        "ttft_p99_s": round(ttft2.quantile(0.99), 6),
        "recompiles": det2.recompiles,
    }

    # --- int8 paged-KV variant (ISSUE 13): the same requests through an
    # int8 page pool with per-token-row scales, attending via the
    # dequant-attend kernels. Tokens may deviate from the bf16 stream
    # only within the quantization quality budget (quant_token_match
    # reports the agreement honestly); throughput is its own stream's
    # tokens over its own decode time, best-of-2 like the baseline.
    reg_q = obs.MetricsRegistry()
    eng_q = serving.ServingEngine(
        model, params, num_slots=num_slots, page_size=page_size,
        max_tokens_per_slot=hi + cap, prefill_chunk=chunk,
        attn_impl=attn_impl, cache_dtype=jnp.int8, registry=reg_q,
        prefix_sharing=False, tracer=obs.Tracer(enabled=False))
    eng_q.warmup(cost_gauges=False)
    det_q = obs.RecompileDetector("serving_bench_int8", warmup=0,
                                  registry=reg_q)

    def quant_pass():
        reg_q.unregister("serving_decode_step_seconds")
        rids_q = [eng_q.submit(p, cap, eos_id=e)
                  for p, e in zip(prompts, eos_ids)]
        while not eng_q.scheduler.idle():
            eng_q.step()
        outs = [eng_q.result(r) for r in rids_q]
        dq = reg_q.histogram("serving_decode_step_seconds"
                             ).summary()["sum"]
        return dq, outs

    qp = min((quant_pass() for _ in range(2)), key=lambda r: r[0])
    det_q.check()
    dq_decode_s, outs_q = qp
    tokens_q = int(sum(len(o) for o in outs_q))
    quant_tps = tokens_q / max(dq_decode_s, 1e-9)
    agree = compared = 0
    for base_t, q_t in zip(ep["streams"], outs_q):
        m = min(len(base_t), len(q_t))
        agree += int((np.asarray(base_t[:m]) == np.asarray(q_t[:m])).sum())
        compared += m
    quant_speedup = quant_tps / max(engine_tps, 1e-9)
    quant_variant = {
        "decode_tokens_per_sec": round(quant_tps, 2),
        "decode_seconds": round(dq_decode_s, 3),
        "tokens": tokens_q,
        "token_match_vs_bf16": round(agree / max(compared, 1), 4),
        "recompiles": det_q.recompiles,
    }

    # --- speculative variant (ISSUE 13): draft proposes spec_k tokens
    # per slot, the target verifies them in ONE batched-prefill-shaped
    # step. Random init has no trained small draft, so the draft IS the
    # target (self-draft): accept rate ~1.0 exercises the long-accept
    # path and the mechanism's overhead honestly. The acceptance GATE:
    # greedy streams must be BIT-EXACT vs the non-speculative engine.
    reg_s = obs.MetricsRegistry()
    eng_s = serving.ServingEngine(
        model, params, num_slots=num_slots, page_size=page_size,
        max_tokens_per_slot=hi + cap, prefill_chunk=chunk,
        attn_impl=attn_impl, cache_dtype=cache_dtype, registry=reg_s,
        tracer=obs.Tracer(enabled=False), draft_model=model,
        draft_params=params, spec_k=4)
    eng_s.warmup(cost_gauges=False)
    det_s = obs.RecompileDetector("serving_bench_spec", warmup=0,
                                  registry=reg_s)

    def spec_pass():
        # the counters are monotonic across passes: report THIS pass's
        # deltas so the committed proposed/accepted match the same
        # single pass the timing and streams come from
        p0 = reg_s.counter("serving_spec_proposed_total").value()
        a0 = reg_s.counter("serving_spec_accepted_total").value()
        reg_s.unregister("serving_decode_step_seconds")
        rids_s = [eng_s.submit(p, cap, eos_id=e)
                  for p, e in zip(prompts, eos_ids)]
        while not eng_s.scheduler.idle():
            eng_s.step()
        outs = [eng_s.result(r) for r in rids_s]
        ds = reg_s.histogram("serving_decode_step_seconds"
                             ).summary()["sum"]
        proposed = reg_s.counter("serving_spec_proposed_total"
                                 ).value() - p0
        accepted = reg_s.counter("serving_spec_accepted_total"
                                 ).value() - a0
        return ds, outs, proposed, accepted

    sp = min((spec_pass() for _ in range(2)), key=lambda r: r[0])
    det_s.check()
    ds_decode_s, outs_s, spec_proposed, spec_accepted = sp
    for base_t, s_t in zip(ep["streams"], outs_s):
        if not np.array_equal(base_t, s_t):
            raise RuntimeError(
                "speculative greedy diverged from non-speculative "
                "greedy — the bit-exactness gate failed")
    spec_accept_rate = spec_accepted / max(spec_proposed, 1)
    tokens_s = int(sum(len(o) for o in outs_s))
    spec_variant = {
        "decode_tokens_per_sec": round(tokens_s /
                                       max(ds_decode_s, 1e-9), 2),
        "decode_seconds": round(ds_decode_s, 3),
        "spec_k": eng_s.spec_k,
        "proposed": int(spec_proposed),
        "accepted": int(spec_accepted),
        "draft": "self (random init has no trained small draft; "
                 "exercises the long-accept path)",
        "exact_vs_nonspeculative": True,
        "recompiles": det_s.recompiles,
    }

    # --- static tokens-per-HBM-byte probe (ISSUE 13 acceptance): lower
    # the decode step of a bf16 and an int8 engine with an identical,
    # KV-dominated pool through the PR 7 cost model, and read each
    # step's KV-cache HBM bytes from the CostReport's argument
    # accounting. tokens_per_hbm_byte = the live tokens the pool hosts
    # per byte of KV HBM the decode step holds — the serving-capacity
    # number the int8 pool doubles (per token: 2x H*Dh bytes bf16 vs
    # H*Dh + 8 scale bytes int8).
    from paddle_tpu import analysis
    from paddle_tpu.models.gpt import GPTConfig as _Cfg
    pcfg = _Cfg(vocab_size=256, hidden_size=128, num_layers=2,
                num_heads=4, ffn_size=256, max_position=1024,
                dropout=0.0, attn_impl="xla")
    pmodel = GPT(pcfg)
    pparams = pmodel.init(jax.random.PRNGKey(2))
    p_pages, p_ps = 2049, 16

    def probe(dtype):
        engp = serving.ServingEngine(
            pmodel, pparams, num_slots=8, page_size=p_ps,
            max_tokens_per_slot=512, num_pages=p_pages,
            attn_impl="lax", cache_dtype=dtype, decode_block=8)
        c = engp.cache.config
        pages_abs = analysis.abstractify(engp.cache.pages)
        args = (analysis.abstractify(engp.params), pages_abs,
                jax.ShapeDtypeStruct((8, 8), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32),
                jax.ShapeDtypeStruct((8,), jnp.int32))
        cost = analysis.estimate_cost(engp.decode_step, *args,
                                      name=f"decode_{dtype}")
        import math as _math
        kv_bytes = sum(
            _math.prod(a.shape) * jnp.dtype(a.dtype).itemsize
            for a in jax.tree_util.tree_leaves(pages_abs))
        # sanity: the KV pool really is inside the step's arg bytes
        assert cost.arg_bytes > kv_bytes > 0
        capacity_tokens = (c.num_pages - 1) * c.page_size
        return capacity_tokens / kv_bytes, cost

    tpb_int8, cost_int8 = probe(jnp.int8)
    tpb_bf16, cost_bf16 = probe(jnp.bfloat16)
    quant_static_ratio = tpb_int8 / tpb_bf16
    if quant_static_ratio < 1.8:
        raise RuntimeError(
            f"static tokens-per-HBM-byte ratio {quant_static_ratio:.3f} "
            "< 1.8x the bf16 baseline — the int8 pool lost its bytes "
            "advantage")

    # --- trace canary: a tiny engine with a deliberately starved page
    # pool + an EDF-boosted deadline, so the exported timeline ALWAYS
    # carries scheduler-decision annotations (sched_skip / sched_boost)
    # next to the measured passes' request lifecycles — the decisions
    # depend on saturation timing in the measured mix, the canary makes
    # them deterministic. Runs after det/det2.check(), on its own
    # registry, so its compiles never pollute the recompile accounting.
    ccfg = GPTConfig.tiny(vocab_size=64, hidden_size=16, num_layers=1,
                          num_heads=2, ffn_size=32, max_position=32,
                          dropout=0.0, attn_impl="xla")
    cmodel = GPT(ccfg)
    cparams = cmodel.init(jax.random.PRNGKey(1))
    eng3 = serving.ServingEngine(
        cmodel, cparams, num_slots=2, page_size=4,
        max_tokens_per_slot=16, num_pages=5, prefill_chunk=4,
        attn_impl="lax", registry=obs.MetricsRegistry(), tracer=tracer,
        prefix_sharing=False)
    eng3.warmup(cost_gauges=False)
    canary = np.arange(1, 9, dtype=np.int32)
    eng3.submit(canary, 8)                   # takes all 4 usable pages
    eng3.scheduler.note_ttft(10.0)           # seed the TTFT estimator
    # deadline < EWMA estimate -> at-risk -> sched_boost; no pages while
    # the first request runs -> sched_skip per admission pass
    eng3.submit(canary, 8, lane="interactive", ttft_deadline_s=5.0)
    csteps = 0
    while not eng3.scheduler.idle():
        eng3.step()
        csteps += 1
        if csteps > 10_000:
            raise RuntimeError("trace canary did not converge")

    # --- trace artifact: self-validate the Perfetto contract + the
    # lifecycle-reconstruction acceptance before writing it next to
    # BENCH_SERVING.json
    all_spans = tracer.spans()          # one ring snapshot, then index
    req_spans = [s for s in all_spans if s.name == "serving.request"]
    traces_by_name = {}
    for s in all_spans:
        traces_by_name.setdefault(s.name, set()).add(s.trace_id)
    ev_names = {e[1] for s in req_spans for e in s.events}
    for needed in ("submitted", "admitted", "first_token", "finished",
                   "prefix_shared", "sched_skip", "sched_boost"):
        if needed not in ev_names:
            raise RuntimeError(
                f"trace self-check: no {needed!r} event in any "
                "serving.request span")
    full = [s for s in req_spans if s.end is not None
            and s.trace_id in traces_by_name.get("serving.prefill_chunk",
                                                 ())
            and s.trace_id in traces_by_name.get("serving.decode_block",
                                                 ())]
    if not full:
        raise RuntimeError("trace self-check: no request trace "
                           "reconstructs queue->prefill->decode->finish")
    chrome = tracer.to_chrome()
    obs.chrome_trace_valid(chrome, require_events=len(full))
    jpath = serving_json_path(dryrun)
    trace_path = (jpath[:-5] if jpath.endswith(".json") else jpath) \
        + ".trace.json"
    with open(trace_path, "w") as f:
        json.dump(chrome, f)

    ttft_p = ep["ttft_q"]
    qw_p = ep["qw_q"]
    result = {
        "metric": "serving_decode_tokens_per_sec",
        "value": round(engine_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(speedup / 2.0, 4),  # 1.0 == the 2x target
        "decode_tokens_per_sec": round(engine_tps, 2),
        "baseline_tokens_per_sec": round(dense_tps, 2),
        "speedup_vs_dense_loop": round(speedup, 4),
        "end_to_end_tokens_per_sec": round(engine_e2e, 2),
        "end_to_end_speedup": round(e2e_speedup, 4),
        "decode_seconds_engine": round(eng_decode_s, 3),
        "decode_seconds_dense": round(dense_decode_s, 3),
        "prefill_seconds_engine": round(eng_prefill_s, 3),
        "prefill_seconds_dense": round(dense_prefill_s, 3),
        "ttft_mean_s": round(ttft.get("mean", 0.0), 6),
        "ttft_max_s": round(ttft.get("max", 0.0), 6),
        "ttft_p50_s": round(ttft_p[0.5], 6),
        "ttft_p90_s": round(ttft_p[0.9], 6),
        "ttft_p99_s": round(ttft_p[0.99], 6),
        "ttft_interactive_p99_s": round(interactive_p99, 6),
        "ttft_budget_s": ttft_budget,
        "ttft_slo_met": bool(interactive_p99 <= ttft_budget),
        "queue_wait_p50_s": round(qw_p[0.5], 6),
        "queue_wait_p90_s": round(qw_p[0.9], 6),
        "queue_wait_p99_s": round(qw_p[0.99], 6),
        "admit_to_first_token_p99_s": round(ep["a2f_p99"], 6),
        # burn-rate monitor state at bench end: the burst mix BLOWS the
        # interactive budget by construction (batch-lane TTFT is
        # backlog-dominated), so a nonzero alert count here is the
        # monitor working, not a failure
        "slo_burn_rate": round(eng.slo_monitor.burn["fast"], 4),
        "slo_alerts_total": eng.slo_monitor.alerts_total,
        "trace_json": trace_path,
        "trace_spans": len(tracer.spans()),
        "prefix_variant": prefix_variant,
        # ISSUE 13: quantized pool + speculative decoding. The static
        # keys come from the cost model (deterministic); the measured
        # keys are this box's wall clock, best-of-2.
        "tokens_per_hbm_byte": round(tpb_int8, 9),
        "tokens_per_hbm_byte_bf16": round(tpb_bf16, 9),
        "quant_static_bytes_ratio": round(quant_static_ratio, 4),
        "quant_speedup": round(quant_speedup, 4),
        "quant_variant": quant_variant,
        "spec_accept_rate": round(spec_accept_rate, 4),
        "spec_variant": spec_variant,
        "mean_slot_occupancy": round(float(np.mean(occ)), 4),
        "page_utilization_peak": round(peak_util, 4),
        "decode_recompiles_after_warmup": det.recompiles,
        "num_requests": n_req,
        "num_slots": num_slots,
        "page_size": page_size,
        "decode_cap": cap,
        "useful_tokens": total_useful,
        "mean_useful_per_request": round(total_useful / n_req, 2),
        "prompt_lens": [int(lo), int(hi)],
        "device": getattr(dev, "device_kind", dev.platform),
        "dryrun": bool(dryrun),
        "_telemetry": {"steps": len(occ), "dt": dt_engine,
                       "examples_per_step": num_slots,
                       "tokens_per_step": total_useful / max(len(occ), 1)},
    }

    missing = [k for k in SERVING_SCHEMA if k not in result]
    if missing:
        raise RuntimeError(f"BENCH_SERVING schema self-check failed: "
                           f"missing {missing}")
    if result["decode_recompiles_after_warmup"] != 0:
        raise RuntimeError("steady-state serving recompiled "
                           f"{det.recompiles}x — fixed-shape invariant "
                           "broken (decode or prefill bucket missed by "
                           "warmup)")
    if prefix_variant["recompiles"] != 0:
        raise RuntimeError("prefix-sharing variant recompiled "
                           f"{prefix_variant['recompiles']}x — CoW/"
                           "prefill shapes drifted")
    if quant_variant["recompiles"] != 0:
        raise RuntimeError("int8 variant recompiled "
                           f"{quant_variant['recompiles']}x — the "
                           "quantized decode/prefill buckets drifted")
    if spec_variant["recompiles"] != 0:
        raise RuntimeError("speculative variant recompiled "
                           f"{spec_variant['recompiles']}x — a "
                           "draft/verify bucket missed warmup")
    if not dryrun and quant_speedup < 1.0:
        raise RuntimeError(
            f"int8 decode tokens/s regressed vs the bf16 baseline "
            f"({quant_speedup:.3f}x) — the quantized path must be no "
            "worse on this box")
    import os
    path = serving_json_path(dryrun)
    committed = {k: v for k, v in result.items() if k != "_telemetry"}
    # the checked-in artifact must be portable across checkouts: the
    # trace sits next to this JSON, so record the basename (the stdout
    # result keeps the absolute path for run_ci / tooling)
    committed["trace_json"] = os.path.basename(trace_path)
    with open(path, "w") as f:
        json.dump(committed, f, indent=2)
    result["bench_json"] = path
    return result


KERNELS_SCHEMA = ("metric", "value", "unit", "vs_baseline", "kernels",
                  "impl", "tuner_cache_hits", "tuner_cache_misses",
                  "tuner_stale_entries", "committed_cache_entries",
                  "committed_cache_stale", "device", "dryrun")


def serving_tp_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_SERVING_TP",
                              "/tmp/BENCH_SERVING_TP.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_SERVING_TP",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_SERVING_TP.json"))


def run_bench_serving_tp(dev, dryrun=False):
    """Tensor-parallel paged decode scaling (ISSUE 15 acceptance) on a
    simulated tp=1/2/4 mesh of virtual CPU devices.

    Two legs per tp degree:

    - **Correctness leg** — the REAL sharded engine (``mesh=`` over tp
      devices, shard_map steps, per-shard page pools): greedy tokens
      must be IDENTICAL to the tp=1 engine on the same workload, zero
      recompiles after warmup, and the decode step's collective bytes
      come from the static CostReport (one psum per layer at the
      attention output — the allowlisted kind).
    - **Busy-time leg** — per-chip decode tokens/s via the probe engine
      (``tp_probe=True``: ONE shard's local computation on one device,
      collectives elided). Shards are symmetric, so one shard's wall
      time IS the per-chip critical path — the same honest accounting
      BENCH_ROUTER uses (max over replicas ≙ any shard); the elided
      collective payload is reported alongside from the CostReport so
      the omission is visible. tokens/s(tp) = decode tokens / the probe
      registry's ``serving_decode_step_seconds`` sum; best of 2 passes.

    The model is attention-heavy on purpose (long live contexts, small
    MLP): decode throughput at scale is bounded by per-chip KV
    bandwidth, which is exactly the term tp divides. Emits
    BENCH_SERVING_TP.json (schema self-validated; the >=1.6x tp=2 gate
    is asserted non-dryrun) next to this file (dryrun: /tmp)."""
    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.models.gpt import GPT, GPTConfig

    devs = jax.devices()
    if len(devs) < 4:
        raise RuntimeError(
            "serving_tp bench needs >= 4 devices (CI runs it on the "
            "virtual 8-device CPU mesh; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")
    if dryrun:
        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                        num_heads=8, ffn_size=64, max_position=320,
                        dropout=0.0, attn_impl="xla")
        n_req, num_slots, page_size, chunk, cap = 6, 4, 16, 32, 16
        len_set = (80, 144, 208)
        max_tokens = 240
    else:
        cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=3,
                        num_heads=8, ffn_size=64, max_position=640,
                        dropout=0.0, attn_impl="xla")
        n_req, num_slots, page_size, chunk, cap = 16, 8, 16, 32, 32
        len_set = (96, 160, 224, 320, 448)
        max_tokens = 480
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    lens = rng.choice(len_set, n_req)
    prompts = [rng.integers(1, cfg.vocab_size, int(n)).astype(np.int32)
               for n in lens]

    # anatomy probe cadence for the REAL sharded engines: every Nth
    # decode round replays on the collective-elided probe jit, so the
    # bench reports MEASURED exposed-collective time (not just the
    # CostReport's static payload)
    probe_every = 2 if dryrun else 8

    def make_engine(tp, probe=False):
        reg = obs.MetricsRegistry()
        eng = serving.ServingEngine(
            model, params, num_slots=num_slots, page_size=page_size,
            max_tokens_per_slot=max_tokens, prefill_chunk=chunk,
            attn_impl="lax", registry=reg,
            **({} if tp == 1 else
               {"tp": tp, "tp_probe": True} if probe else
               {"tp": tp, "anatomy_probe_every": probe_every}))
        eng.warmup(cost_gauges=False)
        return eng, reg

    def run_pass(eng):
        # eos=None: fixed work per request, so every tp degree (and
        # every probe) executes the identical step schedule
        return [np.asarray(t) for t in
                eng.generate_many(prompts, cap, eos_id=None)]

    def decode_busy(reg):
        return float(reg.histogram(
            "serving_decode_step_seconds").summary()["sum"])

    def decode_collective_bytes(eng):
        from paddle_tpu.analysis import cost_model
        c = eng.cache.config
        s_tot = eng.scheduler.num_slots
        w = eng._pow2_width(c.max_pages_per_slot)
        zeros = jnp.zeros((s_tot,), jnp.int32)
        args = (eng._step_params, eng.cache.pages,
                jnp.zeros((s_tot, w), jnp.int32), zeros, zeros, zeros)
        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), args)
        cost = cost_model.estimate_cost(eng.decode_step, *abstract,
                                        name=f"decode_tp{eng.tp}")
        # the psum sits inside the decode fori_loop BODY, so the
        # CostReport counts it once per loop iteration = once per
        # decode token per slot
        return int(cost.summary()["collective_bytes"])

    # Each engine's WHOLE lifecycle runs contiguously: the compile
    # listener is process-wide, so another engine's warmup compiles
    # would land in this engine's next recompile check otherwise.
    t_bench0 = time.perf_counter()
    decode_tokens = float(n_req * cap)

    def busy_leg(eng, reg):
        """Best-of-2 per-chip decode tokens/s (histogram-sum delta)."""
        best = 0.0
        for _ in range(2):
            before = decode_busy(reg)
            run_pass(eng)
            best = max(best, decode_tokens
                       / max(decode_busy(reg) - before, 1e-9))
        return round(best, 2)

    # --- tp=1: the baseline tokens AND the tp=1 busy time
    base_eng, base_reg = make_engine(1)
    baseline = run_pass(base_eng)
    tokps = {"1": busy_leg(base_eng, base_reg)}
    if base_eng.recompile_detector.recompiles:
        raise RuntimeError("tp=1 engine recompiled after warmup")
    tp_info = {"1": {
        "greedy_identical": True, "recompiles": 0,
        "collective_bytes_per_decode_body": 0,
        "collective_bytes_per_token": 0.0, "mesh_devices": 1,
    }}

    for tp in (2, 4):
        # correctness leg: the REAL sharded engine
        eng, _reg = make_engine(tp)
        outs = run_pass(eng)
        if not all(np.array_equal(a, b) for a, b in zip(baseline, outs)):
            raise RuntimeError(
                f"tp={tp} greedy tokens diverged from the tp=1 engine")
        cbytes = decode_collective_bytes(eng)    # lowering only
        if eng.recompile_detector.recompiles:
            raise RuntimeError(f"tp={tp} engine recompiled in steady "
                               "state after warmup")
        # step anatomy (ISSUE 16): measured collective-exposed time per
        # decode step (real wall minus the collective-elided probe's
        # wall, sampled), host-gap fraction, and the headroom plane
        asum = eng.anatomy.summary()
        health = eng.health()
        tp_info[str(tp)] = {
            "greedy_identical": True,
            "recompiles": eng.recompile_detector.recompiles,
            "collective_bytes_per_decode_body": cbytes,
            "collective_bytes_per_token": round(cbytes / num_slots, 1),
            "mesh_devices": health["mesh_devices"],
            "collective_exposed_s": round(
                float(asum.get("collective_exposed_s", 0.0)), 6),
            "collective_exposed_frac": round(
                float(asum.get("collective_exposed_frac", 0.0)), 4),
            "probe_samples": int(asum.get("probe_samples", 0)),
            "host_gap_frac": round(float(asum["host_gap_frac"]), 4),
            "headroom": health["headroom"],
        }
        del eng
        # busy-time leg: the per-chip probe
        peng, preg = make_engine(tp, probe=True)
        tokps[str(tp)] = busy_leg(peng, preg)
        if peng.recompile_detector.recompiles:
            raise RuntimeError(
                f"tp={tp} probe engine recompiled after warmup")
        del peng
    scaling_2x = tokps["2"] / max(tokps["1"], 1e-9)
    scaling_4x = tokps["4"] / max(tokps["1"], 1e-9)
    if not dryrun and scaling_2x < 1.6:
        raise RuntimeError(
            f"tp=2 decode scaling {scaling_2x:.2f}x < the 1.6x "
            "acceptance floor")

    result = {
        "metric": "serving_tp_decode_scaling_2x",
        "value": round(scaling_2x, 3),
        "unit": "x vs tp=1 (busy-time accounting)",
        "vs_baseline": round(scaling_2x / 1.6, 3),
        "decode_tokens_per_s": tokps,
        "scaling_2x": round(scaling_2x, 3),
        "scaling_4x": round(scaling_4x, 3),
        "tp": tp_info,
        "greedy_identical_all_tp": True,
        "recompiles_after_warmup": 0,
        "requests": n_req,
        "decode_cap": cap,
        "prompt_lens": sorted(set(int(n) for n in lens)),
        "model": {"hidden": cfg.hidden_size, "heads": cfg.num_heads,
                  "layers": cfg.num_layers, "ffn": cfg.ffn_size,
                  "vocab": cfg.vocab_size},
        "bench_wall_s": round(time.perf_counter() - t_bench0, 1),
        "device": str(dev.device_kind if hasattr(dev, "device_kind")
                      else dev.platform),
        "dryrun": bool(dryrun),
    }
    # schema self-check before the file lands
    for k in ("decode_tokens_per_s", "scaling_2x", "scaling_4x", "tp",
              "greedy_identical_all_tp", "recompiles_after_warmup"):
        assert k in result, f"BENCH_SERVING_TP missing {k}"
    assert set(result["decode_tokens_per_s"]) == {"1", "2", "4"}
    for tp, info in result["tp"].items():
        assert info["recompiles"] == 0, (tp, info)
        assert info["greedy_identical"] is True
    assert result["tp"]["2"]["collective_bytes_per_decode_body"] > 0, \
        "tp=2 step lowered no collective — the psum is missing"
    for tp in ("2", "4"):
        info = result["tp"][tp]
        assert info["probe_samples"] >= 1, \
            f"tp={tp} anatomy probe never sampled a decode round"
        assert info["collective_exposed_s"] >= 0.0, (tp, info)
        assert 0.0 <= info["host_gap_frac"] <= 1.0, (tp, info)
        assert set(info["headroom"]) >= {"flops", "pages", "slots",
                                         "hbm"}, (tp, info)
    path = serving_tp_json_path(dryrun)
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    return result


def kernels_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_KERNELS",
                              "/tmp/BENCH_KERNELS.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_KERNELS",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_KERNELS.json"))


def disagg_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_DISAGG",
                              "/tmp/BENCH_DISAGG.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_DISAGG",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_DISAGG.json"))


def run_bench_disagg(dev, dryrun=False):
    """Prefill/decode disaggregation (ISSUE 19 acceptance): a
    flops-bound prefill tier streaming pages into a KV-bound decode
    tier, against the colocated fleet it replaces, under the SAME
    saturating mixed burst.

    Two fleets, identical chips (2 replicas each), identical workload:

    - **colocated** — two ordinary replicas; every slot is held for
      its request's ENTIRE decode, so a burst of long decodes pins
      every slot and interactive prompts queue behind them.
    - **disaggregated** — one ``tier="prefill"`` replica (slot-light:
      slots churn at prefill speed) streaming each prefill-complete
      slot to one ``tier="decode"`` replica (slot-heavy: sized for KV
      capacity, the provisioning freedom disaggregation buys). The
      handoff is the sha256-verified per-(page, tp-shard) shard
      manifest — the exact ``snapshot_slot``/``restore_slot``
      migration format.

    The workload is a background wave of long decodes saturating every
    colocated slot, with short interactive prompts injected while it
    runs. Reported gates (hard non-dryrun):

    - interactive TTFT p99: colocated degrades to ~the background
      decode time (queue wait for a slot), the prefill tier stays flat
      — the ratio must be >= 2x;
    - decode tokens/s by busy-time accounting (tokens / the engines'
      ``serving_decode_step_seconds`` histogram sum): the decode tier
      must be within 10% of colocated (>= 0.9x);
    - transfer bytes: counted from ``fleet_handoff_bytes_total`` and
      budget-gated against pages_for(max_tokens) * page_bytes per
      handoff;
    - ZERO steady-state recompiles on BOTH tiers (every engine fully
      warmed through its tier-filtered ``warmup_plan`` first), with
      per-tier bucket coverage (plan superset of reachable).

    Background outputs must also be bit-identical across the two
    fleets (greedy determinism survives the handoff). Emits
    BENCH_DISAGG.json (schema self-validated) next to this file
    (dryrun: /tmp)."""
    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.serving import fleet
    from paddle_tpu.models.gpt import GPT, GPTConfig

    if dryrun:
        cfg = GPTConfig.tiny(vocab_size=128, hidden_size=32,
                             num_layers=2, num_heads=2, ffn_size=64,
                             max_position=128, dropout=0.0,
                             attn_impl="xla")
        page_size, chunk = 4, 8
        bg_n, bg_cap, bg_lens = 4, 12, (9, 12)
        int_n, int_cap, int_len = 4, 4, 5
        colo_slots, pre_slots, dec_slots = 2, 2, 8
        interactive_every = 2
    else:
        # CPU measurement config: background decodes long enough that
        # colocated slot-wait dominates interactive TTFT; the decode
        # tier sized so the whole background wave PLUS the interactive
        # overlap fit without in-place fallback — but no larger: the
        # decode step is a fixed num_slots-lane shape, so every slot
        # beyond the live wave is padded work the busy-time throughput
        # gate charges against the disaggregated fleet
        cfg = GPTConfig(vocab_size=512, hidden_size=192, num_layers=3,
                        num_heads=4, ffn_size=768, max_position=256,
                        dropout=0.0, attn_impl="xla")
        page_size, chunk = 16, 32
        bg_n, bg_cap, bg_lens = 8, 48, (24, 40, 56)
        int_n, int_cap, int_len = 8, 8, 16
        colo_slots, pre_slots, dec_slots = 4, 4, 12
        interactive_every = 3
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    # identical per-slot token budget everywhere: the migration format
    # reserves prompt+budget on restore, so the decode tier must honor
    # the same cap the prefill tier admitted under
    max_tok = max(bg_lens) + bg_cap
    bg_prompts = [rng.integers(1, cfg.vocab_size,
                               int(n)).astype(np.int32)
                  for n in rng.choice(bg_lens, bg_n)]
    int_prompts = [rng.integers(1, cfg.vocab_size,
                                int_len).astype(np.int32)
                   for _ in range(int_n)]

    def make_replica(name, tier, slots):
        eng = serving.ServingEngine(
            model, params, num_slots=slots, page_size=page_size,
            max_tokens_per_slot=max_tok, prefill_chunk=chunk,
            attn_impl="lax", registry=obs.MetricsRegistry(), tier=tier)
        # per-tier bucket coverage: the tier-filtered warmup plan must
        # reach every signature the tier can execute
        plan = set(eng.warmup_plan())
        reach = eng.reachable_signatures()
        if not plan >= reach:
            raise RuntimeError(
                f"{tier} tier bucket coverage hole: {reach - plan}")
        return fleet.LocalReplica(eng, name=name).warmup()

    def decode_busy(replicas):
        return sum(float(r.engine._reg.histogram(
            "serving_decode_step_seconds").summary()["sum"])
            for r in replicas)

    t_bench0 = time.perf_counter()

    def mixed_burst(replicas, reg):
        router = fleet.FleetRouter(replicas, policy="p2c",
                                   registry=reg, seed=5)
        busy0 = decode_busy(replicas)
        bg = [router.submit(p, bg_cap) for p in bg_prompts]
        inter, steps, nsub = [], 0, 0
        while not router.idle() or nsub < int_n:
            router.step()
            steps += 1
            if steps % interactive_every == 0 and nsub < int_n:
                inter.append(router.submit(int_prompts[nsub], int_cap,
                                           lane="interactive"))
                nsub += 1
            if steps > 1_000_000:
                raise RuntimeError("disagg burst did not converge")
        outs = [router.result(f) for f in bg]
        stats = [router.request_stats(f) for f in inter]
        if any(o is None for o in outs) or any(s is None
                                               for s in stats):
            raise RuntimeError("mixed burst lost a request")
        ttfts = [float(s["ttft_s"]) for s in stats]
        tokens = float(bg_n * bg_cap + int_n * int_cap)
        tps = tokens / max(decode_busy(replicas) - busy0, 1e-9)
        return router, outs, ttfts, tps, steps

    # --- colocated leg
    colo = [make_replica(f"c{i}", "colocated", colo_slots)
            for i in range(2)]
    reg_c = obs.MetricsRegistry()
    _, outs_c, ttfts_c, tps_c, steps_c = mixed_burst(colo, reg_c)

    # --- disaggregated leg
    pre = make_replica("p0", "prefill", pre_slots)
    dec = make_replica("d0", "decode", dec_slots)
    reg_d = obs.MetricsRegistry()
    router_d, outs_d, ttfts_d, tps_d, steps_d = mixed_burst(
        [pre, dec], reg_d)

    if not all(np.array_equal(a, b)
               for a, b in zip(outs_c, outs_d)):
        raise RuntimeError("disaggregated greedy tokens diverged "
                           "from the colocated fleet")
    for rep, tier in ((colo[0], "colocated"), (colo[1], "colocated"),
                      (pre, "prefill"), (dec, "decode")):
        n = rep.engine.recompile_detector.recompiles
        if n:
            raise RuntimeError(
                f"{tier} replica {rep.name} recompiled {n}x in "
                "steady state after warmup")

    # --- handoff transfer accounting, budget-gated
    fh = router_d.health()
    handoffs = int(fh["handoffs_total"])
    transfer_bytes = float(reg_d.counter(
        "fleet_handoff_bytes_total",
        "sha256-verified page bytes shipped prefill -> "
        "decode").value(src="p0", dst="d0"))
    c = dec.engine.cache.config
    page_bytes = (dec.engine.cache.pages.nbytes // c.num_pages
                  if hasattr(dec.engine.cache.pages, "nbytes")
                  else sum(int(p.nbytes) for p in jax.tree_util
                           .tree_leaves(dec.engine.cache.pages))
                  // c.num_pages)
    transfer_budget = float(handoffs * c.pages_for(max_tok)
                            * page_bytes)
    if handoffs < bg_n:
        raise RuntimeError(
            f"only {handoffs} handoffs for {bg_n} background "
            "requests — the prefill tier is not streaming")
    if not 0.0 < transfer_bytes <= transfer_budget:
        raise RuntimeError(
            f"handoff transfer {transfer_bytes:.0f}B outside the "
            f"(0, {transfer_budget:.0f}B] budget")

    ttft_p99_c = float(np.percentile(ttfts_c, 99))
    ttft_p99_d = float(np.percentile(ttfts_d, 99))
    ttft_ratio = ttft_p99_c / max(ttft_p99_d, 1e-9)
    tput_ratio = tps_d / max(tps_c, 1e-9)
    if not dryrun:
        if ttft_ratio < 2.0:
            raise RuntimeError(
                f"disagg TTFT p99 improvement {ttft_ratio:.2f}x "
                "< the 2x acceptance floor")
        if tput_ratio < 0.9:
            raise RuntimeError(
                f"disagg decode throughput {tput_ratio:.2f}x of "
                "colocated — below the 0.9x (within-10%) floor")

    result = {
        "metric": "serving_disagg_ttft_p99_improvement",
        "value": round(ttft_ratio, 3),
        "unit": "x vs colocated (mixed burst)",
        "vs_baseline": round(ttft_ratio / 2.0, 3),
        "ttft_interactive_p99_s": {
            "colocated": round(ttft_p99_c, 4),
            "disaggregated": round(ttft_p99_d, 4)},
        "ttft_ratio": round(ttft_ratio, 3),
        "decode_tokens_per_s_busy": {
            "colocated": round(tps_c, 2),
            "disaggregated": round(tps_d, 2)},
        "throughput_ratio": round(tput_ratio, 3),
        "greedy_identical": True,
        "recompiles_after_warmup": {"prefill": 0, "decode": 0,
                                    "colocated": 0},
        "handoffs": handoffs,
        "handoff_fallbacks_in_place": int(
            0 if reg_d.get("fleet_handoff_fallback_total") is None
            else reg_d.get("fleet_handoff_fallback_total").value(
                replica="p0")),
        "transfer_bytes": int(transfer_bytes),
        "transfer_budget_bytes": int(transfer_budget),
        "transfer_bytes_per_handoff": round(
            transfer_bytes / max(handoffs, 1), 1),
        "tiers": {"prefill": {"slots": pre_slots},
                  "decode": {"slots": dec_slots},
                  "colocated": {"slots": colo_slots, "replicas": 2}},
        "workload": {"background": bg_n, "background_cap": bg_cap,
                     "interactive": int_n, "interactive_cap": int_cap,
                     "prompt_lens": sorted(set(int(n) for n in bg_lens)),
                     "interactive_len": int_len},
        "steps": {"colocated": steps_c, "disaggregated": steps_d},
        "bench_wall_s": round(time.perf_counter() - t_bench0, 1),
        "device": str(dev.device_kind if hasattr(dev, "device_kind")
                      else dev.platform),
        "dryrun": bool(dryrun),
    }
    # schema self-check before the file lands
    for k in ("ttft_interactive_p99_s", "ttft_ratio",
              "decode_tokens_per_s_busy", "throughput_ratio",
              "greedy_identical", "recompiles_after_warmup",
              "handoffs", "transfer_bytes", "transfer_budget_bytes"):
        if k not in result:
            raise RuntimeError(f"BENCH_DISAGG schema self-check "
                               f"failed: missing {k}")
    path = disagg_json_path(dryrun)
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    result["json"] = path
    return result


def prefix_fleet_json_path(dryrun: bool) -> str:
    import os
    if dryrun:  # CI smoke must not dirty the checkout
        return os.environ.get("PADDLE_TPU_BENCH_PREFIX_FLEET",
                              "/tmp/BENCH_PREFIX_FLEET.json")
    return os.environ.get(
        "PADDLE_TPU_BENCH_PREFIX_FLEET",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_PREFIX_FLEET.json"))


def run_bench_prefix_fleet(dev, dryrun=False):
    """Hierarchical KV (ISSUE 20 acceptance): host-spilled cold pages
    plus fleet-global prefix fetch, against the affinity-only router
    it extends, under the SAME shared-prefix workload with scale-out
    AND scale-in churn.

    Two fleets, identical chips and identical traffic:

    - **affinity-only** — ``prefix_fetch=False, host_spill_pages=0``:
      routing chases the prefix holder, but a miss (or an evicted
      page) re-prefills from scratch, and a drained holder takes its
      prefix pages to the grave.
    - **hierarchical** — allocator pressure spills published pages to
      a pinned host pool (restored byte-identical on the next hit),
      and a replica that misses a prefix a peer advertises imports
      the committed pages as hash-verified migration shards instead
      of recomputing them.

    The churn script (identical in both legs): wave A publishes the
    shared prefixes and adds filler pressure on a 2-replica fleet; a
    THIRD warmed replica scales out; every prefix holder starts
    draining (drain refuses new work, so wave B must route to the
    non-holders — the hierarchical leg fetches, the baseline
    re-prefills); the holders are then drain-removed (scale-in) and
    wave C runs on the survivors.

    Headline metric: fleet prefill tokens actually COMPUTED per
    served token (``serving_prefill_tokens_total`` summed over every
    engine that ever served, divided by ``serving_tokens_total`` —
    lower is better). Gates (hard non-dryrun):

    - the hierarchical fleet must be STRICTLY below affinity-only;
    - greedy outputs bit-identical across the two legs (sharing and
      fetching never change tokens);
    - ZERO steady-state recompiles on every replica in BOTH legs
      (spill/restore and page import ride the warmed
      ``("page_read",)``/``("page_write",)`` signatures);
    - the hierarchical leg actually exercised BOTH tiers: fetched
      pages > 0 and spilled pages > 0.

    Emits BENCH_PREFIX_FLEET.json (schema self-validated) next to
    this file (dryrun: /tmp)."""
    import numpy as np

    from paddle_tpu import observability as obs
    from paddle_tpu import serving
    from paddle_tpu.serving import fleet
    from paddle_tpu.serving.paged_cache import prompt_prefix_digests
    from paddle_tpu.models.gpt import GPT, GPTConfig

    if dryrun:
        cfg = GPTConfig.tiny(vocab_size=64, hidden_size=16,
                             num_layers=2, num_heads=2, ffn_size=32,
                             max_position=64, dropout=0.0,
                             attn_impl="xla")
        reqs_per_prefix = 2
    else:
        cfg = GPTConfig.tiny(vocab_size=256, hidden_size=64,
                             num_layers=2, num_heads=4, ffn_size=128,
                             max_position=64, dropout=0.0,
                             attn_impl="xla")
        reqs_per_prefix = 3
    page_size, prefix_len, cap = 4, 16, 6
    num_pages, spill_pages = 14, 8
    filler_len = 24
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    vocab = cfg.vocab_size
    prefixes = [rng.integers(1, vocab, prefix_len).astype(np.int32)
                for _ in range(2)]
    prefix_digs = set()
    for pre in prefixes:
        prefix_digs.update(prompt_prefix_digests(pre, page_size))

    def shared(pre):
        tail = rng.integers(1, vocab,
                            int(rng.integers(2, 5))).astype(np.int32)
        return np.concatenate([pre, tail])

    def filler():
        return rng.integers(1, vocab, filler_len).astype(np.int32)

    # one deterministic prompt script, replayed by BOTH legs
    wave_a = [shared(p) for p in prefixes
              for _ in range(reqs_per_prefix)] + [filler(), filler()]
    wave_b = [shared(p) for p in prefixes
              for _ in range(reqs_per_prefix)] + [filler()]
    wave_c = [shared(p) for p in prefixes
              for _ in range(reqs_per_prefix)]
    served_cap = cap * (len(wave_a) + len(wave_b) + len(wave_c))

    t_bench0 = time.perf_counter()

    def make_replica(name, spill):
        eng = serving.ServingEngine(
            model, params, num_slots=2, page_size=page_size,
            num_pages=num_pages, max_tokens_per_slot=44,
            prefill_chunk=page_size, attn_impl="lax",
            registry=obs.MetricsRegistry(), host_spill_pages=spill)
        return fleet.LocalReplica(eng, name=name).warmup()

    def run_wave(router, prompts, outs):
        frids = [router.submit(p, cap) for p in prompts]
        router.run_until_idle(max_steps=200_000)
        for f in frids:
            o = router.result(f)
            if o is None:
                raise RuntimeError("prefix_fleet wave lost a request")
            outs.append(o)

    def leg(prefix_fetch, spill):
        reps = [make_replica(f"r{i}", spill) for i in range(2)]
        reg = obs.MetricsRegistry()
        router = fleet.FleetRouter(reps, policy="affinity",
                                   registry=reg, seed=9,
                                   prefix_fetch=prefix_fetch)
        all_reps = list(reps)
        outs = []
        run_wave(router, wave_a, outs)
        # scale-out churn: a fresh warmed replica joins mid-traffic
        extra = make_replica("r2", spill)
        router.add_replica(extra)
        all_reps.append(extra)
        # every prefix holder starts draining — wave B MUST land on
        # replicas that never saw the prefixes (drain refuses new
        # work, but exporting committed pages is a read)
        holders = [r for r in reps
                   if prefix_digs & set(r.prefix_digests())]
        if not holders:
            raise RuntimeError("wave A published no shared prefix")
        for h in holders:
            h.draining = True
        run_wave(router, wave_b, outs)
        # scale-in churn: the holders leave the fleet for good
        for h in holders:
            router.drain_replica(h, remove=True)
        run_wave(router, wave_c, outs)
        prefill = sum(float(r.engine._reg.counter(
            "serving_prefill_tokens_total").value()) for r in all_reps)
        served = sum(float(r.engine._reg.counter(
            "serving_tokens_total").value()) for r in all_reps)
        shared_tok = sum(float(r.engine._reg.counter(
            "serving_prefix_shared_tokens_total").value())
            for r in all_reps)
        recompiles = sum(int(r.engine.recompile_detector.recompiles)
                         for r in all_reps)
        spilled = sum(int(r.engine.cache.spill_pool.spilled_total)
                      for r in all_reps if r.engine.cache.spill_pool)
        spilled_bytes = sum(
            int(r.engine.cache.spill_pool.spilled_bytes_total)
            for r in all_reps if r.engine.cache.spill_pool)
        restored = sum(int(r.engine.cache.spill_pool.restored_total)
                       for r in all_reps if r.engine.cache.spill_pool)
        return {
            "outs": outs, "router_reg": reg,
            "prefill_tokens": prefill, "served_tokens": served,
            "prefill_per_served": prefill / max(served, 1e-9),
            "shared_tokens": shared_tok,
            "prefix_hit_rate": round(
                shared_tok / max(prefill + shared_tok, 1e-9), 4),
            "recompiles": recompiles,
            "spilled_pages": spilled, "spilled_bytes": spilled_bytes,
            "restored_pages": restored,
        }

    base = leg(prefix_fetch=False, spill=0)
    hier = leg(prefix_fetch=True, spill=spill_pages)

    if not all(np.array_equal(a, b)
               for a, b in zip(base["outs"], hier["outs"])):
        raise RuntimeError("hierarchical greedy tokens diverged from "
                           "the affinity-only fleet")
    if base["recompiles"] or hier["recompiles"]:
        raise RuntimeError(
            f"steady-state recompiles after warmup: affinity-only="
            f"{base['recompiles']} hierarchical={hier['recompiles']}")
    hreg = hier["router_reg"]
    fetched_pages = int(hreg.counter(
        "fleet_prefix_fetch_pages_total").value())
    fetched_bytes = int(hreg.counter(
        "fleet_prefix_fetch_bytes_total").value())
    degraded = int(hreg.counter(
        "fleet_prefix_fetch_degraded_total").value())
    ratio = (base["prefill_per_served"]
             / max(hier["prefill_per_served"], 1e-9))
    if not dryrun:
        if hier["prefill_per_served"] >= base["prefill_per_served"]:
            raise RuntimeError(
                f"hierarchical prefill/served "
                f"{hier['prefill_per_served']:.3f} not strictly below "
                f"affinity-only {base['prefill_per_served']:.3f}")
        if fetched_pages <= 0:
            raise RuntimeError("fleet prefix fetch never fired")
        if hier["spilled_pages"] <= 0:
            raise RuntimeError("host spill tier never engaged")

    result = {
        "metric": "prefix_fleet_prefill_tokens_per_served_token",
        "value": round(hier["prefill_per_served"], 4),
        "unit": "prefill tokens/served token (lower is better)",
        "vs_baseline": round(ratio, 3),
        "prefill_per_served": {
            "affinity_only": round(base["prefill_per_served"], 4),
            "hierarchical": round(hier["prefill_per_served"], 4)},
        "prefill_tokens": {
            "affinity_only": int(base["prefill_tokens"]),
            "hierarchical": int(hier["prefill_tokens"])},
        "served_tokens": {
            "affinity_only": int(base["served_tokens"]),
            "hierarchical": int(hier["served_tokens"])},
        "prefix_hit_rate": {
            "affinity_only": base["prefix_hit_rate"],
            "hierarchical": hier["prefix_hit_rate"]},
        "fetch": {"pages": fetched_pages, "bytes": fetched_bytes,
                  "degraded": degraded},
        "spill": {"spilled_pages": hier["spilled_pages"],
                  "spilled_bytes": hier["spilled_bytes"],
                  "restored_pages": hier["restored_pages"]},
        "greedy_identical": True,
        "recompiles_after_warmup": {
            "affinity_only": base["recompiles"],
            "hierarchical": hier["recompiles"]},
        "churn": {"scale_out_replicas": 1, "drained_holders": True},
        "workload": {"prefixes": len(prefixes),
                     "prefix_len": prefix_len,
                     "requests": (len(wave_a) + len(wave_b)
                                  + len(wave_c)),
                     "cap": cap, "served_cap": served_cap,
                     "filler_len": filler_len},
        "bench_wall_s": round(time.perf_counter() - t_bench0, 1),
        "device": str(dev.device_kind if hasattr(dev, "device_kind")
                      else dev.platform),
        "dryrun": bool(dryrun),
    }
    # schema self-check before the file lands
    for k in ("prefill_per_served", "prefill_tokens", "served_tokens",
              "prefix_hit_rate", "fetch", "spill", "greedy_identical",
              "recompiles_after_warmup", "churn"):
        if k not in result:
            raise RuntimeError(f"BENCH_PREFIX_FLEET schema "
                               f"self-check failed: missing {k}")
    path = prefix_fleet_json_path(dryrun)
    with open(path, "w") as f:
        json.dump(result, f, indent=2)
        f.write("\n")
    result["json"] = path
    return result


def run_bench_kernels(dev, dryrun=False):
    """Shared kernel-layer microbench (ISSUE 12 acceptance): for every
    registered single-device kernel (flash attention, ragged paged
    decode, ragged paged prefill — ring inherits the flash inner blocks)
    measure autotuned vs default block sizes across the kernel's 3
    sample shape buckets, through ONE harness: ``kernels.dispatch`` with
    an explicit candidate override, timed on the live backend (Pallas on
    TPU, the same kernels under the interpreter on CPU). Then assert the
    tuner-cache contract: a measured entry is a HIT on the next
    resolution of the same bucket, and the committed
    ``tools/kernel_tune.json`` loads with zero stale entries (a contract
    version bump without a reseed fails the bench, not the user). A
    non-dryrun run MERGES its measured winners into the committed cache
    (keys carry the device kind, so a TPU session refreshes TPU entries
    without touching the CPU-CI ones) — commit the updated manifest with
    the session. Emits BENCH_KERNELS.json (schema self-validated) next
    to this file (dryrun: /tmp, cache untouched)."""
    import numpy as np

    from paddle_tpu import kernels

    kernels.load_all()
    on_tpu = dev.platform == "tpu"
    impl = "pallas" if on_tpu else "pallas_interpret"
    reps = 5 if on_tpu else 1
    tuner = kernels.KernelTuner(path=None)    # cold: measure fresh
    leaf = [n for n in kernels.names()
            if kernels.get(n).contract.block_candidates
            and not kernels.get(n).requires_mesh]
    per_kernel = {}
    speedups = []
    t_bench0 = time.perf_counter()
    for name in leaf:
        spec = kernels.get(name)
        buckets = {}
        for seed in (0, 1, 2):
            args, kw = spec.sample_inputs(seed)
            res = tuner.measure(spec, args, kw, impl=impl, reps=reps)
            speedup = res["default_s"] / max(res["best_s"], 1e-9)
            speedups.append(speedup)
            buckets[kernels.tune_key(spec, args, kw)] = {
                "default_blocks": res["default_blocks"],
                "tuned_blocks": res["blocks"],
                "default_s": round(res["default_s"], 6),
                "tuned_s": round(res["best_s"], 6),
                "speedup_vs_default": round(speedup, 3),
            }
        per_kernel[name] = buckets

    # tuner-cache hit contract: the bucket just measured must resolve
    # from cache (not re-derive a prior) on the next dispatch
    for name in leaf:
        spec = kernels.get(name)
        args, kw = spec.sample_inputs(0)
        hits_before = tuner.hits
        blocks = tuner.get(spec, args, kw)
        if tuner.hits != hits_before + 1:
            raise RuntimeError(
                f"tuner cache MISSED a just-measured bucket for {name} "
                f"(stats {tuner.stats()}) — key derivation is not "
                "deterministic")
        key = kernels.tune_key(spec, args, kw)
        if blocks != tuner.entries[key]["blocks"]:
            raise RuntimeError(f"cache returned foreign blocks for {key}")

    # committed-manifest round trip: loads, and nothing in it is stale.
    # Validate BEFORE any write — a failing gate must not leave the
    # checkout with a rewritten (still-failing) manifest.
    committed = kernels.KernelTuner(kernels.DEFAULT_CACHE_PATH)
    committed_stale = len(committed.stale_entries())
    if committed_stale:
        raise RuntimeError(
            f"tools/kernel_tune.json has {committed_stale} stale "
            "entr(ies) — a kernel's contract version moved without "
            "reseeding (python -m paddle_tpu.kernels.autotune --seed)")
    # Non-dryrun: fold this session's measured winners in and persist —
    # THIS is the documented "refresh measured entries on the target
    # device" path (the dryrun CI smoke must not dirty the checkout).
    # Seed-time cost_prior stamps survive the overwrite.
    if not dryrun:
        for key, ent in tuner.entries.items():
            old = committed.entries.get(key, {})
            if "cost_prior" in old and "cost_prior" not in ent:
                ent = {**ent, "cost_prior": old["cost_prior"]}
            committed.entries[key] = ent
        committed.save(kernels.DEFAULT_CACHE_PATH)

    geomean = float(np.exp(np.mean(np.log(np.maximum(speedups, 1e-9)))))
    result = {
        "metric": "kernels_autotune_speedup_geomean",
        "value": round(geomean, 3),
        "unit": "x vs default blocks",
        "vs_baseline": round(geomean, 3),   # 1.0 == defaults already best
        "kernels": per_kernel,
        "impl": impl,
        "tuner_cache_hits": tuner.hits,
        "tuner_cache_misses": tuner.misses,
        "tuner_stale_entries": tuner.stale,
        "committed_cache_entries": len(committed.entries),
        "committed_cache_stale": committed_stale,
        "device": getattr(dev, "device_kind", dev.platform),
        "dryrun": bool(dryrun),
        "_telemetry": {"steps": len(speedups),
                       "dt": time.perf_counter() - t_bench0,
                       "examples_per_step": 1},
    }
    missing = [k for k in KERNELS_SCHEMA if k not in result]
    if missing:
        raise RuntimeError(f"BENCH_KERNELS schema self-check failed: "
                           f"missing {missing}")
    path = kernels_json_path(dryrun)
    with open(path, "w") as f:
        json.dump({k: v for k, v in result.items()
                   if k != "_telemetry"}, f, indent=2)
    result["bench_json"] = path
    return result


_BENCHES = {
    "bert": (run_bench, "bert_base_tokens_per_sec_per_chip",
             "tokens/s/chip"),
    "resnet50": (run_bench_resnet, "resnet50_images_per_sec_per_chip",
                 "images/s/chip"),
    "transformer": (run_bench_transformer,
                    "transformer_big_packed_tokens_per_sec_per_chip",
                    "real tokens/s/chip"),
    "deepfm": (run_bench_deepfm, "deepfm_examples_per_sec_per_chip",
               "examples/s/chip"),
    "serving": (run_bench_serving, "serving_decode_tokens_per_sec",
                "tokens/s"),
    "embedding_serving": (run_bench_embedding_serving,
                          "embedding_serving_examples_per_sec",
                          "examples/s"),
    "router": (run_bench_router, "router_aggregate_tokens_per_sec",
               "tokens/s"),
    "kernels": (run_bench_kernels, "kernels_autotune_speedup_geomean",
                "x vs default blocks"),
    "serving_tp": (run_bench_serving_tp, "serving_tp_decode_scaling_2x",
                   "x vs tp=1 (busy-time accounting)"),
    "net_router": (run_bench_net_router, "net_router_tokens_per_sec",
                   "tokens/s"),
    "disagg": (run_bench_disagg, "serving_disagg_ttft_p99_improvement",
               "x vs colocated (mixed burst)"),
    "prefix_fleet": (run_bench_prefix_fleet,
                     "prefix_fleet_prefill_tokens_per_served_token",
                     "prefill tokens/served token (lower is better)"),
}


def main():
    # --model bert (default, the driver's headline metric) | resnet50 |
    # transformer | deepfm. Either way EXACTLY ONE JSON line goes to
    # stdout (even on bad args).
    which = "bert"
    try:
        if "--model" in sys.argv:
            which = sys.argv[sys.argv.index("--model") + 1]
        if which not in _BENCHES:
            raise ValueError(f"unknown --model {which!r} "
                             f"(expected {'|'.join(_BENCHES)})")
        from paddle_tpu import observability as obs
        obs.install_compile_listener()  # compiles_cum covers the warmup
        dev, degraded = acquire_device()
        if which in ("serving", "embedding_serving", "router", "kernels",
                     "serving_tp", "net_router", "disagg",
                     "prefix_fleet"):
            # CI smoke: tiny sizes + schema self-check
            result = _BENCHES[which][0](dev,
                                        dryrun="--dryrun" in sys.argv)
        else:
            result = _BENCHES[which][0](dev)
        if degraded:  # zero BEFORE telemetry so JSONL/.prom agree with stdout
            result["error"] = degraded
            result["vs_baseline"] = 0.0
        log_path = write_bench_telemetry(result)
        if log_path:
            result["metrics_log"] = log_path
    except Exception as e:  # fail-soft: always emit a parseable line, rc=0
        fn, metric, unit = _BENCHES.get(which, _BENCHES["bert"])
        result = {
            "metric": metric,
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0,
            "error": f"{type(e).__name__}: {e}",
        }
    result.pop("_telemetry", None)  # never leak internals to the JSON line
    print(json.dumps(result))


if __name__ == "__main__":
    main()
