"""Fleet: multi-host training bootstrap + role management.

Reference mapping (SURVEY.md §2.6): the ``Fleet`` facade
(``incubate/fleet/base/fleet_base.py:38`` init/init_worker/init_server),
role makers (``role_maker.py`` — ``PaddleCloudRoleMaker:328`` reads
PADDLE_TRAINER_ID/PADDLE_TRAINER_ENDPOINTS env vars; ``MPISymetricRoleMaker``)
and the nccl-id bootstrap (``c_gen_nccl_id_op.cc`` socket exchange).

TPU-native: there are no pserver/trainer roles — every host is a worker in
one SPMD program. Bootstrap is ``jax.distributed.initialize`` (the JAX
coordination service replaces the nccl-id exchange); role queries map to
process_index/process_count; ``DistributedStrategy`` becomes the typed
(MeshConfig, ShardingPlan, Policy) triple.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional

import jax


@dataclasses.dataclass
class RoleMaker:
    """Resolved distributed identity (role_maker.py parity, minus
    pserver roles)."""

    worker_index: int = 0
    worker_num: int = 1
    coordinator: Optional[str] = None

    def is_first_worker(self) -> bool:
        return self.worker_index == 0

    @classmethod
    def from_env(cls) -> "RoleMaker":
        """PaddleCloud-style env bootstrap (PADDLE_* honored for parity;
        JAX_* / TPU pod env preferred)."""
        idx = int(os.environ.get("JAX_PROCESS_INDEX",
                                 os.environ.get("PADDLE_TRAINER_ID", "0")))
        num = int(os.environ.get("JAX_PROCESS_COUNT",
                                 os.environ.get("PADDLE_TRAINERS_NUM", "1")))
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS",
                               os.environ.get("PADDLE_COORDINATOR", None))
        return cls(idx, num, coord)


_INITIALIZED = False


def init(role: Optional[RoleMaker] = None) -> RoleMaker:
    """Initialize multi-host JAX (Fleet.init parity).

    Single-process (worker_num == 1) is a no-op; multi-process calls
    ``jax.distributed.initialize`` — the coordination service replaces the
    reference's out-of-band nccl-id/gRPC bootstrap. On TPU pods with
    standard env, argument-less initialize() autodetects everything.
    """
    global _INITIALIZED
    role = role or RoleMaker.from_env()
    if role.worker_num > 1 and not _INITIALIZED:
        jax.distributed.initialize(
            coordinator_address=role.coordinator,
            num_processes=role.worker_num,
            process_id=role.worker_index)
        _INITIALIZED = True
    # identity gauges: every host's exposition shows who it is, so a
    # scraper can join per-host series (observability.aggregate's view)
    from paddle_tpu import observability as _obs
    _obs.gauge("fleet_worker_index").set(role.worker_index)
    _obs.gauge("fleet_worker_num").set(role.worker_num)
    return role


def worker_index() -> int:
    return jax.process_index()


def worker_num() -> int:
    return jax.process_count()


def is_first_worker() -> bool:
    return jax.process_index() == 0


def barrier(name: str = "fleet"):
    """Cross-host sync point (fleet_util barrier parity)."""
    from paddle_tpu.parallel import collective
    from paddle_tpu.core.mesh import current_mesh, make_mesh

    mesh = current_mesh() or make_mesh()
    collective.barrier(axis=tuple(mesh.axis_names), mesh=mesh)


def agree_on_resume_step(step: Optional[int]) -> Optional[int]:
    """Multi-host barrier on restore: every host reports the newest valid
    snapshot step it can see (``None`` = nothing restorable) and ALL hosts
    adopt the minimum — the newest step the whole fleet can restore. A
    host that committed its shards just before a crash may be ahead of
    the others; resuming from its private step would fork the SPMD
    program, so it drops back. If ANY host has no valid snapshot the
    fleet starts from scratch together (returns ``None``).

    Doubles as the restore-time barrier: the all-gather blocks until
    every host arrives, so no host starts stepping before the slowest one
    finished scanning its manifests."""
    local = -1 if step is None else int(step)
    if jax.process_count() == 1:
        agreed = local
    else:
        import numpy as np
        from jax.experimental import multihost_utils

        steps = np.asarray(multihost_utils.process_allgather(
            jax.numpy.asarray(local, jax.numpy.int32)))
        agreed = int(steps.min())
        if int(steps.max()) != agreed:
            from paddle_tpu import observability as _obs
            _obs.counter(
                "resilience_resume_step_disagreements_total",
                "restores where hosts saw different latest snapshots").inc()
            print(f"[fleet] resume-step disagreement across hosts "
                  f"(min={agreed} max={int(steps.max())}); "
                  f"all hosts resume from {agreed}")
    return None if agreed < 0 else agreed


class HeartbeatMonitor:
    """Training-stall watchdog (operators/distributed/heart_beat_monitor.h:54
    ``LostWorkerMonitor`` parity — there: pserver tracks per-worker update
    times; here: a host thread tracks step progress and calls ``on_stall``
    when no beat arrives within the timeout)."""

    def __init__(self, timeout_s: float = 300.0, *, check_every_s: float = 10.0,
                 on_stall=None, log_fn=print):
        import threading
        import time as _time

        self.timeout_s = timeout_s
        self._last = _time.monotonic()
        self._step = -1
        self._stop = threading.Event()
        self._on_stall = on_stall
        self._log = log_fn

        def watch():
            from paddle_tpu import observability as _obs
            while not self._stop.wait(check_every_s):
                idle = _time.monotonic() - self._last
                _obs.gauge("fleet_heartbeat_idle_seconds",
                           "seconds since the last step beat").set(idle)
                if idle > self.timeout_s:
                    msg = (f"[heartbeat] no progress for {idle:.0f}s "
                           f"(last step {self._step})")
                    self._log(msg)
                    _obs.counter("fleet_heartbeat_stalls_total").inc()
                    if self._on_stall is not None:
                        self._on_stall(self._step, idle)

        self._thread = threading.Thread(target=watch, daemon=True)
        self._thread.start()

    def beat(self, step: int):
        import time as _time

        self._last = _time.monotonic()
        self._step = step
        from paddle_tpu import observability as _obs
        _obs.gauge("fleet_last_step", "latest step a beat reported").set(step)

    def stop(self):
        self._stop.set()


class ElasticCoordinator:
    """Worker-process supervisor: spawn N ranks, watch for failures,
    respawn crashed ranks (same rank id) until the job finishes or the
    restart budget is spent.

    Reference mapping (SURVEY.md §5.3): fluid's fault tolerance pairs the
    pserver-side LostWorkerMonitor (heart_beat_monitor.h:54) with
    cloud-side restart policy; here detection is HeartbeatMonitor /
    process exit, and THIS is the restart policy half: a host-side
    coordinator owning the worker processes. Workers are expected to
    resume from their latest checkpoint on restart (io.CheckpointManager
    pattern — see tests/test_dist_multiprocess.py for the full loop).

    ``spawn_fn(rank, attempt) -> subprocess.Popen`` creates a worker;
    ``success_rc`` exits that count as done; every other exit triggers a
    respawn while ``max_restarts`` allows. Exits in ``preempt_rc``
    (default: ``resilience.EXIT_PREEMPTED``, the drained-and-snapshotted
    preemption code) respawn WITHOUT consuming the restart budget —
    a preemption is the platform's doing, not the job's. Exits in
    ``drain_rc`` (default: ``resilience.EXIT_DRAINED``, the serving
    fleet's voluntary scale-in code) retire the rank as DONE — the
    worker migrated its state away on purpose, so it is neither
    respawned nor charged against the budget; ``drained_exits`` counts
    them.

    ``gang=True`` (default): ANY failure kills every worker and respawns
    the whole gang at attempt+1 — required for SPMD jobs, where a
    ``jax.distributed`` coordination service cannot admit a lone
    rejoining rank; training resumes from the latest checkpoint.
    ``gang=False`` restarts ranks individually (independent workers,
    e.g. pserver clients).
    """

    def __init__(self, spawn_fn, num_workers: int, *,
                 max_restarts: int = 2, poll_s: float = 0.2,
                 success_rc: tuple = (0,), gang: bool = True,
                 preempt_rc: Optional[tuple] = None,
                 drain_rc: Optional[tuple] = None,
                 log_fn=print):
        if preempt_rc is None:
            from paddle_tpu.resilience.preempt import EXIT_PREEMPTED
            preempt_rc = (EXIT_PREEMPTED,)
        if drain_rc is None:
            from paddle_tpu.resilience.preempt import EXIT_DRAINED
            drain_rc = (EXIT_DRAINED,)
        self.spawn_fn = spawn_fn
        self.num_workers = num_workers
        self.max_restarts = max_restarts
        self.poll_s = poll_s
        self.success_rc = tuple(success_rc)
        self.preempt_rc = tuple(preempt_rc)
        self.drain_rc = tuple(drain_rc)
        self.gang = gang
        self.restarts = 0                      # gang restarts
        self.rank_restarts = [0] * num_workers
        self.preemption_restarts = 0           # budget-free respawns
        self.drained_exits = 0                 # voluntary scale-in exits
        self._log = log_fn

    def _spawn_all(self, attempt):
        return [self.spawn_fn(r, attempt) for r in range(self.num_workers)]

    def run(self, timeout_s: float = 600.0) -> bool:
        """Supervise until every rank succeeds (True) or the restart
        budget / deadline is exhausted (False; survivors terminated)."""
        import time as _time

        procs = self._spawn_all(0)
        done = [False] * self.num_workers
        # ranks that exited via drain_rc: retired for good — a gang
        # respawn must not resurrect them (their work migrated away)
        drained = [False] * self.num_workers
        deadline = _time.monotonic() + timeout_s
        try:
            while not all(done):
                if _time.monotonic() > deadline:
                    self._log("[elastic] deadline exceeded")
                    return False
                failed = None
                # scan EVERY exited rank before acting on a failure: a
                # drain/success exit in the same poll window must be
                # recorded first, or the gang respawn below would
                # resurrect a rank that already retired voluntarily
                for r, p in enumerate(procs):
                    if done[r] or p.poll() is None:
                        continue
                    rc = p.returncode
                    if rc in self.success_rc:
                        done[r] = True
                    elif rc in self.drain_rc:
                        # voluntary scale-in: the rank migrated its
                        # work away and exited on purpose — done, no
                        # respawn, no budget consumed (gang included:
                        # the fleet CHOSE fewer replicas)
                        self.drained_exits += 1
                        self._log(f"[elastic] rank {r} drained rc={rc}; "
                                  "retired (no respawn, no restart "
                                  "budget consumed)")
                        done[r] = True
                        drained[r] = True
                    elif failed is None:
                        failed = (r, rc)
                if failed is None:
                    _time.sleep(self.poll_s)
                    continue
                r, rc = failed
                preempted = rc in self.preempt_rc
                if self.gang:
                    if preempted:
                        # drained preemption: the platform took the slice,
                        # not the job's fault — respawn on the house (the
                        # run() deadline is the backstop against a
                        # permanently-preempting pool)
                        self.preemption_restarts += 1
                        self._log(f"[elastic] rank {r} preempted rc={rc}; "
                                  f"gang respawn (preemption "
                                  f"{self.preemption_restarts}, no restart "
                                  "budget consumed)")
                    else:
                        if self.restarts >= self.max_restarts:
                            self._log(f"[elastic] rank {r} failed rc={rc}; "
                                      "gang restart budget exhausted")
                            return False
                        self.restarts += 1
                        self._log(f"[elastic] rank {r} failed rc={rc}; gang "
                                  f"restart {self.restarts}/"
                                  f"{self.max_restarts} (kill + respawn all, "
                                  "resume from checkpoint)")
                    for p in procs:
                        if p.poll() is None:
                            p.kill()
                    for p in procs:
                        p.wait()
                    # drained ranks stay retired across a gang respawn
                    # (their state lives on the peers): keep their dead
                    # proc handle and pre-mark them done
                    attempt = self.restarts + self.preemption_restarts
                    procs = [procs[i] if drained[i]
                             else self.spawn_fn(i, attempt)
                             for i in range(self.num_workers)]
                    done = list(drained)
                else:
                    if preempted:
                        self.preemption_restarts += 1
                        self._log(f"[elastic] rank {r} preempted rc={rc}; "
                                  "respawn (no restart budget consumed)")
                        procs[r] = self.spawn_fn(
                            r, self.rank_restarts[r]
                            + self.preemption_restarts)
                        continue
                    if self.rank_restarts[r] >= self.max_restarts:
                        self._log(f"[elastic] rank {r} failed rc={rc}, "
                                  "restart budget exhausted")
                        return False
                    self.rank_restarts[r] += 1
                    self._log(f"[elastic] rank {r} failed rc={rc}; "
                              f"restart {self.rank_restarts[r]}/"
                              f"{self.max_restarts}")
                    procs[r] = self.spawn_fn(r, self.rank_restarts[r])
            return True
        finally:
            for r, p in enumerate(procs):
                if not done[r] and p.poll() is None:
                    p.kill()
            for r, p in enumerate(procs):
                if not done[r]:
                    p.wait()  # reap: no zombies in the supervisor


def local_shard(batch, *, index: Optional[int] = None,
                num: Optional[int] = None):
    """Slice a host's shard out of a global host batch (the data-feed
    filelist-split analog at batch granularity)."""
    import numpy as np

    index = jax.process_index() if index is None else index
    num = jax.process_count() if num is None else num

    def shard(x):
        n = x.shape[0]
        if n % num:
            raise ValueError(
                f"batch dim {n} not divisible by {num} workers — pad or "
                f"drop the remainder explicitly before sharding")
        per = n // num
        return x[index * per:(index + 1) * per]

    return jax.tree_util.tree_map(shard, batch)
