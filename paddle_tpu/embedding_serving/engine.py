"""EmbeddingServingEngine: online embedding lookups with a device cache.

The recommender-at-scale serving loop (ROADMAP item 4): inference
traffic arrives as batches of sparse feature ids; the full table lives
in a :class:`~paddle_tpu.parallel.host_kv.HostKVStore` (or a
:class:`~paddle_tpu.parallel.kv_server.RemoteKVStore` pserver); hot
rows are served from the fixed-shape device cache and misses are pulled
(deduped, ``pull_async``-overlapped) and installed with an eviction
policy. Per batch:

  submit(feat_ids):
    uniq/inv dedup (host)            — HostKVEmbedding's contract
    staleness gate                   — flush the streaming channel when
                                       its lag exceeds the bound, then
                                       drain its applied-update dirty
                                       set: unreferenced ids are
                                       invalidated outright, ids pinned
                                       by in-flight batches get a
                                       version requirement that makes
                                       split() reclassify them as
                                       misses until refreshed (pushed
                                       rows become misses → refreshed;
                                       O(pushed rows), not O(batch))
    pull_async(missing uniq ids)     — overlaps earlier batches' device
                                       work; buffers pinned by handle
  step():
    wait oldest pull → install       — ONE bucketed donated scatter
    gather + DeepFM forward          — ONE fixed-shape jitted call
                                       (pow2 row buckets) → (B,) probs

``submit`` load-sheds with a structured :class:`EmbedReject` (the
:class:`~paddle_tpu.serving.Reject` convention) when the miss pipeline
is ``max_pending`` batches deep — bounded memory AND a bounded
staleness window, since a served batch's rows are never older than its
own submit-time store state.

Metrics (observability registry): hit-rate / staleness gauges,
``embedding_serving_requests_total``, miss-latency and lookup-latency
histograms, eviction + reject counters; zero steady-state recompiles
after :meth:`warmup` (RecompileDetector-asserted in tests and bench).

Persistence: :meth:`snapshot` / :meth:`restore` wrap
``persistence.save_kv_snapshot`` — manifest-committed, hash-verified
KV-table saves that include the streaming version counters.
"""

from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict, deque
from typing import Dict, Optional, Sequence

import numpy as np

from paddle_tpu.embedding_serving.device_cache import (CacheCapacityError,
                                                       DeviceEmbeddingCache,
                                                       _pow2_bucket)
from paddle_tpu.embedding_serving import persistence as _persist

_LOOKUP_BUCKETS = (1e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3, 5e-3,
                   0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 5.0)


@dataclasses.dataclass
class EmbedReject:
    """Structured load-shed verdict (mirrors ``serving.Reject``): why
    the engine refused to queue a lookup batch, and what a client
    should do about it."""
    reason: str              # "miss_queue_full"
    queue_depth: int         # pending lookup batches
    pending_miss_rows: int   # rows still in flight from the store
    retry_after_s: float


class EmbeddingLoadShedError(RuntimeError):
    """Raised by ``submit`` instead of queueing past ``max_pending``;
    carries an :class:`EmbedReject`."""

    def __init__(self, reject: EmbedReject):
        super().__init__(
            f"embedding load shed ({reject.reason}): "
            f"queue_depth={reject.queue_depth} "
            f"pending_miss_rows={reject.pending_miss_rows} "
            f"retry_after={reject.retry_after_s:.4f}s")
        self.reject = reject


@dataclasses.dataclass
class _Pending:
    rid: int
    uniq: np.ndarray                 # (U,) real uniq ids
    uniq_set: set                    # same ids, for eviction protection
    inv: np.ndarray                  # (B, F) indices into uniq
    feat_vals: Optional[np.ndarray]
    handle: object                   # PullHandle | None (no misses)
    miss_ids: np.ndarray
    req: Dict[int, int]              # miss id -> version the refresh
    #                                  must install (staleness bookkeeping)
    hits: int
    submitted_at: float
    pull_issued_at: float
    span: object = None              # trace root (None = tracing off)


class EmbeddingServingEngine:
    """Submit batches of sparse ids → dense embedding rows → (optional)
    DeepFM forward.

    ``model``/``params``: a :class:`~paddle_tpu.models.deepfm.
    DeepFMHostKV` (or any model exposing ``predict_proba(params, rows,
    inv, feat_vals)``); without one the engine serves raw padded row
    arrays. ``capacity`` is the device hot-row count — it must cover at
    least one batch's unique ids (the fixed-shape gather's hard floor).
    """

    def __init__(self, store, model=None, params=None, *,
                 capacity: int = 1 << 16, policy: str = "lru",
                 min_bucket: int = 256, max_pending: int = 4,
                 channel=None, max_staleness_s: Optional[float] = None,
                 max_lag_updates: Optional[int] = None,
                 cache_dtype=None, registry=None, tracer=None):
        import jax

        self.store = store
        self.model = model
        self.params = params
        self.max_pending = int(max_pending)
        self.channel = channel
        self.max_staleness_s = max_staleness_s
        self.max_lag_updates = max_lag_updates
        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        # per-batch lifecycle tracing (host-side only — no jitted code
        # is touched): dedup → miss pull → install → gather → predict
        self.tracer = tracer or obs.tracing.default()
        self.cache = DeviceEmbeddingCache(
            capacity, store.dim, policy=policy, dtype=cache_dtype,
            min_gather_bucket=min_bucket, registry=self._reg)
        self._pending: "deque[_Pending]" = deque()
        # ids whose cached row must reach version v before serving as a
        # hit: pushed rows still referenced by in-flight batches cannot
        # be invalidated (their slots are about to be gathered), so the
        # staleness gate records the required version here and submit's
        # split() reclassifies them as misses until a refresh installs
        self._stale_req: Dict[int, int] = {}
        self._results: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._results_cap = max(64, 8 * self.max_pending)
        self._rid = 0
        self._served_rows = 0
        self._served_hits = 0

        if model is not None:
            self._forward = jax.jit(
                lambda p, rows, inv, fv: model.predict_proba(
                    p, rows, inv, fv))
            self._forward_novals = jax.jit(
                lambda p, rows, inv: model.predict_proba(p, rows, inv))
        self.recompile_detector = obs.RecompileDetector(
            "embedding_serving", warmup=1, registry=self._reg)

        self._req_c = self._reg.counter(
            "embedding_serving_requests_total", "lookup batches submitted")
        self._reject_c = self._reg.counter(
            "embedding_serving_rejected_total",
            "lookup batches load-shed instead of queued")
        self._hit_g = self._reg.gauge(
            "embedding_serving_hit_rate",
            "device-cache hit fraction of id lookups (cumulative, "
            "occurrence-weighted)")
        self._stale_g = self._reg.gauge(
            "embedding_serving_staleness_seconds",
            "streaming-channel lag at the last staleness gate")
        self._lag_g = self._reg.gauge(
            "embedding_serving_lag_updates",
            "streaming pushes accepted but not yet applied")

    # histograms are fetched from the registry at observe time (the
    # ServingEngine idiom), so a bench can unregister() between passes
    # and still see fresh per-pass samples
    def _miss_h(self):
        return self._reg.histogram(
            "embedding_serving_miss_latency_seconds",
            "store pull wall time per batch (issue -> rows ready)",
            buckets=_LOOKUP_BUCKETS)

    def _lookup_h(self):
        return self._reg.histogram(
            "embedding_serving_lookup_seconds",
            "submit -> rows served end to end", buckets=_LOOKUP_BUCKETS)

    # -- request surface --------------------------------------------------

    def submit(self, feat_ids: np.ndarray,
               feat_vals: Optional[np.ndarray] = None) -> int:
        """Enqueue one lookup batch; returns its rid. Dedup + the
        staleness gate + version probe + the async miss pull all happen
        here, so the pull overlaps earlier batches' device work.
        Raises :class:`EmbeddingLoadShedError` when ``max_pending``
        batches are already in flight."""
        now = time.monotonic()
        if len(self._pending) >= self.max_pending:
            rej = EmbedReject(
                "miss_queue_full", len(self._pending),
                int(sum(p.miss_ids.size for p in self._pending)),
                retry_after_s=max(
                    self._miss_h().summary()["mean"], 1e-4))
            self._reject_c.inc(reason=rej.reason)
            if self.tracer.enabled:
                self.tracer.record_span(
                    "embed.request", duration_s=0.0, status="shed",
                    shed_reason=rej.reason,
                    queue_depth=rej.queue_depth)
            raise EmbeddingLoadShedError(rej)
        self._req_c.inc()
        feat_ids = np.asarray(feat_ids, np.int64)
        uniq, inv = np.unique(feat_ids, return_inverse=True)
        inv = inv.reshape(feat_ids.shape).astype(np.int32)
        if uniq.size > self.cache.capacity:
            raise ValueError(
                f"batch has {uniq.size} unique ids > cache capacity "
                f"{self.cache.capacity}")

        self._staleness_gate()
        hit_mask, miss_ids = self.cache.split(
            uniq, self._stale_req if self._stale_req else None)
        # occurrence-weighted traffic: a hot id looked up 100 times in
        # the batch counts 100 hits — the number ads-serving dashboards
        # mean by "hit rate" (uniq-weighted would understate hot-head
        # caching exactly where it matters)
        occ = np.bincount(inv.ravel(), minlength=uniq.size)
        hits = int(occ[hit_mask].sum())
        miss_occ = int(occ[~hit_mask].sum())
        self.cache.note_traffic(hits, miss_occ)
        handle = None
        if miss_ids.size:
            b = _pow2_bucket(miss_ids.size, self.cache.min_install_bucket,
                             max(self.cache.capacity, miss_ids.size))
            out = np.zeros((b, self.store.dim), np.float32)
            handle = self.store.pull_async(miss_ids, out=out)
        req = {}
        if self._stale_req and miss_ids.size:
            sr = self._stale_req
            req = {i: sr[i] for i in miss_ids.tolist() if i in sr}
        self._rid += 1
        span = None
        if self.tracer.enabled:
            span = self.tracer.start_span(
                "embed.request", rid=self._rid, batch=int(inv.size),
                uniq=int(uniq.size), misses=int(miss_ids.size),
                hit_occurrences=hits)
            span.add_event("dedup", uniq=int(uniq.size),
                           misses=int(miss_ids.size))
            if handle is not None:
                span.add_event("pull_issued", rows=int(miss_ids.size))
        self._pending.append(_Pending(
            self._rid, uniq, set(uniq.tolist()), inv, feat_vals, handle,
            miss_ids, req, hits, now, time.monotonic(), span))
        return self._rid

    def step(self) -> Dict[int, np.ndarray]:
        """Complete the OLDEST pending batch: wait its miss pull,
        install the rows (bucketed donated scatter, evicting by
        policy), run the fixed-shape gather (+ model forward), and
        return ``{rid: probs}`` (or ``{rid: padded rows}`` without a
        model)."""
        if not self._pending:
            return {}
        p = self._pending.popleft()
        try:
            return self._step_popped(p)
        except BaseException:
            # the batch is already popped: its root span would otherwise
            # never reach the ring — and the FAILING request's trace is
            # the one an operator needs most
            if p.span is not None:
                p.span.add_event("error")
                p.span.finish(status="error")
            raise

    def _step_popped(self, p: _Pending) -> Dict[int, np.ndarray]:
        if p.handle is not None:
            t0 = time.monotonic()
            rows = p.handle.wait()
            t1 = time.monotonic()
            self._miss_h().observe(t1 - p.pull_issued_at)
            if p.span is not None:
                self.tracer.record_span(
                    "embed.pull_wait", start=t0, end=t1, parent=p.span,
                    rows=int(p.miss_ids.size),
                    pull_age_s=round(t1 - p.pull_issued_at, 6))
            protect = p.uniq_set.union(
                *(q.uniq_set for q in self._pending))
            t0 = time.monotonic()
            try:
                self.cache.install(p.miss_ids, np.asarray(rows),
                                   versions=p.req or None,
                                   protect=protect)
            except CacheCapacityError:
                # the aggregate in-flight working set outgrew the
                # table: protect only THIS batch (capacity must hold
                # one batch — submit's hard check). Later batches whose
                # hit-classified rows get evicted here self-heal below.
                if p.span is not None:
                    p.span.add_event("capacity_retry",
                                     protected=len(p.uniq_set))
                self.cache.install(p.miss_ids, np.asarray(rows),
                                   versions=p.req or None,
                                   protect=p.uniq_set)
            if p.span is not None:
                self.tracer.record_span(
                    "embed.install", start=t0, parent=p.span,
                    rows=int(p.miss_ids.size))
            self._settle_stale(p.req)
        # self-heal: a row classified as a hit at submit may have been
        # evicted since (a later batch's install under capacity
        # pressure). Re-pull the residue synchronously — slow path, but
        # it keeps step() total instead of crashing the popped batch.
        _, gone = self.cache.split(p.uniq)
        if gone.size:
            sr = self._stale_req
            req2 = {i: sr[i] for i in gone.tolist() if i in sr} \
                if sr else {}
            if p.span is not None:
                p.span.add_event("self_heal_repull",
                                 rows=int(gone.size))
            self.cache.install(gone, self.store.pull(gone),
                               versions=req2 or None,
                               protect=p.uniq_set)
            self._settle_stale(req2)
        u_pad = _pow2_bucket(p.uniq.size, self.cache.min_gather_bucket,
                             max(self.cache.capacity, p.uniq.size))
        t0 = time.monotonic()
        rows_dev = self.cache.gather(p.uniq, pad_to=u_pad)
        if self.model is not None:
            import jax.numpy as jnp
            inv = jnp.asarray(p.inv)
            if p.feat_vals is not None:
                out = self._forward(self.params, rows_dev, inv,
                                    jnp.asarray(p.feat_vals, jnp.float32))
            else:
                out = self._forward_novals(self.params, rows_dev, inv)
            out = np.asarray(out)
        else:
            out = np.asarray(rows_dev)
        now = time.monotonic()
        if p.span is not None:
            self.tracer.record_span(
                "embed.gather_forward", start=t0, end=now, parent=p.span,
                uniq=int(p.uniq.size), pad_to=int(u_pad),
                model=self.model is not None)
            p.span.add_event("finished")
            p.span.finish()
        self._served_rows += int(p.inv.size)
        self._served_hits += p.hits
        self._hit_g.set(self._served_hits / max(self._served_rows, 1))
        self._lookup_h().observe(now - p.submitted_at)
        self._results[p.rid] = out
        while len(self._results) > self._results_cap:
            self._results.popitem(last=False)
        return {p.rid: out}

    def result(self, rid: int) -> Optional[np.ndarray]:
        """Pop a finished batch's output (None while pending/consumed;
        the store is bounded — consume promptly)."""
        return self._results.pop(rid, None)

    def serve(self, feat_ids: np.ndarray,
              feat_vals: Optional[np.ndarray] = None) -> np.ndarray:
        """Synchronous convenience: submit one batch and drain the
        pipeline until it completes."""
        rid = self.submit(feat_ids, feat_vals)
        while True:
            done = self.step()
            if rid in done:
                # earlier batches' results stay poppable via result()
                self._results.pop(rid, None)
                return done[rid]

    def pending(self) -> int:
        return len(self._pending)

    # -- freshness --------------------------------------------------------

    def _staleness_gate(self):
        """Enforce + observe the staleness bound, then refresh the
        cache: drain the channel's applied-update dirty set; pushed ids
        nobody is waiting on are invalidated (their next lookup is a
        miss — re-pulled fresh), while ids still referenced by
        in-flight batches cannot have their slots freed (those batches
        are about to gather them), so their required store version is
        recorded in ``_stale_req`` instead — submit's version-aware
        split reclassifies them as misses until a refresh installs at
        that version. O(pushed rows) per serve, not O(batch ids); with
        nothing dirty and nothing outstanding this is two lag reads."""
        ch = self.channel
        if ch is None:
            return
        lag_s = ch.lag_seconds()
        lag_n = ch.lag_updates()
        if (self.max_staleness_s is not None
                and lag_s > self.max_staleness_s) or \
                (self.max_lag_updates is not None
                 and lag_n > self.max_lag_updates):
            t0 = time.monotonic()
            ch.flush()          # hard bound: apply the backlog first
            if self.tracer.enabled:
                self.tracer.record_span(
                    "embed.staleness_flush", start=t0,
                    lag_seconds=round(lag_s, 6), lag_updates=lag_n)
            lag_s, lag_n = 0.0, 0
        self._stale_g.set(lag_s)
        self._lag_g.set(lag_n)
        dirty = ch.drain_dirty()
        if not dirty and not self._stale_req:
            return
        pinned = set().union(*(q.uniq_set for q in self._pending)) \
            if self._pending else set()
        if dirty:
            free = dirty - pinned
            if free:
                self.cache.invalidate(np.fromiter(free, np.int64,
                                                  len(free)))
            held = dirty & pinned
            if held:
                self._stale_req.update(ch.versions(held))
        if self._stale_req:
            # requirements whose ids are no longer pinned downgrade to
            # plain invalidation — keeps _stale_req from accumulating
            unpinned = [i for i in self._stale_req if i not in pinned]
            if unpinned:
                self.cache.invalidate(np.asarray(unpinned, np.int64))
                for i in unpinned:
                    del self._stale_req[i]

    def _settle_stale(self, installed: Dict[int, int]):
        """Clear satisfied refresh requirements (unless a newer push
        raised the bar while the pull was in flight)."""
        if not installed or not self._stale_req:
            return
        sr = self._stale_req
        for i, v in installed.items():
            if sr.get(i) == v:
                del sr[i]

    # -- lifecycle --------------------------------------------------------

    def warmup(self, batch_shape: Sequence[int],
               with_feat_vals: bool = False):
        """Precompile every bucket a ``batch_shape`` (B, F) lookup can
        touch — cache gather/install widths AND the model forward per
        gather width — so steady-state serving compiles nothing."""
        b, f = int(batch_shape[0]), int(batch_shape[1])
        max_uniq = min(b * f, self.cache.capacity)
        self.cache.warmup(max_uniq)
        if self.model is None:
            return
        import jax.numpy as jnp
        inv = jnp.zeros((b, f), jnp.int32)
        fv = jnp.ones((b, f), jnp.float32)
        w = max(self.cache.min_gather_bucket, 1)
        top = _pow2_bucket(max_uniq, self.cache.min_gather_bucket,
                           max(self.cache.capacity, max_uniq))
        while True:
            rows = jnp.zeros((w, self.store.dim), self.cache.dtype)
            if with_feat_vals:
                np.asarray(self._forward(self.params, rows, inv, fv))
            else:
                np.asarray(self._forward_novals(self.params, rows, inv))
            if w >= top:
                break
            w *= 2

    def snapshot(self, directory: str, step: int) -> str:
        """Manifest-committed KV-table snapshot (incl. streaming
        version counters); torn saves are invisible, corrupt payloads
        refused at restore — the resilience discipline."""
        versions = None
        if self.channel is not None:
            self.channel.flush()
            with self.channel._vlock:
                versions = dict(self.channel._versions)
        return _persist.save_kv_snapshot(self.store, directory, step,
                                         versions=versions)

    def restore(self, directory: str, step: Optional[int] = None):
        """Load the newest valid snapshot into the backing store and
        reset the device cache (resident rows may predate the loaded
        table). Restores version counters into the channel."""
        versions = _persist.restore_kv_snapshot(self.store, directory,
                                                step)
        ids = list(self.cache._slot_of)
        if ids:
            self.cache.invalidate(np.asarray(ids, np.int64))
        if self.channel is not None:
            with self.channel._vlock:
                self.channel._versions = dict(versions)
        return versions
