"""Device-resident hot-row cache over a beyond-HBM embedding table.

The serving half of the parameter-server world: the full sparse table
(millions of ids) lives in a host/remote KV store; the device holds a
fixed-shape HBM table of ``capacity`` hot rows plus a host-side id→slot
index. A batch lookup becomes

  uniq ids  ->  split into resident hits / misses (host dict probes)
  misses    ->  pulled from the backing store, installed via ONE
                bucketed scatter (``table.at[slots].set(rows)``,
                table DONATED — the update step never copies HBM)
  all uniq  ->  ONE bucketed fixed-shape gather (``take``) returning
                the padded (U_pad, dim) rows the model consumes

Both the scatter and the gather run at pow2-bucketed widths, so the
number of compiled shapes is O(log max_batch_uniq) and a ``warmup()``
precompiles them all — steady-state serving triggers zero recompiles
(RecompileDetector-asserted by tests and the bench, exactly like the
token-serving engine).

Slot 0 is a reserved NULL slot: gather padding lanes read it and
scatter padding lanes write it, so ragged real counts never change a
compiled shape. Its contents are scratch — no real id ever maps to it.

Host-side cost scales with ids, not python statements: the id→slot map
is one dict maintained with C-level ``update(zip(...))`` bulk ops, and
the eviction policy (``lru`` = least-recently served, ``lfu`` = least
frequently served with LRU tiebreak) lives in slot-indexed numpy
arrays — touching a 10k-id batch is two vectorized writes, and victim
selection is one argsort over used slots. (The first cut kept an
OrderedDict per id; at ~9k uniq ids/batch its per-id bookkeeping cost
more than the entire KV pull it was saving.)

Pure device+index structure: no store dependency — the
:class:`~paddle_tpu.embedding_serving.engine.EmbeddingServingEngine`
mediates pulls/pushes, which keeps this class unit-testable (and
lintable) without the native KV library.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional, Tuple

import numpy as np


def _pow2_bucket(n: int, minimum: int, cap: int) -> int:
    b = max(int(minimum), 1)
    while b < n:
        b *= 2
    # round the cap itself up to a power of two: clamping to a raw
    # (possibly non-pow2) capacity would mint a serve-time width that
    # warmup()'s doubling loop never compiled
    c = 1
    while c < cap:
        c *= 2
    return min(b, c)


class CacheCapacityError(RuntimeError):
    """A single batch references more unique ids than the device table
    can hold — the fixed-shape gather cannot serve it. Size ``capacity``
    to at least the per-batch unique-id high-water mark."""


class DeviceEmbeddingCache:
    """Fixed-shape HBM hot-row table + host id→slot index.

    ``capacity`` device rows (plus the null slot), ``dim`` floats each.
    The jitted update step donates the table, so installs mutate HBM in
    place; the gather step only reads it.
    """

    def __init__(self, capacity: int, dim: int, *, dtype=None,
                 policy: str = "lru", min_gather_bucket: int = 256,
                 min_install_bucket: int = 8, registry=None):
        import jax
        import jax.numpy as jnp

        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        if policy not in ("lru", "lfu"):
            raise ValueError(f"policy must be 'lru' or 'lfu', "
                             f"got {policy!r}")
        self.capacity = int(capacity)
        self.dim = int(dim)
        self.policy = policy
        self.min_gather_bucket = int(min_gather_bucket)
        self.min_install_bucket = int(min_install_bucket)
        self.dtype = dtype or jnp.float32
        # slot 0 = null; real slots 1..capacity
        self.table = jnp.zeros((self.capacity + 1, self.dim), self.dtype)
        self._slot_of: Dict[int, int] = {}
        self._id_of = np.full((self.capacity + 1,), -1, np.int64)
        self._free = list(range(self.capacity, 0, -1))  # pop() -> slot 1 last
        # slot-indexed policy books (vectorized touch/evict)
        self._slot_last = np.full((self.capacity + 1,), -1, np.int64)
        self._slot_freq = np.zeros((self.capacity + 1,), np.int64)
        self._tick = 0
        self._version: Dict[int, int] = {}
        self.warmed_buckets: set = set()      # filled by warmup()

        self._gather_fn = jax.jit(
            lambda table, slots: jnp.take(table, slots, axis=0))
        self._install_fn = jax.jit(
            lambda table, slots, rows: table.at[slots].set(rows),
            donate_argnums=(0,))

        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self._hits = self._reg.counter(
            "embedding_cache_hits_total", "id lookups served from HBM")
        self._misses = self._reg.counter(
            "embedding_cache_misses_total",
            "id lookups that pulled from the store")
        self._evictions = self._reg.counter(
            "embedding_cache_evictions_total", "rows evicted from HBM")

    # -- index ------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._slot_of)

    def resident(self, id_: int) -> bool:
        return int(id_) in self._slot_of

    def version_of(self, id_: int) -> Optional[int]:
        """Version recorded when ``id_``'s row was installed (None when
        not resident)."""
        return self._version.get(int(id_))

    def split(self, uniq_ids: np.ndarray,
              current_versions: Optional[Dict[int, int]] = None
              ) -> Tuple[np.ndarray, np.ndarray]:
        """Partition ``uniq_ids`` into (hit_mask, miss_ids). A resident
        row whose installed version is behind ``current_versions[id]``
        counts as a MISS (stale — streaming refresh path): the caller
        re-pulls it and ``install`` overwrites the same slot."""
        ids = uniq_ids.tolist()
        probe = self._slot_of
        if current_versions is None:
            hit = np.fromiter((i in probe for i in ids), bool,
                              uniq_ids.size)
        else:
            ver = self._version
            hit = np.fromiter(
                ((i in probe
                  and ver.get(i, 0) >= current_versions.get(i, 0))
                 for i in ids), bool, uniq_ids.size)
        return hit, uniq_ids[~hit]

    # -- eviction ---------------------------------------------------------

    def _victim_slots(self, n: int, protect: set) -> np.ndarray:
        """Slots of the ``n`` policy-best eviction victims, never
        touching ``protect``-ed ids. LRU uses argpartition over the
        slot-tick array (protected ids are recently served, so they
        rarely land in the oldest-n window and the first window almost
        always suffices); LFU pays one lexsort. No per-id python
        bookkeeping beyond the protection probe on candidates."""
        cand = np.flatnonzero(self._id_of >= 0)
        if self.policy == "lfu":
            order = cand[np.lexsort((self._slot_last[cand],
                                     self._slot_freq[cand]))]
            ids = self._id_of[order]
            keep = np.fromiter((int(i) not in protect
                                for i in ids.tolist()), bool, ids.size)
            sel = order[keep][:n]
            if sel.size < n:
                raise CacheCapacityError(
                    f"need {n} free slots but only {sel.size} evictable "
                    f"(capacity {self.capacity}, protected "
                    f"{len(protect)}) — batch uniq ids exceed capacity")
            return sel
        k = min(n + 256, cand.size)
        while True:
            part = cand[np.argpartition(self._slot_last[cand],
                                        k - 1)[:k]] \
                if k < cand.size else cand
            part = part[np.argsort(self._slot_last[part],
                                   kind="stable")]   # oldest first
            ids = self._id_of[part]
            keep = np.fromiter((int(i) not in protect
                                for i in ids.tolist()), bool, ids.size)
            sel = part[keep][:n]
            if sel.size >= n:
                return sel
            if k >= cand.size:
                raise CacheCapacityError(
                    f"need {n} free slots but only {sel.size} evictable "
                    f"(capacity {self.capacity}, protected "
                    f"{len(protect)}) — batch uniq ids exceed capacity")
            k = min(k * 2, cand.size)

    def invalidate(self, ids: np.ndarray) -> int:
        """Drop ids from the device index (their next lookup is a miss).
        The HBM rows are left as garbage in now-free slots — unreachable
        through the index, so never served. Returns rows dropped."""
        dropped = []
        for id_ in np.asarray(ids, np.int64).ravel().tolist():
            slot = self._slot_of.pop(id_, None)
            if slot is None:
                continue
            dropped.append(slot)
            self._version.pop(id_, None)
        if dropped:
            s = np.asarray(dropped, np.int64)
            self._id_of[s] = -1
            self._slot_last[s] = -1
            self._slot_freq[s] = 0
            self._free.extend(s.tolist())
        return len(dropped)

    # -- update / serve ---------------------------------------------------

    def install(self, miss_ids: np.ndarray, rows: np.ndarray,
                versions: Optional[Dict[int, int]] = None,
                protect: Optional[Iterable[int]] = None):
        """Write pulled rows into HBM via one bucketed donated scatter.
        Already-resident ids are refreshed in their existing slot; new
        ids take free slots, evicting policy victims (never ``protect``,
        defaulting to the install set itself) when none are free."""
        import jax.numpy as jnp

        miss_ids = np.asarray(miss_ids, np.int64).ravel()
        if miss_ids.size == 0:
            return
        rows = np.ascontiguousarray(rows, np.float32)
        if rows.shape[0] < miss_ids.size or rows.shape[1] != self.dim:
            raise ValueError(f"rows {rows.shape} cannot cover "
                             f"({miss_ids.size}, {self.dim})")
        ids = miss_ids.tolist()
        slots = np.fromiter((self._slot_of.get(i, 0) for i in ids),
                            np.int64, miss_ids.size)
        fresh = slots == 0              # not resident yet
        need = int(fresh.sum())
        short = need - len(self._free)
        if short > 0:
            # evict by reassignment: pop the victims' index entries and
            # hand their slots straight to the new ids — no free-list
            # round trip, dict surgery only (C-level bulk ops)
            vslots = self._victim_slots(
                short, set(ids) | set(int(p) for p in (protect or ())))
            spop, vpop = self._slot_of.pop, self._version.pop
            for old in self._id_of[vslots].tolist():
                spop(old)
                vpop(old, None)
            self._free.extend(vslots.tolist())
            self._id_of[vslots] = -1
            self._slot_last[vslots] = -1
            self._slot_freq[vslots] = 0
            self._evictions.inc(int(vslots.size))
        if need:
            new_slots = np.asarray(self._free[-need:], np.int64)
            del self._free[-need:]
            new_ids = miss_ids[fresh]
            slots[fresh] = new_slots
            self._slot_of.update(
                zip(new_ids.tolist(), new_slots.tolist()))
            self._id_of[new_slots] = new_ids
            self._slot_freq[new_slots] = 0
        self._tick += 1
        self._slot_last[slots] = self._tick
        if versions is not None:
            self._version.update(
                (i, versions.get(i, 0)) for i in ids)
        else:
            ver = self._version
            self._version.update((i, ver.get(i, 0)) for i in ids)
        b = _pow2_bucket(miss_ids.size, self.min_install_bucket,
                         max(self.capacity, miss_ids.size))
        slots_p = np.zeros((b,), np.int32)            # pad -> null slot
        rows_p = np.zeros((b, self.dim), np.float32)
        slots_p[:miss_ids.size] = slots
        rows_p[:miss_ids.size] = rows[:miss_ids.size]
        self.table = self._install_fn(self.table, jnp.asarray(slots_p),
                                      jnp.asarray(rows_p, self.dtype))

    def gather(self, uniq_ids: np.ndarray, *,
               pad_to: Optional[int] = None):
        """One fixed-shape gather of every id's row. Every id must be
        resident (``install`` misses first). Returns a device array
        (U_pad, dim); padding lanes read the null slot (contents
        scratch — the model's ``inv`` indices never point at them).
        Also advances the eviction policy (serve == touch)."""
        import jax.numpy as jnp

        uniq_ids = np.asarray(uniq_ids, np.int64).ravel()
        b = pad_to or _pow2_bucket(uniq_ids.size, self.min_gather_bucket,
                                   max(self.capacity, uniq_ids.size))
        if uniq_ids.size > b:
            raise CacheCapacityError(
                f"{uniq_ids.size} uniq ids > gather width {b}")
        try:
            used = np.fromiter(
                (self._slot_of[i] for i in uniq_ids.tolist()),
                np.int64, uniq_ids.size)
        except KeyError as e:
            raise KeyError(
                f"id {e.args[0]} not resident (install first)") from None
        self._tick += 1
        self._slot_last[used] = self._tick
        self._slot_freq[used] += 1      # uniq ids: no duplicate slots
        slots = np.zeros((b,), np.int32)
        slots[:used.size] = used
        return self._gather_fn(self.table, jnp.asarray(slots))

    def note_traffic(self, hits: int, misses: int):
        self._hits.inc(hits)
        self._misses.inc(misses)

    # -- lifecycle --------------------------------------------------------

    def warmup_plan(self, max_uniq: int):
        """The ``("gather", width)`` / ``("install", width)`` bucket
        signatures :meth:`warmup` precompiles for batches of up to
        ``max_uniq`` unique ids, in compile order — the warmup-side half
        of the bucket-coverage proof (:func:`~paddle_tpu.analysis.
        hlo_lint.embedding_bucket_coverage`)."""
        cap = max(self.capacity, int(max_uniq))
        plan = []
        for kind, minimum in (("gather", self.min_gather_bucket),
                              ("install", self.min_install_bucket)):
            b = max(minimum, 1)
            top = _pow2_bucket(int(max_uniq), minimum, cap)
            while True:
                plan.append((kind, b))
                if b >= top:
                    break
                b *= 2
        return plan

    def reachable_buckets(self, max_uniq: int):
        """Every gather/install width the serve path can request for
        batches of up to ``max_uniq`` unique ids, enumerated by probing
        the STEP-side ``_pow2_bucket`` calls (``gather``/``install``
        bucket misses and uniq sizes 1..max_uniq) at every pow2
        boundary — the step-side half of the coverage proof."""
        max_uniq = int(max_uniq)
        pts = {1, max(max_uniq, 1)}
        p = 1
        while p < max_uniq:           # pow2 boundaries: where the
            pts.add(p)                # bucketing step function can move
            if p + 1 <= max_uniq:
                pts.add(p + 1)
            p *= 2
        sigs = set()
        for n in pts:
            # serve-time calls size their cap as max(capacity, n)
            sigs.add(("gather", _pow2_bucket(
                n, self.min_gather_bucket, max(self.capacity, n))))
            # installs cover 1..uniq misses: same probe points apply
            sigs.add(("install", _pow2_bucket(
                n, self.min_install_bucket, max(self.capacity, n))))
        return sigs

    def warmup(self, max_uniq: int):
        """Precompile every gather and install bucket a batch with up to
        ``max_uniq`` unique ids can hit (all against the null slot — no
        live rows are touched), so steady-state lookups compile
        nothing. Records the compiled set in :attr:`warmed_buckets`."""
        import jax.numpy as jnp

        self.warmed_buckets = set()
        for sig in self.warmup_plan(max_uniq):
            kind, b = sig
            if kind == "gather":
                self._gather_fn(self.table, jnp.zeros((b,), jnp.int32))
            else:
                self.table = self._install_fn(
                    self.table, jnp.zeros((b,), jnp.int32),
                    jnp.zeros((b, self.dim), self.dtype))
            self.warmed_buckets.add(sig)

    def check_invariants(self):
        """Index consistency (the property test's spine): id→slot and
        slot→id are inverse bijections, free+used partition the slots,
        the null slot is never mapped, and the policy/version books
        cover exactly the resident set."""
        used = set(self._slot_of.values())
        assert 0 not in used, "null slot mapped to a real id"
        assert len(used) == len(self._slot_of), "two ids share a slot"
        free = set(self._free)
        assert not (used & free), "slot both free and used"
        assert used | free == set(range(1, self.capacity + 1)), \
            "slots leaked"
        for id_, slot in self._slot_of.items():
            assert self._id_of[slot] == id_, "reverse index mismatch"
        assert set(np.flatnonzero(self._id_of >= 0).tolist()) == used, \
            "slot->id book out of sync"
        assert (self._slot_last[sorted(used)] >= 0).all() if used \
            else True, "used slot without a policy tick"
        assert (self._slot_last[sorted(free)] == -1).all() if free \
            else True, "free slot with a live policy tick"
        assert set(self._version) == set(self._slot_of), \
            "version book out of sync"

    def hit_ratio_window(self) -> float:
        h = self._hits.value()
        m = self._misses.value()
        return h / max(h + m, 1.0)
