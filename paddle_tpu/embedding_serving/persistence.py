"""KV-store snapshots with the resilience manifest discipline.

The embedding service's durable state is the backing KV table (plus the
streaming channel's per-row version counters, so a restored server
resumes with correct freshness bookkeeping). Persistence follows the
two-phase pattern of ``resilience/snapshot.py``: write the payload
files, fsync, then commit a ``manifest.json`` (per-file sha256 + sizes
+ schema) via tmp-write → fsync → atomic rename. No manifest ⇒ the
snapshot is invisible; a torn save can never be restored; a bit-rotted
payload is REFUSED (:class:`SnapshotCorruptionError`, shared with the
resilience engine) and ``latest_valid_step`` falls back past it.

Layout::

    <dir>/step_00000042/table.kv        native kv_save blob
                        versions.npz    streaming version counters
                        manifest.json   committed last, atomically
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.resilience.snapshot import SnapshotCorruptionError

MANIFEST = "manifest.json"
FORMAT_VERSION = 1
_TABLE = "table.kv"
_VERSIONS = "versions.npz"
_CHUNK = 1 << 16


def _sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
    return h.hexdigest()


def _fsync_file(path: str):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _step_dirname(step: int) -> str:
    return f"step_{int(step):08d}"


def _parse_step(name: str) -> Optional[int]:
    if not name.startswith("step_"):
        return None
    try:
        return int(name[len("step_"):])
    except ValueError:
        return None


def save_kv_snapshot(store, directory: str, step: int, *,
                     versions: Optional[Dict[int, int]] = None) -> str:
    """Snapshot ``store`` (HostKVStore/RemoteKVStore surface) at
    ``step``. Returns the committed step directory. Re-saving a step
    that already committed is a no-op (committed steps are immutable,
    like the resilience engine)."""
    sdir = os.path.join(directory, _step_dirname(step))
    if os.path.exists(os.path.join(sdir, MANIFEST)):
        return sdir
    os.makedirs(sdir, exist_ok=True)
    table_path = os.path.join(sdir, _TABLE)
    store.save(table_path)          # flushes outstanding async ops first
    _fsync_file(table_path)
    files = {_TABLE: {"sha256": _sha256(table_path),
                      "bytes": os.path.getsize(table_path)}}
    if versions is not None:
        vpath = os.path.join(sdir, _VERSIONS)
        ids = np.fromiter(versions, np.int64, len(versions))
        vs = np.asarray([versions[int(i)] for i in ids], np.int64)
        np.savez(vpath, ids=ids, versions=vs)
        _fsync_file(vpath)
        files[_VERSIONS] = {"sha256": _sha256(vpath),
                            "bytes": os.path.getsize(vpath)}
    manifest = {"format_version": FORMAT_VERSION, "step": int(step),
                "dim": int(store.dim),
                "optimizer": getattr(store, "optimizer", None),
                "rows": len(store), "files": files,
                "created_at": time.time()}
    tmp = os.path.join(sdir, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(sdir, MANIFEST))
    return sdir


def _verify(sdir: str, manifest: dict):
    for name, rec in manifest["files"].items():
        path = os.path.join(sdir, name)
        if not os.path.exists(path):
            raise SnapshotCorruptionError(f"{path} missing")
        if os.path.getsize(path) != rec["bytes"] or \
                _sha256(path) != rec["sha256"]:
            raise SnapshotCorruptionError(
                f"{path} does not match its manifest hash")


def committed_steps(directory: str) -> List[int]:
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        step = _parse_step(name)
        if step is not None and os.path.exists(
                os.path.join(directory, name, MANIFEST)):
            out.append(step)
    return sorted(out)


def latest_valid_step(directory: str) -> Optional[int]:
    """Newest committed step whose payload verifies — torn/corrupt
    snapshots are skipped, falling back to the previous good one."""
    for step in reversed(committed_steps(directory)):
        sdir = os.path.join(directory, _step_dirname(step))
        try:
            with open(os.path.join(sdir, MANIFEST)) as f:
                manifest = json.load(f)
            _verify(sdir, manifest)
            return step
        except (SnapshotCorruptionError, OSError, ValueError,
                KeyError):
            continue
    return None


def restore_kv_snapshot(store, directory: str,
                        step: Optional[int] = None
                        ) -> Dict[int, int]:
    """Load the newest valid (or a specific committed) snapshot into
    ``store``; hashes are re-verified first and a corrupt payload is
    refused. Returns the saved version counters ({} when none were
    stored)."""
    if step is None:
        step = latest_valid_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no valid committed snapshot under {directory}")
    sdir = os.path.join(directory, _step_dirname(step))
    mpath = os.path.join(sdir, MANIFEST)
    if not os.path.exists(mpath):
        raise FileNotFoundError(f"step {step} was never committed")
    with open(mpath) as f:
        manifest = json.load(f)
    if manifest.get("dim") != store.dim:
        raise SnapshotCorruptionError(
            f"snapshot dim {manifest.get('dim')} != store dim "
            f"{store.dim}")
    _verify(sdir, manifest)
    store.load(os.path.join(sdir, _TABLE))
    versions: Dict[int, int] = {}
    if _VERSIONS in manifest["files"]:
        with np.load(os.path.join(sdir, _VERSIONS)) as z:
            versions = {int(i): int(v)
                        for i, v in zip(z["ids"], z["versions"])}
    return versions
