"""Online embedding serving: device-cached host-KV lookups with
streaming updates.

The serving half of the parameter-server world (ROADMAP item 4 — the
ads/recsys production loop): millions of sparse embedding rows live in
a host/remote KV store; inference pulls hot rows through a fixed-shape
device cache; online-learning pushes from a trainer serve within a
bounded staleness window. Four parts:

1. **Device hot-row cache** (`device_cache.py`):
   :class:`DeviceEmbeddingCache` — an HBM table (capacity ≪ vocab) with
   a host id→slot index, ONE pow2-bucketed fixed-shape gather per
   lookup and ONE bucketed donated scatter per install, LRU/LFU
   eviction, ``warmup()`` ⇒ zero steady-state recompiles.
2. **Streaming updates** (`streaming.py`):
   :class:`StreamingUpdateChannel` — an AsyncCommunicator-style
   trainer→server push channel (merged background applies, value or
   gradient pushes) with per-row version counters; pushed rows refresh
   cached device slots on their next lookup, and channel lag (seconds
   and updates behind) is the observable, engine-enforced staleness
   bound.
3. **Serving engine** (`engine.py`): :class:`EmbeddingServingEngine` —
   ``submit``/``step``/``serve`` batches of sparse ids → dense rows →
   DeepFM probabilities, miss pulls ``pull_async``-overlapped with
   device work, structured :class:`EmbeddingLoadShedError` rejects when
   the miss pipeline saturates, hit-rate/staleness/miss-latency metrics
   in the observability registry.
4. **Persistence** (`persistence.py`): manifest-committed, sha256-
   verified KV-table snapshots (the resilience discipline) including
   the streaming version counters.
"""

from paddle_tpu.embedding_serving.device_cache import (CacheCapacityError,
                                                       DeviceEmbeddingCache)
from paddle_tpu.embedding_serving.streaming import StreamingUpdateChannel
from paddle_tpu.embedding_serving.engine import (EmbeddingLoadShedError,
                                                 EmbeddingServingEngine,
                                                 EmbedReject)
from paddle_tpu.embedding_serving.persistence import (committed_steps,
                                                      latest_valid_step,
                                                      restore_kv_snapshot,
                                                      save_kv_snapshot)

__all__ = [
    "CacheCapacityError", "DeviceEmbeddingCache",
    "StreamingUpdateChannel",
    "EmbeddingLoadShedError", "EmbeddingServingEngine", "EmbedReject",
    "committed_steps", "latest_valid_step", "restore_kv_snapshot",
    "save_kv_snapshot",
]
