"""Trainer→server streaming updates for the online embedding service.

The online-learning half of the ads/recsys loop: a trainer keeps
pushing sparse-row updates (fresh row VALUES from its optimizer, or raw
gradients for the store's host-side optimizer) and the serving side
must pick them up within seconds. Reference semantics are
``parallel/communicator.py``'s AsyncCommunicator: pushes enqueue and
return immediately; ONE background worker drains the queue, merges up
to ``max_merge`` pending pushes (last-writer-wins per id for values,
sum for gradients — the send-queue merge of communicator.h:166), and
applies them to the backing KV store.

Freshness bookkeeping, the part serving needs:

- **per-row version counters**: every id touched by an applied push
  bumps its version; the device cache records the version it installed,
  so :meth:`EmbeddingServingEngine.submit`'s version probe reclassifies
  a stale cached row as a miss (refresh) — a pushed row is re-served
  from the store on the very next lookup after its update applies.
- **staleness bound**: :meth:`lag_seconds` (age of the oldest
  unapplied push) and :meth:`lag_updates` (pushes still queued) are the
  observable lag; the engine enforces its configured bound by draining
  the channel (``flush``) before serving whenever the bound is
  exceeded, and exports both as gauges.

Thread contract: one internal worker thread; ``push_rows``/
``push_grads`` are safe from any thread (the trainer's); ``versions``
snapshots are lock-guarded. Worker failures surface at the next
``push``/``flush`` (never silently dropped), like AsyncCommunicator.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional, Sequence

import numpy as np

from paddle_tpu.analysis.concurrency import guarded_by


@guarded_by("_cv", "_pending", "_oldest_pending_ts", "_error")
@guarded_by("_vlock", "_versions", "_dirty")
class StreamingUpdateChannel:
    """Bounded async push channel between a trainer and a serving
    engine's backing store."""

    def __init__(self, store, *, max_merge: int = 32,
                 queue_size: int = 256, registry=None, tracer=None):
        self.store = store
        self.max_merge = int(max_merge)
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._stop = threading.Event()
        self._cv = threading.Condition()
        self._pending = 0
        self._oldest_pending_ts: Optional[float] = None
        self._vlock = threading.Lock()
        self._versions: Dict[int, int] = {}
        self._dirty: set = set()      # ids applied since the last drain
        self._error: Optional[Exception] = None
        self.pushed_rows = 0          # rows received
        self.applied_batches = 0      # store applications (post-merge)

        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        # applier-thread spans live on the worker's OWN thread-local
        # stack (a fresh trace per apply) — the thread-correct
        # attribution the tracing module's design notes call out
        self.tracer = tracer or obs.tracing.default()
        self._apply_h = self._reg.histogram(
            "embedding_stream_apply_seconds",
            "store-apply wall time per merged push batch")
        self._applied_c = self._reg.counter(
            "embedding_stream_rows_applied_total",
            "sparse rows applied to the backing store")
        self._thread = threading.Thread(target=self._worker, daemon=True)
        self._thread.start()

    # -- trainer side -----------------------------------------------------

    def push_rows(self, ids: np.ndarray, rows: np.ndarray):
        """Enqueue fresh row VALUES (trainer-side optimizer already
        applied — the GeoSGD/set_rows shape). Copies its inputs;
        blocks only when the queue is full (backpressure)."""
        self._push(("rows", *self._copy(ids, rows), None))

    def push_grads(self, ids: np.ndarray, grads: np.ndarray, lr: float):
        """Enqueue a sparse GRADIENT push (the store's host optimizer
        applies it — the Async hogwild shape)."""
        self._push(("grads", *self._copy(ids, grads), float(lr)))

    def _copy(self, ids, vals):
        ids = np.array(ids, np.int64, copy=True).ravel()
        vals = np.array(vals, np.float32, copy=True)
        if vals.shape != (ids.size, self.store.dim):
            raise ValueError(f"vals shape {vals.shape} != "
                             f"({ids.size}, {self.store.dim})")
        return ids, vals

    def _push(self, item):
        self._raise_if_failed()
        now = time.monotonic()
        with self._cv:
            self._pending += 1
            if self._oldest_pending_ts is None:
                self._oldest_pending_ts = now
        self.pushed_rows += item[1].size
        self._q.put(item + (now,))

    # -- freshness surface ------------------------------------------------

    def version_of(self, id_: int) -> int:
        with self._vlock:
            return self._versions.get(int(id_), 0)

    def versions(self, ids: Sequence[int]) -> Dict[int, int]:
        """Snapshot of current per-row versions for ``ids`` (0 = never
        pushed). The engine compares these against install versions."""
        with self._vlock:
            return {int(i): self._versions.get(int(i), 0) for i in ids}

    def drain_dirty(self, keep=None) -> set:
        """Pop the ids whose updates have APPLIED since the last drain
        — the serving engine invalidates exactly these device slots
        (O(pushed rows) per serve, not O(batch ids)). Ids in ``keep``
        stay queued for a later drain (in-flight batches may still
        gather their current slots)."""
        with self._vlock:
            if not self._dirty:
                return set()
            if keep:
                out = {i for i in self._dirty if i not in keep}
                self._dirty -= out
            else:
                out, self._dirty = self._dirty, set()
            return out

    def lag_updates(self) -> int:
        """Pushes accepted but not yet applied to the store."""
        with self._cv:
            return self._pending

    def lag_seconds(self) -> float:
        """Age of the oldest unapplied push (0.0 when drained) — the
        observable staleness the engine bounds."""
        with self._cv:
            if self._oldest_pending_ts is None:
                return 0.0
            return max(time.monotonic() - self._oldest_pending_ts, 0.0)

    # -- worker -----------------------------------------------------------

    def _worker(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                items = [self._q.get(timeout=0.05)]
            except queue.Empty:
                continue
            while len(items) < self.max_merge:
                try:
                    items.append(self._q.get_nowait())
                except queue.Empty:
                    break
            count = len(items)
            err = None
            try:
                self._apply(items)
            except Exception as e:
                err = e
            with self._cv:
                if err is not None:
                    self._error = err
                self._pending -= count
                if self._pending == 0:
                    self._oldest_pending_ts = None
                self._cv.notify_all()

    def _apply(self, items):
        t0 = time.monotonic()
        # merge: consecutive same-kind pushes collapse into one store
        # call (values last-writer-wins per id, grads concatenated —
        # the store's sharded optimizer accumulates them)
        i = 0
        applied = 0
        while i < len(items):
            kind = items[i][0]
            j = i
            while j < len(items) and items[j][0] == kind and \
                    (kind == "rows" or items[j][3] == items[i][3]):
                j += 1
            group = items[i:j]
            if kind == "rows":
                merged: Dict[int, np.ndarray] = {}
                for _, ids, vals, _, _ in group:
                    for k, id_ in enumerate(ids.tolist()):
                        merged[id_] = vals[k]
                ids = np.fromiter(merged, np.int64, len(merged))
                vals = np.stack([merged[x] for x in ids.tolist()]) \
                    if len(merged) else \
                    np.zeros((0, self.store.dim), np.float32)
                if ids.size:
                    self.store.set_rows(ids, vals)
            else:
                ids = np.concatenate([g[1] for g in group])
                vals = np.concatenate([g[2] for g in group])
                if ids.size:
                    self.store.push(ids, vals, group[0][3], wait=True)
            applied_ids = ids.tolist()
            with self._vlock:
                for id_ in applied_ids:
                    self._versions[id_] = self._versions.get(id_, 0) + 1
                self._dirty.update(applied_ids)
            applied += int(ids.size)
            i = j
        self.applied_batches += 1
        self._applied_c.inc(applied)
        now = time.monotonic()
        self._apply_h.observe(now - t0)
        if self.tracer.enabled:
            self.tracer.record_span("embed.stream_apply", start=t0,
                                    end=now, rows=applied,
                                    merged_pushes=len(items))

    # -- lifecycle --------------------------------------------------------

    def _raise_if_failed(self):
        # read-and-clear is a two-step mutation: without the lock a
        # worker error landing between the read and the clear is lost
        with self._cv:
            err, self._error = self._error, None
        if err is not None:
            raise RuntimeError("streaming update worker failed") from err

    def flush(self):
        """Block until every accepted push is applied to the store —
        the engine's hard staleness-bound enforcement point."""
        with self._cv:
            self._cv.wait_for(lambda: self._pending == 0)
        self._raise_if_failed()

    def stop(self):
        self.flush()
        self._stop.set()
        self._thread.join(timeout=10)

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass
