"""Inference export/serving: the AnalysisPredictor-world replacement.

Reference mapping (SURVEY.md §2.7):
- ``save_inference_model`` (``io.py:974`` — prune program to feed/fetch
  targets, serialize ProgramDesc ``__model__`` + params) →
  :func:`save_inference_model`: serialize the jitted forward as portable
  StableHLO (``jax.export``) + the param pytree. The StableHLO artifact is
  the ``__model__`` analog: loadable without the Python model class.
- ``AnalysisPredictor`` (api/analysis_predictor.h:47 — load, run analysis
  passes, zero-copy run loop) → :class:`Predictor` (in-process) and the
  C++ native serving shell :class:`paddle_tpu.native.pjrt.NativePredictor`
  (``native/pjrt_runner.cc``: dlopen a PJRT C-API plugin, compile the
  frozen StableHLO once, serve over a C ABI — the capi/ analog). XLA
  replaces the analysis pass pipeline (fuse passes ≙ XLA fusion;
  memory_optimize ≙ buffer assignment).
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax import export as jax_export

from paddle_tpu import io as io_lib

_MODEL_FILE = "__model__.stablehlo"
_PARAMS_FILE = "params.pkl"
_META_FILE = "meta.json"


def save_inference_model(path: str, fn, params: Any,
                         example_inputs: Sequence[Any],
                         input_names: Optional[Sequence[str]] = None,
                         freeze_native: bool = True,
                         platforms: Optional[Sequence[str]] = None,
                         weight_quantize: Optional[str] = None):
    """Export ``fn(params, *inputs)`` for serving.

    Writes into ``path`` (a directory):
      __model__.stablehlo         portable serialized export (vm-agnostic)
      params.pkl                  host copy of the param pytree
      meta.json                   input/output names/shapes/dtypes
    and, with ``freeze_native`` (for the C++ PJRT runner):
      __model__frozen__.stablehlo raw StableHLO bytecode with the params
                                  BAKED IN as constants (inputs-only main —
                                  the frozen-program serving convention;
                                  the reference's save_inference_model
                                  likewise prunes to a feed/fetch program)
      compile_options.pb          serialized XLA CompileOptionsProto

    ``platforms``: lowering platforms for the export (e.g. ["tpu"] to
    export a serving artifact for TPU from a CPU dev host). Default: the
    current backend. The frozen native artifact requires a SINGLE
    platform (a multi-platform module takes a platform-index argument
    the C++ runner does not feed).

    ``weight_quantize="int8"``: int8 serving artifact (the reference
    freezes quantized programs for deployment via QuantizationFreezePass
    + save_inference_model, contrib/slim quantization_pass.py:587).
    Weights are stored/baked as per-channel symmetric int8
    (slim.quantize_weights_int8) and dequantized IN-GRAPH at the compute
    edge — params.pkl, the frozen native artifact, and the weights'
    device residency shrink ~4x. In the frozen artifact the int8
    constants sit behind ``lax.optimization_barrier`` so XLA cannot
    constant-fold q*scale back to full-width float (test_inference
    asserts s8 buffers survive in the OPTIMIZED HLO); in the Predictor
    path the int8 weights are arguments, which XLA never folds. Whether
    per-call weight HBM *reads* happen at int8 width depends on the
    backend fusing the dequant into the consumer (the CPU backend
    materializes a float temp; TPU measurement is part of the bench
    session) — the guaranteed wins are artifact size and at-rest
    memory. Works for both PTQ (pass trained float params) and
    QAT-frozen params (pass slim.qat_convert(...) output — already
    grid-snapped, so int8 storage is exact).
    """
    os.makedirs(path, exist_ok=True)
    if platforms is not None and freeze_native and len(platforms) != 1:
        raise ValueError("freeze_native requires exactly one platform; "
                         f"got {platforms}")
    if weight_quantize not in (None, "int8"):
        raise ValueError(f"weight_quantize must be None or 'int8', "
                         f"got {weight_quantize!r}")

    if weight_quantize == "int8":
        from paddle_tpu import slim
        params = slim.quantize_weights_int8(params)

        def fwd(qparams, *inputs):
            from paddle_tpu import slim
            # barrier keeps baked int8 constants int8 through XLA's
            # constant folding (frozen path); harmless for the
            # argument path where folding can't happen anyway
            return fn(slim.dequantize_weights(
                qparams, keep_int8_resident=True), *inputs)
    else:
        def fwd(params, *inputs):
            return fn(params, *inputs)

    exp = jax_export.export(jax.jit(fwd), platforms=platforms)(
        params, *example_inputs)
    with open(os.path.join(path, _MODEL_FILE), "wb") as f:
        f.write(exp.serialize())
    io_lib.save_params(params, os.path.join(path, _PARAMS_FILE))
    names = list(input_names or
                 [f"x{i}" for i in range(len(example_inputs))])
    out_leaves = list(exp.out_avals)  # flattened, no extra trace
    meta = {
        "input_names": names,
        "inputs": [{"shape": list(np.shape(a)),
                    "dtype": str(np.asarray(a).dtype)}
                   for a in example_inputs],
        "outputs": [{"shape": list(o.shape), "dtype": str(o.dtype)}
                    for o in out_leaves],
        "weight_quantize": weight_quantize,
    }

    frozen_files = ("__model__frozen__.stablehlo", "compile_options.pb")
    if freeze_native:
        frozen = jax_export.export(
            jax.jit(lambda *inputs: fwd(params, *inputs)),
            platforms=platforms)(*example_inputs)
        with open(os.path.join(path, frozen_files[0]), "wb") as f:
            f.write(frozen.mlir_module_serialized)
        from jaxlib import xla_client
        with open(os.path.join(path, frozen_files[1]), "wb") as f:
            f.write(xla_client.CompileOptions().SerializeAsString())
    else:
        # never leave a PREVIOUS export's frozen artifacts behind — the
        # native runner would silently serve the old weights
        for fname in frozen_files:
            fpath = os.path.join(path, fname)
            if os.path.exists(fpath):
                os.remove(fpath)

    with open(os.path.join(path, _META_FILE), "w") as f:
        json.dump(meta, f, indent=2)


def _is_bn(node) -> bool:
    return (isinstance(node, dict)
            and {"scale", "bias", "mean", "variance"} <= set(node))


def _fold_pair(conv, bn, eps):
    """Fold an eval-mode BatchNorm into the preceding conv's params.

    Returns (conv', bn') computing the identical function: the per-
    channel scale s = gamma/sqrt(var+eps) moves INTO the conv weight
    (last axis = out channels — also what int8 export should quantize),
    and bn' degenerates to a pure bias add (scale 1, mean 0,
    variance 1-eps so sqrt(var+eps) == 1 exactly)."""
    import jax.numpy as jnp

    s = bn["scale"] / jnp.sqrt(bn["variance"] + eps)
    conv = dict(conv)
    conv["weight"] = conv["weight"] * s
    if "bias" in conv:
        new_bias = bn["bias"] + s * (conv["bias"] - bn["mean"])
        conv["bias"] = jnp.zeros_like(conv["bias"])
    else:
        new_bias = bn["bias"] - s * bn["mean"]
    bn = dict(bn)
    bn["scale"] = jnp.ones_like(bn["scale"])
    bn["bias"] = new_bias
    bn["mean"] = jnp.zeros_like(bn["mean"])
    bn["variance"] = jnp.ones_like(bn["variance"]) * (1.0 - eps)
    return conv, bn


def fold_batch_norms(params, eps: float = 1e-5):
    """Inference-time conv+BN folding (the reference's
    ``conv_bn_fuse_pass``, framework/ir/conv_bn_fuse_pass.cc — there an
    IR pass over the frozen graph; here a param-tree transform).

    Detects the two layouts the model zoo uses: a ``{"conv": .., "bn":
    ..}`` sibling pair (ConvBNLayer — ResNet/MobileNet/SE-ResNeXt/
    detectors) and parallel ``{"convs": {i: ..}, "bns": {i: ..}}``
    LayerLists (VGG). EVAL graphs only — training mode recomputes batch
    statistics, which folding cannot represent. The transformed tree
    evaluates identically (BN degenerates to the bias add), so it drops
    into the same model object; pair with
    ``save_inference_model(weight_quantize="int8")`` so quantization
    sees the folded weights."""
    if not isinstance(params, dict):
        return params
    out = {k: fold_batch_norms(v, eps) for k, v in params.items()}
    if ("conv" in out and "bn" in out and _is_bn(out["bn"])
            and isinstance(out["conv"], dict) and "weight" in out["conv"]):
        out["conv"], out["bn"] = _fold_pair(out["conv"], out["bn"], eps)
    if (isinstance(out.get("convs"), dict)
            and isinstance(out.get("bns"), dict)
            # fold ONLY index-aligned lists (bns[i] follows convs[i], the
            # VGG layout). A key mismatch means an offset mapping — e.g.
            # DCGAN's discriminator has bns[i] after convs[i+1] — where
            # positional folding would silently corrupt the function.
            and set(out["convs"]) == set(out["bns"])):
        for i in out["bns"]:
            if (_is_bn(out["bns"][i])
                    and isinstance(out["convs"][i], dict)
                    and "weight" in out["convs"][i]):
                out["convs"] = dict(out["convs"])
                out["bns"] = dict(out["bns"])
                out["convs"][i], out["bns"][i] = _fold_pair(
                    out["convs"][i], out["bns"][i], eps)
    return out


def load_inference_model(path: str) -> "Predictor":
    return Predictor(path)


def make_serving_engine(model, params, **kwargs):
    """Continuous-batching serving front end for a generative model —
    the high-QPS sibling of :class:`Predictor` (which serves one
    exported forward per ``run()``). Builds a
    :class:`paddle_tpu.serving.ServingEngine` over a paged KV cache:
    ``submit()`` requests, drive ``step()`` (or ``generate_many``), and
    the engine keeps its fixed decode slots full — admission into freed
    slots, immediate EOS eviction, O(live tokens) ragged paged decode
    attention — while reporting tokens/s, TTFT, slot occupancy and page
    utilization through the observability registry."""
    from paddle_tpu import serving as _serving
    return _serving.ServingEngine(model, params, **kwargs)


def make_serving_fleet(model, params, *, num_replicas: int = 2,
                       policy: str = "affinity", registry=None,
                       tracer=None, warmup: bool = True,
                       autoscaler=None, seed: int = 0, faults=None,
                       postmortem_dir=None, shed_spike_threshold: int = 4,
                       **engine_kwargs):
    """Multi-replica serving front end — N continuous-batching
    :func:`make_serving_engine` replicas behind one
    :class:`paddle_tpu.serving.fleet.FleetRouter`: prefix-affinity
    routing (shared-prompt traffic lands where its prefix pages are
    already hot), power-of-two-choices load balancing over live
    ``health()``, router-minted trace ids crossing into replica spans,
    and (optionally, via ``autoscaler=``) burn-rate elastic scaling
    with live request migration on drain. All replicas share ``model``
    + ``params`` (weights are read-only) and the given tracer so the
    fleet emits ONE timeline; each gets its own metrics registry plus
    the shared ``registry`` for fleet-level series. ``engine_kwargs``
    pass through to every :class:`~paddle_tpu.serving.ServingEngine`.
    Fault tolerance is armed by default (``faults=`` takes a
    :class:`~paddle_tpu.serving.fleet.FaultPolicy`): crashed/hung
    replicas are detected and ejected with their requests redriven
    exactly-once, and per-replica circuit breakers pause routing to
    transiently sick replicas; ``postmortem_dir=`` additionally writes
    each ejection/breaker-open flight-recorder bundle to disk (see
    :mod:`paddle_tpu.observability.flight`). Returns the router; replicas are warmed
    (every bucket precompiled) before it is handed back unless
    ``warmup=False``."""
    from paddle_tpu import observability as _obs
    from paddle_tpu import serving as _serving
    from paddle_tpu.serving import fleet as _fleet
    registry = registry or _obs.default()
    tracer = tracer or _obs.tracing.default()
    reps = []
    for i in range(num_replicas):
        eng = _serving.ServingEngine(
            model, params, registry=_obs.MetricsRegistry(),
            tracer=tracer, **engine_kwargs)
        rep = _fleet.LocalReplica(eng, name=f"replica{i}")
        if warmup:
            rep.warmup()
        reps.append(rep)
    return _fleet.FleetRouter(reps, policy=policy, registry=registry,
                              tracer=tracer, seed=seed,
                              autoscaler=autoscaler, faults=faults,
                              postmortem_dir=postmortem_dir,
                              shed_spike_threshold=shed_spike_threshold)


def make_net_serving_fleet(addresses, *, policy: str = "affinity",
                           registry=None, tracer=None, seed: int = 0,
                           faults=None, postmortem_dir=None,
                           call_timeout_s: float = 60.0,
                           shed_spike_threshold: int = 4):
    """Process-isolated serving front end — the network sibling of
    :func:`make_serving_fleet`. Each address in ``addresses`` points at
    a replica server process (spawn them with
    ``python -m paddle_tpu.serving.fleet.net.replica_server`` or
    :func:`paddle_tpu.serving.fleet.net.spawn_replica_server`); this
    connects a :class:`~paddle_tpu.serving.fleet.net.NetReplica` to
    each and fronts them with the same
    :class:`~paddle_tpu.serving.fleet.FleetRouter` the in-process fleet
    uses — identical routing, breakers, exactly-once redrive and
    migration, because the router cannot tell a socket from a thread
    (the ReplicaHandle contract). A dead process shows up as transport
    errors that trip its breaker and eject it; wrap the router in a
    :class:`~paddle_tpu.serving.fleet.net.FrontDoor` to stream tokens
    to clients. Returns the router."""
    from paddle_tpu import observability as _obs
    from paddle_tpu.serving import fleet as _fleet
    from paddle_tpu.serving.fleet import net as _net
    registry = registry or _obs.default()
    tracer = tracer or _obs.tracing.default()
    reps = [_net.NetReplica(addr, call_timeout_s=call_timeout_s,
                            registry=registry)
            for addr in addresses]
    return _fleet.FleetRouter(reps, policy=policy, registry=registry,
                              tracer=tracer, seed=seed, faults=faults,
                              postmortem_dir=postmortem_dir,
                              shed_spike_threshold=shed_spike_threshold)


def make_embedding_serving_engine(store, model=None, params=None,
                                  **kwargs):
    """Online embedding-lookup serving front end — the sparse/recsys
    sibling of :func:`make_serving_engine`. Builds a
    :class:`paddle_tpu.embedding_serving.EmbeddingServingEngine` over a
    host/remote KV backing store: ``submit()`` batches of sparse ids,
    drive ``step()`` (or call ``serve()``), and hot rows come from a
    fixed-shape device cache (misses pulled async and installed with
    LRU/LFU eviction) while trainer pushes stream in through a
    :class:`~paddle_tpu.embedding_serving.StreamingUpdateChannel` under
    an enforced staleness bound — hit-rate, staleness, miss-latency and
    eviction metrics land in the observability registry."""
    from paddle_tpu import embedding_serving as _es
    return _es.EmbeddingServingEngine(store, model, params, **kwargs)


class Predictor:
    """Zero-copy-ish serving wrapper over an exported model.

    ``run(*inputs)`` or ``run(feed={name: array})`` — feed-dict parity with
    the reference Executor feed/fetch protocol.
    """

    def __init__(self, path: str):
        with open(os.path.join(path, _MODEL_FILE), "rb") as f:
            self._exported = jax_export.deserialize(f.read())
        self._params = io_lib.load_params(os.path.join(path, _PARAMS_FILE))
        with open(os.path.join(path, _META_FILE)) as f:
            self.meta = json.load(f)
        self.input_names = self.meta["input_names"]
        from paddle_tpu import observability as _obs
        self._reg = _obs.default()
        self._reg.counter("inference_predictors_total",
                          "Predictor instances loaded").inc()

    def run(self, *inputs, feed: Optional[Dict[str, Any]] = None):
        import time as _time
        if feed is not None:
            inputs = tuple(feed[name] for name in self.input_names)
        t0 = _time.perf_counter()
        out = self._exported.call(self._params, *inputs)
        # serving observability: request count + dispatch latency, per
        # exported artifact — the AnalysisPredictor-side QPS counters
        self._reg.counter("inference_requests_total").inc()
        self._reg.histogram("inference_latency_seconds",
                            "Predictor.run dispatch latency").observe(
                                _time.perf_counter() - t0)
        return out
