"""paddle_tpu: a TPU-native deep-learning framework.

A ground-up JAX/XLA/Pallas re-design with the capabilities of PaddlePaddle
Fluid 1.5 (see SURVEY.md at the repo root for the capability map). The
compute path is traced Python -> XLA HLO -> pjit/GSPMD over a device mesh;
runtime services (data feeding, inference serving) are native C++.
"""

from paddle_tpu.version import __version__

from paddle_tpu import (amp, analysis, config, core, data, debug,
                        embedding_serving, fleet, inference, io, kernels,
                        metrics, models, nn, observability, ops,
                        optimizer, parallel, profiler, resilience,
                        serving, train, trainer)
from paddle_tpu.trainer import Trainer
from paddle_tpu.config import global_config, set_flags
from paddle_tpu.core.mesh import MeshConfig, make_mesh, mesh_context
from paddle_tpu.executor import CompiledProgram, Executor, Program
from paddle_tpu.train import build_eval_step, build_train_step, make_train_state

__all__ = [
    "__version__", "amp", "analysis", "config", "core", "data", "debug",
    "embedding_serving", "fleet", "inference", "io", "kernels", "metrics",
    "models", "nn", "observability", "ops", "optimizer", "parallel",
    "profiler", "resilience", "serving", "train", "trainer", "Trainer",
    "global_config", "set_flags", "MeshConfig", "make_mesh", "mesh_context",
    "CompiledProgram", "Executor", "Program",
    "build_eval_step", "build_train_step", "make_train_state",
]
