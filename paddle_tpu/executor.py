"""Executor + Program: the run-a-model facade.

Reference mapping:
- ``Executor`` (``python/paddle/fluid/executor.py:418``, C++ hot loop
  ``executor.cc:437``) interprets a ProgramDesc op-by-op. The TPU-native
  equivalent compiles the whole step with XLA once and replays it:
  :class:`Program` wraps a traced step function; :class:`Executor` feeds
  host arrays, runs the compiled executable, fetches host results.
- ``CompiledProgram.with_data_parallel`` (``compiler.py:138``) + the
  AllReduce SSA-graph machinery → :meth:`Program.compile` with a mesh:
  pjit/GSPMD shards the batch over ``(dp, fsdp)`` axes; gradient allreduce
  is inserted by XLA, replacing AllReduceOpHandle (details/
  all_reduce_op_handle.cc:127).
- feed/fetch ops (``controlflow/feed_op.cc``) → named kwargs and returned
  pytrees; no graph mutation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional, Sequence

import time

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu import observability
from paddle_tpu.core import mesh as mesh_lib


@dataclasses.dataclass
class Program:
    """A step function + metadata; the ProgramDesc analog (serializable via
    paddle_tpu.inference.export to StableHLO rather than protobuf).

    ``fn(state, **feeds) -> (state, fetches)`` for train programs, or
    ``fn(params, **feeds) -> fetches`` for inference; the Executor doesn't
    care — it passes state through if the output is a 2-tuple with the same
    structure.
    """

    fn: Callable
    name: str = "program"
    # Donate the state buffers to the compiled step (train programs should
    # set True for in-place param updates; False is the safe default so an
    # inference program can be called repeatedly with the same params).
    donate_state: bool = False
    # Sharding: feed arrays get batch sharding over (dp, fsdp) unless listed
    # in `replicated_feeds`.
    replicated_feeds: Sequence[str] = ()
    # Placement for the state argument under a mesh: a pytree of
    # PartitionSpecs or NamedShardings matching the state (e.g. from
    # ShardingPlan.state_specs). Part of the Program — the reference's
    # ProgramDesc likewise carries placement — so Executor.run uses it
    # without extra plumbing.
    state_shardings: Any = None

    def compile(self, mesh: Optional[Mesh] = None,
                state_shardings: Any = None) -> "CompiledProgram":
        if state_shardings is None:
            state_shardings = self.state_shardings
        return CompiledProgram(self, mesh, state_shardings)


class CompiledProgram:
    """jit/pjit-compiled program bound to a mesh (CompiledProgram parity)."""

    def __init__(self, program: Program, mesh: Optional[Mesh] = None,
                 state_shardings: Any = None):
        self.program = program
        self.mesh = mesh
        self._batch_sharding = (mesh_lib.batch_sharding(mesh)
                                if mesh is not None else None)
        self._replicated = (mesh_lib.replicated(mesh)
                            if mesh is not None else None)
        donate = (0,) if program.donate_state else ()
        self.state_shardings = None
        if mesh is not None and state_shardings is not None:
            # accept PartitionSpec leaves and bind them to the mesh
            self.state_shardings = jax.tree_util.tree_map(
                lambda s: (NamedSharding(mesh, s)
                           if isinstance(s, P) else s),
                state_shardings,
                is_leaf=lambda x: isinstance(x, (P, NamedSharding)))
        elif mesh is not None and mesh.size > 1:
            import warnings
            warnings.warn(
                f"Program '{program.name}' compiled for a {mesh.size}-"
                "device mesh WITHOUT state_shardings: the state will be "
                "fully replicated on every device. Pass "
                "Program(state_shardings=...) (e.g. from "
                "ShardingPlan.state_specs) to shard it.",
                stacklevel=3)
        self._fn = jax.jit(program.fn, donate_argnums=donate)

    def __call__(self, state, **feeds):
        if self.mesh is not None:
            feeds = {
                k: jax.device_put(
                    v, self._replicated
                    if k in self.program.replicated_feeds
                    else self._batch_sharding)
                for k, v in feeds.items()
            }
            if self.state_shardings is not None and state is not None:
                # committed placement drives GSPMD; a no-op when the state
                # already sits on these shardings (the steady-state train
                # loop: donated outputs come back correctly placed)
                state = jax.device_put(state, self.state_shardings)
        return self._fn(state, **feeds)


def _dataset_batches(dataset, batch_size, feed_builder, drop_last=False):
    """Iterate batches from either a native MultiSlotDataset (its
    ``batches`` stream) or a python reader creator (callable yielding
    samples, batched here). Reader creators REQUIRE ``feed_builder`` —
    the Executor feeds keyword dicts, not raw sample lists."""
    if hasattr(dataset, "batches"):
        yield from dataset.batches(batch_size, drop_last=drop_last)
        return
    if feed_builder is None:
        raise ValueError(
            "reader-creator datasets need feed_builder(samples) -> feed "
            "dict (native MultiSlotDataset batches pass through as-is)")
    buf = []
    for sample in dataset():
        buf.append(sample)
        if len(buf) == batch_size:
            yield feed_builder(buf)
            buf = []
    if buf and not drop_last:
        yield feed_builder(buf)      # trailing partial batch is NOT lost


class Executor:
    """Feed/fetch runner (fluid Executor parity: run(program, feed, fetch)).

    ``place`` is kept for API familiarity but is advisory — placement is the
    mesh's job.
    """

    def __init__(self, place=None, mesh: Optional[Mesh] = None,
                 lint: str = "off",
                 lint_cost: Optional[Dict[str, Any]] = None):
        self.place = place
        self.mesh = mesh
        self.lint = lint
        # dict of lint_fn cost options (hbm_budget_bytes,
        # collective_allowlist, ...) adding the HLO tier to the gate
        self.lint_cost = lint_cost
        self._cache: Dict[int, tuple] = {}
        self._linted: set = set()

    def run(self, program, state=None, feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence[str]] = None, return_numpy=True):
        """Run one step. ``fetch_list`` selects keys out of a dict result
        (fluid fetch parity); None returns everything."""
        feed = feed or {}
        reg = observability.default()
        if isinstance(program, Program):
            # Keyed by id but the cache holds a strong ref to the Program, so
            # an address can't be recycled while its entry is alive.
            key = id(program)
            if key not in self._cache:
                self._cache[key] = (program, program.compile(self.mesh))
                reg.counter("executor_program_compiles_total",
                            "Program cache misses (new jit wrappers)"
                            ).inc(name=program.name)
            cached_prog, compiled = self._cache[key]
            assert cached_prog is program
            if self.lint != "off" and key not in self._linted \
                    and state is not None:
                # compile-time hook: lint once per Program, against the
                # first run's avals (abstract tracing, nothing executes).
                # Marked linted only AFTER enforcement: a caught LintError
                # must not disarm the gate for the next run.
                self._lint(program, state, feed)
                self._linted.add(key)
        else:
            compiled = program
        t0 = time.perf_counter()
        out = compiled(state, **feed)
        reg.counter("executor_run_calls_total").inc()
        reg.histogram("executor_run_seconds",
                      "Executor.run dispatch wall time").observe(
                          time.perf_counter() - t0)
        if isinstance(out, tuple) and len(out) == 2:
            state, fetches = out
        else:
            fetches = out
        if fetch_list and isinstance(fetches, dict):
            fetches = {k: fetches[k] for k in fetch_list}
        if return_numpy:
            fetches = jax.tree_util.tree_map(np.asarray, jax.device_get(fetches))
        return state, fetches

    def _lint(self, program: Program, state, feed):
        """Static analysis of ``program.fn`` (``paddle_tpu.analysis``)
        before its first dispatch; ``lint='warn'`` warns, ``'error'``
        raises :class:`~paddle_tpu.analysis.LintError` on error-severity
        findings. Donation flags come from ``program.donate_state``."""
        from paddle_tpu import analysis
        cost_kw = dict(self.lint_cost, cost=True) \
            if self.lint_cost is not None else {}
        report = analysis.lint_train_step(
            program.fn, state, feed, name=program.name,
            donate_argnums=(0,) if program.donate_state else (),
            **cost_kw)
        analysis.enforce(report, self.lint)

    def train_from_dataset(self, program, dataset, state, *,
                           batch_size=64, epochs=1, feed_builder=None,
                           fetch_handler=None, run_log=None,
                           checkpoint_dir=None, checkpoint_every=0,
                           resume=False, preemption_guard=None):
        """Dataset-path training (fluid executor.py:1101
        ``train_from_dataset`` → ``Executor::RunFromDataset``,
        executor.cc:168): run ``program`` over every batch of ``dataset``
        for ``epochs``. The reference spawns device-worker threads pulling
        parsed records from the DataFeed channel; here the native feed (or
        a reader creator) streams host batches into one jitted program —
        XLA owns the device parallelism. ``feed_builder(samples) -> feed``
        adapts raw reader samples; ``fetch_handler(step, fetches)``
        observes results (PrintFetchVars parity). ``run_log=`` writes one
        JSONL telemetry record per step (observability.runlog schema).

        Resilience: ``checkpoint_dir`` snapshots ``state`` every
        ``checkpoint_every`` dataset steps through the sharded snapshot
        engine; ``resume=True`` restores the newest valid snapshot and
        fast-forwards the (deterministic) dataset stream to the saved
        step. ``preemption_guard`` drains the in-flight step on SIGTERM,
        snapshots, and exits ``resilience.EXIT_PREEMPTED``.
        Returns (state, last fetches)."""
        from paddle_tpu import io as io_lib

        fetches = None
        step_i = 0
        start_step = 0
        mgr = None
        if checkpoint_dir is not None:
            mgr = io_lib.CheckpointManager(
                checkpoint_dir, save_interval_steps=max(1, checkpoint_every))
            if resume:
                # ONE verified scan decides the resume point; restore by
                # explicit step then re-checks only that snapshot
                manifest = mgr.latest_valid_manifest()
                if manifest is not None:
                    start_step = int(manifest["step"])
                    state = mgr.restore(start_step,
                                        target=jax.device_get(state))
        tel = observability.StepTelemetry(
            "executor_dataset", run_log=run_log,
            run_meta={"batch_size": batch_size, "epochs": epochs})
        try:
            for epoch in range(epochs):
                # training drops the ragged tail (a different batch shape
                # would trigger a recompile for one step per epoch)
                it = iter(_dataset_batches(dataset, batch_size,
                                           feed_builder, drop_last=True))
                while True:
                    t_fetch = time.perf_counter()
                    try:
                        batch = next(it)
                    except StopIteration:
                        break
                    if step_i < start_step:
                        step_i += 1   # fast-forward an already-done step
                        continue
                    tel.data_wait(time.perf_counter() - t_fetch)
                    t_step = time.perf_counter()
                    state, fetches = self.run(program, state, feed=batch,
                                              return_numpy=False)
                    step_i += 1
                    tel.step(step_i, feeds=batch,
                             step_time_s=time.perf_counter() - t_step,
                             examples=batch_size, epoch=epoch)
                    if fetch_handler is not None:
                        fetch_handler(step_i - 1, fetches)
                    if mgr is not None and checkpoint_every \
                            and step_i % checkpoint_every == 0:
                        mgr.save(step_i, jax.device_get(state))
                    if preemption_guard is not None \
                            and preemption_guard.triggered:
                        if mgr is not None:
                            mgr.save(step_i, jax.device_get(state),
                                     wait=True, force=True)
                        preemption_guard.exit()
        finally:
            tel.close()
            if mgr is not None:
                mgr.wait()
        return state, fetches

    def infer_from_dataset(self, program, dataset, state, *,
                           batch_size=64, feed_builder=None):
        """Forward-only dataset pass (fluid infer_from_dataset parity):
        collects per-batch fetches into a list."""
        outs = []
        for batch in _dataset_batches(dataset, batch_size, feed_builder):
            _, fetches = self.run(program, state, feed=batch,
                                  return_numpy=True)
            outs.append(fetches)
        return outs

    def close(self):
        self._cache.clear()

