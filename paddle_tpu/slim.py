"""Model compression (slim): pruning + distillation.

Reference mapping: ``python/paddle/fluid/contrib/slim/`` —
- ``prune`` (SensitivePruneStrategy / magnitude pruning of conv/fc
  weights): here masks are a PYTREE the train step re-applies after each
  optimizer update, so pruned training is one functional transform (no
  graph surgery); sensitivity analysis sweeps per-layer sparsities.
- ``distillation`` (soft-label loss, FSP matrix loss): pure loss-term
  helpers combined into the student's loss function.
- quantization lives in ``ops/quant.py`` (fake-quant + STE).

TPU notes: masks are multiplicative 0/1 arrays — XLA fuses the multiply
into the producer; on MXU-sized blocks magnitude pruning keeps dense
matmul shapes (structured sparsity in hardware is out of scope for v5e).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


# ---------------------------------------------------------------------------
# pruning
# ---------------------------------------------------------------------------

def _prunable(path: Tuple[str, ...], leaf) -> bool:
    """Weight matrices/filters only — never biases, norms, or embeddings'
    1-D state (slim prunes conv/fc weights)."""
    name = path[-1] if path else ""
    return getattr(leaf, "ndim", 0) >= 2 and name in ("weight", "w")


def magnitude_prune_masks(params, sparsity: float, *,
                          predicate: Optional[Callable] = None):
    """Per-layer magnitude masks: zero the smallest-|w| ``sparsity``
    fraction of each prunable leaf (SensitivePruneStrategy's ratio
    pruning). Returns a 0/1 mask pytree matching ``params``."""
    if not 0.0 <= sparsity < 1.0:
        raise ValueError(f"sparsity must be in [0, 1); got {sparsity}")
    predicate = predicate or _prunable

    def walk(tree, path):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if predicate(path, tree) and sparsity > 0.0:
            k = int(round(tree.size * sparsity))
            if k == 0:
                return jnp.ones_like(tree)
            flat = jnp.abs(tree).ravel()
            thresh = jnp.sort(flat)[k - 1]
            return (jnp.abs(tree) > thresh).astype(tree.dtype)
        return jnp.ones_like(tree) if hasattr(tree, "shape") else tree

    return walk(params, ())


def apply_masks(params, masks):
    return jax.tree_util.tree_map(lambda p, m: p * m, params, masks)


def sparsity_of(masks) -> float:
    """Achieved global sparsity over the masked leaves."""
    zeros = total = 0
    for m in jax.tree_util.tree_leaves(masks):
        zeros += int(m.size) - int(jnp.count_nonzero(m))
        total += int(m.size)
    return zeros / max(total, 1)


def pruned_train_step(step: Callable, masks) -> Callable:
    """Wrap a train step so the masks are re-applied after every update
    (pruned weights stay zero through optimizer momentum/adam states —
    the retrain phase of slim's prune strategy)."""

    def wrapped(state, **batch):
        state, metrics = step(state, **batch)
        state = dict(state,
                     params=apply_masks(state["params"], masks))
        return state, metrics

    return wrapped


def sensitivity_analysis(loss_fn: Callable, params, *,
                         sparsities: Sequence[float] = (0.3, 0.5, 0.7),
                         predicate: Optional[Callable] = None
                         ) -> Dict[Tuple[str, ...], Dict[float, float]]:
    """Per-layer sensitivity sweep (slim sensitive.py): prune ONE layer at
    a time to each ratio and record the loss. Returns
    {layer_path: {sparsity: loss}} — pick per-layer ratios by loss budget."""
    predicate = predicate or _prunable
    base = float(loss_fn(params))

    paths = [p for p, leaf in _iter_leaves(params, ())
             if predicate(p, leaf)]
    out: Dict[Tuple[str, ...], Dict[float, float]] = {}
    for path in paths:
        out[path] = {0.0: base}
        for s in sparsities:
            only_this = (lambda p, leaf, target=path:
                         p == target)
            masks = magnitude_prune_masks(params, s, predicate=only_this)
            out[path][s] = float(loss_fn(apply_masks(params, masks)))
    return out


def _iter_leaves(tree, path):
    if isinstance(tree, dict):
        for k, v in tree.items():
            yield from _iter_leaves(v, path + (k,))
    else:
        yield path, tree


# ---------------------------------------------------------------------------
# distillation
# ---------------------------------------------------------------------------

def soft_label_loss(student_logits, teacher_logits, *,
                    temperature: float = 1.0):
    """KD soft-target cross-entropy (slim distillation_strategy soft-label
    loss): KL(teacher_T || student_T) * T^2, mean over batch."""
    t = temperature
    sp = jax.nn.log_softmax(student_logits.astype(jnp.float32) / t, -1)
    tp = jax.nn.softmax(teacher_logits.astype(jnp.float32) / t, -1)
    tlogp = jax.nn.log_softmax(teacher_logits.astype(jnp.float32) / t, -1)
    kl = (tp * (tlogp - sp)).sum(-1)
    return kl.mean() * (t * t)


def fsp_matrix(a, b):
    """Flow-of-solution-procedure matrix (slim FSP distillation): feature
    maps a (B, H, W, Ca), b (B, H, W, Cb) -> (B, Ca, Cb) Gram flow."""
    ba, h, w, ca = a.shape
    bb, h2, w2, cb = b.shape
    if (ba, h, w) != (bb, h2, w2):
        raise ValueError(f"FSP needs matching spatial dims; {a.shape} vs "
                         f"{b.shape}")
    af = a.reshape(ba, h * w, ca)
    bf = b.reshape(bb, h * w, cb)
    return jnp.einsum("bnc,bnd->bcd", af, bf) / (h * w)


def fsp_loss(student_pairs, teacher_pairs):
    """Mean L2 between student/teacher FSP matrices over given feature
    pairs: [((a_s, b_s), (a_t, b_t)), ...]."""
    losses = []
    for (a_s, b_s), (a_t, b_t) in zip(student_pairs, teacher_pairs):
        fs = fsp_matrix(a_s, b_s)
        ft = fsp_matrix(a_t, b_t)
        losses.append(((fs - ft) ** 2).mean())
    return jnp.stack(losses).mean()


# ---------------------------------------------------------------------------
# post-training quantization (weight-only int8)
# ---------------------------------------------------------------------------
#
# slim's quant story has two halves: quant-aware training (fake-quant +
# STE, ops/quant.py) and post-training quantization of a trained model.
# This is the PTQ half for serving: weights stored int8 + per-channel
# scales (4x smaller artifacts, HBM-bandwidth relief), dequantized to the
# compute dtype at load/use — the WeightQuantization path of
# contrib/slim's quantization_pass.

def quantize_weights_int8(params, *, predicate: Optional[Callable] = None,
                          per_channel: bool = True):
    """Symmetric int8 weight quantization. Returns a pytree where each
    quantized leaf becomes {"q": int8, "scale": f32, "axis": int}; other
    leaves pass through. ``per_channel``: scale per output channel (last
    dim) — the accuracy-preserving default."""
    predicate = predicate or _prunable

    def walk(tree, path):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if predicate(path, tree):
            w = jnp.asarray(tree)
            if per_channel:
                amax = jnp.max(jnp.abs(w), axis=tuple(range(w.ndim - 1)),
                               keepdims=True)
            else:
                amax = jnp.max(jnp.abs(w))
            scale = jnp.maximum(amax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
            return {"q": q, "scale": scale.astype(jnp.float32),
                    "axis": -1 if per_channel else None}
        return tree

    return walk(params, ())


def _is_qleaf(node) -> bool:
    return isinstance(node, dict) and set(node) == {"q", "scale", "axis"}


def int8_resident(q):
    """Keep an int8 array int8 through XLA's constant folding.

    THE keep-quantized idiom, in one place: when int8 data is baked as a
    CONSTANT into a jitted graph (frozen weights in a native serving
    artifact, a captured KV page pool), XLA constant-folds the in-graph
    ``q * scale`` dequant into a full-width float constant at compile
    time — silently quadrupling the executable's memory and voiding the
    int8-residency claim. Wrapping the int8 leaf in
    ``lax.optimization_barrier`` before the dequant pins it: the barrier
    survives jit, so the s8 constant stays s8 in the optimized HLO and
    dequantization happens at run time, on-chip. Arguments (the
    Predictor path, the serving engine's donated pages) stay int8 either
    way — arguments cannot be folded — so the wrap is harmless there.
    Users: :func:`dequantize_weights(keep_int8_resident=True)` and the
    int8 paged KV cache's dequant-attend fallback
    (:mod:`paddle_tpu.serving.decode_attention`)."""
    return jax.lax.optimization_barrier(q)


def dequantize_weights(qparams, dtype=jnp.float32, *,
                       keep_int8_resident: bool = False):
    """Inverse of :func:`quantize_weights_int8`: rebuild a dense param
    pytree in ``dtype`` (serve-time load path).

    ``keep_int8_resident``: wrap each int8 leaf in
    ``lax.optimization_barrier`` before the in-graph dequant. Without
    it, when the int8 weights are BAKED AS CONSTANTS (the frozen native
    serving artifact), XLA constant-folds q*scale into a full-width
    float constant at compile time — silently quadrupling the
    executable's weight memory and voiding the int8 residency claim
    (verified on the CPU backend: the s8 constant disappears from the
    optimized HLO without the barrier). Weights passed as *arguments*
    (the Predictor path) stay int8 either way — arguments cannot be
    folded."""

    def walk(node):
        if _is_qleaf(node):
            q = node["q"]
            if keep_int8_resident:
                q = int8_resident(q)
            return (q.astype(jnp.float32)
                    * node["scale"]).astype(dtype)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        return node

    return walk(qparams)


def quantization_error(params, qparams) -> Dict[Tuple[str, ...], float]:
    """Per-quantized-leaf relative L2 error — the accuracy-budget
    diagnostic before shipping a quantized artifact."""
    deq = dequantize_weights(qparams)
    out = {}

    def walk(a, b, q, path):
        if isinstance(a, dict):
            for k in a:
                walk(a[k], b[k], q[k], path + (k,))
        elif _is_qleaf(q):
            num = float(jnp.linalg.norm((a - b).ravel()))
            den = float(jnp.linalg.norm(jnp.asarray(a).ravel())) or 1.0
            out[path] = num / den

    walk(params, deq, qparams, ())
    return out


# ---------------------------------------------------------------------------
# NAS (light): simulated-annealing architecture search
# ---------------------------------------------------------------------------

def sa_search(space: Dict[str, Sequence], eval_fn: Callable[[dict], float],
              *, iters: int = 50, init_temp: float = 1.0,
              cooling: float = 0.95, seed: int = 0,
              init: Optional[dict] = None):
    """Simulated-annealing search over a discrete config space (slim
    light_nas ``sa_controller`` analog: mutate one knob per step, accept
    worse candidates with exp(-delta/T), anneal T).

    ``space``: {knob: [choices...]}; ``eval_fn(config) -> float`` is the
    reward to MAXIMIZE (e.g. -latency-penalized eval loss). Returns
    (best_config, best_reward, history).
    """
    import numpy as _np

    rng = _np.random.default_rng(seed)
    keys = sorted(space)
    cur = dict(init) if init is not None else \
        {k: space[k][int(rng.integers(len(space[k])))] for k in keys}
    for k in keys:
        if cur[k] not in list(space[k]):
            raise ValueError(f"init[{k!r}]={cur[k]!r} not in space")
    cur_r = float(eval_fn(cur))
    best, best_r = dict(cur), cur_r
    temp = init_temp
    history = [(dict(cur), cur_r)]
    # only knobs with >1 choice can move; single-choice knobs would waste
    # a full eval per no-op mutation (eval_fn is a training run in NAS)
    mutable = [k for k in keys if len(space[k]) > 1]
    if not mutable:
        return best, best_r, history
    for _ in range(iters):
        cand = dict(cur)
        k = mutable[int(rng.integers(len(mutable)))]
        choices = [c for c in space[k] if c != cand[k]]
        cand[k] = choices[int(rng.integers(len(choices)))]
        r = float(eval_fn(cand))
        if r >= cur_r or rng.random() < _np.exp((r - cur_r)
                                                / max(temp, 1e-8)):
            cur, cur_r = cand, r
        if cur_r > best_r:
            best, best_r = dict(cur), cur_r
        history.append((dict(cand), r))
        temp *= cooling
    return best, best_r, history


def distill_loss_fn(student_loss_fn: Callable, teacher_fn: Callable, *,
                    alpha: float = 0.5, temperature: float = 2.0
                    ) -> Callable:
    """Combine hard-label student loss with the KD term:
        loss = (1-alpha) * student_loss + alpha * KD(student, teacher)

    ``student_loss_fn(params, **batch) -> (loss, {"logits": ...})`` must
    expose logits in its aux; ``teacher_fn(**batch) -> logits`` runs the
    (frozen) teacher — close over its params and stop_gradient them.
    """

    def loss(params, **batch):
        hard, aux = student_loss_fn(params, **batch)
        teacher_logits = jax.lax.stop_gradient(teacher_fn(**batch))
        kd = soft_label_loss(aux["logits"], teacher_logits,
                             temperature=temperature)
        total = (1 - alpha) * hard + alpha * kd
        return total, dict(aux, hard_loss=hard, kd_loss=kd)

    return loss


# ---------------------------------------------------------------------------
# Quantization-aware training (contrib/slim/quantization
# QuantizationTransformPass parity). The reference rewrites the program
# graph inserting fake_quantize/dequantize ops before quantizable ops; here
# the analogous transform wraps the loss function: weights are fake-quantized
# (STE gradients, ops/quant.py) on the way into the forward pass, so
# training observes int8 rounding while optimizer state stays fp32.
# ---------------------------------------------------------------------------


def _fake_quant_params(params, *, bit_length: int,
                       predicate: Optional[Callable],
                       channel_wise: bool):
    """Shared walk: fake-quantize quantizable leaves (STE grads)."""
    from paddle_tpu.ops import quant as Q

    pred = predicate or _prunable

    def walk(tree, path=()):
        if isinstance(tree, dict):
            return {k: walk(v, path + (k,)) for k, v in tree.items()}
        if not pred(path, tree):
            return tree
        if channel_wise and tree.ndim >= 2:
            return Q.fake_channel_wise_quantize_abs_max(
                tree, bit_length=bit_length)[0]
        return Q.fake_quantize_abs_max(tree, bit_length=bit_length)[0]

    return walk(params)


def qat_transform(loss_fn: Callable, *, bit_length: int = 8,
                  predicate: Optional[Callable] = None,
                  channel_wise: bool = False) -> Callable:
    """Wrap ``loss_fn(params, **batch)`` so quantizable weights pass
    through fake-quant (abs-max, STE) first. ``predicate(path, leaf)``
    selects leaves (default: the same >=2-D weight rule as pruning)."""

    @functools.wraps(loss_fn)
    def wrapped(params, *args, **kwargs):
        return loss_fn(
            _fake_quant_params(params, bit_length=bit_length,
                               predicate=predicate,
                               channel_wise=channel_wise),
            *args, **kwargs)

    return wrapped


def qat_convert(params, *, bit_length: int = 8,
                predicate: Optional[Callable] = None,
                channel_wise: bool = False):
    """Freeze QAT training into deployment weights
    (QuantizationFreezePass parity): snap quantizable leaves to the SAME
    fake-quant grid training observed — pass the ``channel_wise`` used in
    :func:`qat_transform`."""
    return _fake_quant_params(params, bit_length=bit_length,
                              predicate=predicate,
                              channel_wise=channel_wise)
