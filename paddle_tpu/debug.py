"""Numeric debugging: NaN/Inf checks, determinism knobs.

Reference mapping (SURVEY.md §5.2): ``FLAGS_check_nan_inf`` validates every
op output (operator.cc:35,840), ``FLAGS_fast_check_nan_inf`` (operator.cc:37)
is the cheap variant, ``FLAGS_cpu_deterministic``/``cudnn_deterministic``
pin reductions. TPU-native:
- :func:`enable_nan_checks` → ``jax.debug_nans`` (XLA re-runs the failing
  computation op-by-op and points at the op — better than the reference's
  per-op scan, same contract).
- :func:`check_numerics` → explicit in-graph assertion via checkify for
  always-on production guards (fast_check_nan_inf analog).
- determinism: XLA on TPU is deterministic by construction; dropout keys
  are explicit, so there is no cudnn_deterministic analog needed.
"""

from __future__ import annotations

import contextlib
from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import checkify


def enable_nan_checks(enable: bool = True):
    """Global NaN trap (FLAGS_check_nan_inf parity). Mutates global jax
    config with no memory of the prior value — prefer the
    :func:`nan_checks` context manager for scoped use."""
    jax.config.update("jax_debug_nans", enable)


@contextlib.contextmanager
def nan_checks(enable: bool = True):
    """Scoped NaN trap: enables (or disables) ``jax_debug_nans`` for the
    block and restores the PRIOR value on exit — nests correctly, unlike
    :func:`enable_nan_checks` which leaves the flag flipped::

        with debug.nan_checks():
            loss = step(state, **batch)   # raises on NaN/Inf outputs
    """
    prev = jax.config.jax_debug_nans
    jax.config.update("jax_debug_nans", enable)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", prev)


def check_numerics(tree: Any, label: str = "tensor") -> Any:
    """In-graph guard: error (under checkify) if any leaf has NaN/Inf.
    Returns the tree unchanged, so it can be inserted mid-computation."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            name = label + jax.tree_util.keystr(path)
            checkify.check(jnp.all(jnp.isfinite(leaf)),
                           "non-finite values in {}".format(name))
    return tree


def checked(fn):
    """Wrap a jittable fn so checkify.check assertions become returned
    errors: ``err, out = checked(step)(...)``; ``err.throw()`` raises."""
    return checkify.checkify(fn)


def finite_or_zero(x):
    """Scrub non-finite values (grad-scrubbing util for AMP overflow
    handling — the reference's loss-scaling path skips steps instead)."""
    return jnp.where(jnp.isfinite(x), x, 0.0)


def print_program(fn, *example_args, stage="jaxpr", **example_kwargs):
    """Program pretty-printer (``debugger.py`` ``draw_block_graphviz`` /
    program printer parity). The "Program IR" of this framework is the
    traced computation: ``stage="jaxpr"`` prints the closed jaxpr (op-level
    view ≙ ProgramDesc blocks/ops), ``stage="hlo"`` the optimized-ready
    StableHLO text XLA compiles (graph-IR view ≙ ir::Graph dumps).
    Returns the string (and prints it)."""
    import jax

    if stage == "jaxpr":
        text = str(jax.make_jaxpr(fn)(*example_args, **example_kwargs))
    elif stage == "hlo":
        text = jax.jit(fn).lower(
            *example_args, **example_kwargs).as_text()
    else:
        raise ValueError(f"stage must be 'jaxpr' or 'hlo', got {stage!r}")
    print(text)
    return text


def program_to_dot(fn, *example_args, max_nodes=200, **example_kwargs):
    """Graphviz dot of the traced program (``net_drawer.py`` /
    ``graph_viz_pass.cc`` parity): one node per jaxpr equation, edges along
    var def->use. Returns the dot source string."""
    import jax

    jaxpr = jax.make_jaxpr(fn)(*example_args, **example_kwargs).jaxpr
    lines = ["digraph program {", "  rankdir=TB;",
             "  node [shape=box, fontsize=10];"]
    producers = {}
    for i, eqn in enumerate(jaxpr.eqns[:max_nodes]):
        label = eqn.primitive.name
        lines.append(f'  op{i} [label="{label}"];')
        for v in eqn.outvars:
            producers[str(v)] = i
    for i, eqn in enumerate(jaxpr.eqns[:max_nodes]):
        for v in eqn.invars:
            src = producers.get(str(v))
            if src is not None and src != i:
                lines.append(f"  op{src} -> op{i};")
    if len(jaxpr.eqns) > max_nodes:
        lines.append(f'  trunc [label="... {len(jaxpr.eqns) - max_nodes} '
                     f'more ops", style=dashed];')
    lines.append("}")
    return "\n".join(lines)


def op_frequency(fn, *example_args, **example_kwargs):
    """Count primitive frequencies in a traced program
    (``contrib/op_frequence.py`` parity): {primitive_name: count},
    sorted dict by descending count."""
    import collections
    import jax

    jaxpr = jax.make_jaxpr(fn)(*example_args, **example_kwargs).jaxpr
    counts = collections.Counter()

    def walk(jx):
        for eqn in jx.eqns:
            counts[eqn.primitive.name] += 1
            for v in eqn.params.values():
                # nested programs hide in single params (scan's "jaxpr")
                # AND in tuples of them (cond's "branches")
                for sub in (v if isinstance(v, (tuple, list)) else (v,)):
                    if hasattr(sub, "jaxpr"):
                        walk(sub.jaxpr)
        return counts

    walk(jaxpr)
    return dict(sorted(counts.items(), key=lambda kv: -kv[1]))


def estimate_memory(fn, *example_args, **example_kwargs):
    """Peak-memory / traffic estimate for a jitted function
    (``contrib/memory_usage_calc.py`` parity, but from the compiler
    itself): returns {"argument_bytes", "output_bytes",
    "temp_bytes", "generated_code_bytes", "total_bytes"} from XLA's
    compiled memory analysis — the authoritative number, not a
    shape-walk approximation."""
    import jax

    compiled = jax.jit(fn).lower(*example_args, **example_kwargs).compile()
    m = compiled.memory_analysis()
    if m is None:                                  # backend w/o analysis
        return None
    out = {
        "argument_bytes": int(getattr(m, "argument_size_in_bytes", 0)),
        "output_bytes": int(getattr(m, "output_size_in_bytes", 0)),
        "temp_bytes": int(getattr(m, "temp_size_in_bytes", 0)),
        "generated_code_bytes": int(
            getattr(m, "generated_code_size_in_bytes", 0)),
    }
    out["total_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                          + out["temp_bytes"])
    return out
