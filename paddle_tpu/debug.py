"""Numeric debugging: NaN/Inf checks, determinism knobs.

Reference mapping (SURVEY.md §5.2): ``FLAGS_check_nan_inf`` validates every
op output (operator.cc:35,840), ``FLAGS_fast_check_nan_inf`` (operator.cc:37)
is the cheap variant, ``FLAGS_cpu_deterministic``/``cudnn_deterministic``
pin reductions. TPU-native:
- :func:`enable_nan_checks` → ``jax.debug_nans`` (XLA re-runs the failing
  computation op-by-op and points at the op — better than the reference's
  per-op scan, same contract).
- :func:`check_numerics` → explicit in-graph assertion via checkify for
  always-on production guards (fast_check_nan_inf analog).
- determinism: XLA on TPU is deterministic by construction; dropout keys
  are explicit, so there is no cudnn_deterministic analog needed.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.experimental import checkify


def enable_nan_checks(enable: bool = True):
    """Global NaN trap (FLAGS_check_nan_inf parity)."""
    jax.config.update("jax_debug_nans", enable)


def check_numerics(tree: Any, label: str = "tensor") -> Any:
    """In-graph guard: error (under checkify) if any leaf has NaN/Inf.
    Returns the tree unchanged, so it can be inserted mid-computation."""
    for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype,
                                                     jnp.floating):
            name = label + jax.tree_util.keystr(path)
            checkify.check(jnp.all(jnp.isfinite(leaf)),
                           "non-finite values in {}".format(name))
    return tree


def checked(fn):
    """Wrap a jittable fn so checkify.check assertions become returned
    errors: ``err, out = checked(step)(...)``; ``err.throw()`` raises."""
    return checkify.checkify(fn)


def finite_or_zero(x):
    """Scrub non-finite values (grad-scrubbing util for AMP overflow
    handling — the reference's loss-scaling path skips steps instead)."""
    return jnp.where(jnp.isfinite(x), x, 0.0)
