"""Reusable beam-search ops (``beam_search_op`` / ``beam_search_decode_op``).

Reference: ``paddle/fluid/operators/beam_search_op.cc`` (one expansion step:
pre_ids/pre_scores -> selected_ids/selected_scores/parent_idx, grouped per
source sentence) and ``beam_search_decode_op.cc`` (walk the parent pointers
of every step's selections back into full sentences + scores).

TPU-native: static shapes throughout. Beams live on a dense ``(B, K)``
lattice (the batch dimension replaces the reference's LoD beam segments —
segment-aware grouping = the leading axis), finished beams are masked
rather than pruned, and the per-step op composes with ``lax.scan``/
``fori_loop`` so whole decodes stay inside one XLA program. Backtracking
in :func:`beam_search_decode` is a reverse ``lax.scan`` over parent
pointers instead of the reference's host-side sentence walk.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op

NEG_INF = -1e9

__all__ = ["beam_search_step", "beam_search_decode", "beam_init",
           "gather_beams", "NEG_INF"]


def beam_init(batch: int, beam_size: int, dtype=jnp.float32):
    """Initial ``(scores, done)`` lattice: beam 0 live at score 0, beams
    1..K-1 at -inf so the first step fans out from a single hypothesis
    (reference ``beam_search_op``'s first-step LoD of one candidate)."""
    scores = jnp.tile(
        jnp.array([0.0] + [NEG_INF] * (beam_size - 1), dtype), (batch, 1))
    done = jnp.zeros((batch, beam_size), bool)
    return scores, done


@register_op("beam_search", has_grad=False)
def beam_search_step(logp, scores, done, *, eos_id: int, pad_id: int = 0,
                     beam_size: Optional[int] = None):
    """One beam expansion (reference ``beam_search_op.cc``).

    Args:
      logp: (B, K, V) next-token log-probabilities per live beam.
      scores: (B, K) cumulative log-probs (``pre_scores``).
      done: (B, K) bool — beams that already emitted EOS.
      eos_id / pad_id: termination token / filler for finished beams.
      beam_size: output beams per group; defaults to K (shrinking mid-
        decode is allowed, growing is not).

    Returns ``(tokens, new_scores, new_done, parent_idx)``, each (B, K'):
    the chosen continuation tokens, their cumulative scores, finished
    flags, and the index of the beam each selection extends — the
    reference op's ``selected_ids``/``selected_scores``/``parent_idx``.
    Finished beams only ever continue with ``pad_id`` at unchanged score.
    """
    b, k, v = logp.shape
    # `or` would silently treat an explicit beam_size=0 as "default to k"
    k_out = k if beam_size is None else beam_size
    if k_out < 1:
        raise ValueError(f"beam_size must be >= 1, got {k_out}")
    if k_out > k:
        raise ValueError(f"cannot grow beams: {k_out} > {k}")
    logp = logp.astype(jnp.float32)
    # a finished beam contributes exactly one candidate: PAD, score += 0
    pad_only = jnp.full((v,), NEG_INF, logp.dtype).at[pad_id].set(0.0)
    logp = jnp.where(done[..., None], pad_only[None, None, :], logp)
    cand = scores[..., None] + logp                       # (B, K, V)
    new_scores, idx = jax.lax.top_k(cand.reshape(b, k * v), k_out)
    parent = idx // v
    tok = (idx % v).astype(jnp.int32)
    new_done = jnp.take_along_axis(done, parent, axis=1) | (tok == eos_id)
    return tok, new_scores, new_done, parent


def gather_beams(tree, parent_idx):
    """Reorder per-beam state along the chosen parents: every leaf of
    ``tree`` has leading dims ``(B, K, ...)`` or flat ``(B*K, ...)`` and
    its rows follow ``parent_idx`` (B, K). The companion to the reference
    op's ``parent_idx`` output — used to carry RNN hidden state or KV
    caches along with their beams."""
    b, k = parent_idx.shape

    def g(leaf):
        flat = leaf.shape[0] == b * k
        shaped = leaf.reshape((b, k) + leaf.shape[1:]) if flat else leaf
        ix = parent_idx.reshape((b, k) + (1,) * (shaped.ndim - 2))
        shaped = jnp.take_along_axis(shaped, ix, axis=1)
        return shaped.reshape(leaf.shape) if flat else shaped

    return jax.tree_util.tree_map(g, tree)


@register_op("beam_search_decode", has_grad=False)
def beam_search_decode(step_tokens, step_parents, scores, *,
                       eos_id: int, pad_id: int = 0, bos_id: Optional[int] = None,
                       length_penalty: float = 0.0):
    """Backtrack stacked step selections into full sequences (reference
    ``beam_search_decode_op.cc``).

    Args:
      step_tokens: (B, T, K) tokens chosen at each step (the scan stack of
        :func:`beam_search_step`'s ``tokens``).
      step_parents: (B, T, K) matching ``parent_idx`` stack.
      scores: (B, K) final cumulative scores.
      bos_id: when given, sequences are prefixed with it (length T+1).
      length_penalty: GNMT alpha; 0 ranks by raw cumulative score like the
        reference op, >0 divides by ((5+len)/6)^alpha.

    Returns ``(sequences, norm_scores)``: (B, K, T[+1]) int32 sequences,
    post-EOS filled with ``pad_id``, and the (possibly length-normalized)
    scores, both sorted best-first.
    """
    b, t, k = step_tokens.shape
    toks = jnp.moveaxis(step_tokens, 1, 0)     # (T, B, K)
    pars = jnp.moveaxis(step_parents, 1, 0)

    # walk parents right-to-left: the beam that holds slot j at the end
    # occupied pars[t, :, j] at step t-1
    def back(ptr, inp):
        tok_t, par_t = inp
        tok = jnp.take_along_axis(tok_t, ptr, axis=1)      # (B, K)
        ptr = jnp.take_along_axis(par_t, ptr, axis=1)
        return ptr, tok

    ptr0 = jnp.tile(jnp.arange(k)[None, :], (b, 1))
    _, rev = jax.lax.scan(back, ptr0, (toks[::-1], pars[::-1]))
    seqs = jnp.moveaxis(rev[::-1], 0, 1)                   # (B, T, K)
    seqs = jnp.moveaxis(seqs, 2, 1).astype(jnp.int32)      # (B, K, T)

    # mask everything after the first EOS to pad (keep the EOS itself)
    is_eos = seqs == eos_id
    after = jnp.cumsum(jnp.cumsum(is_eos, axis=-1), axis=-1) > 1
    seqs = jnp.where(after, pad_id, seqs)

    if length_penalty > 0.0:
        # length = first-EOS position + 1 (cumsum of is_eos), NOT a count
        # of non-pad tokens: a legitimate mid-sequence emission of the
        # pad-VALUED token is part of the hypothesis and must count, or
        # its beam gets a smaller divisor and is misranked. No EOS -> all
        # T steps are real tokens.
        any_eos = is_eos.any(axis=-1)
        first_eos = jnp.argmax(is_eos, axis=-1)
        lengths = jnp.where(any_eos, first_eos + 1, t).astype(jnp.float32)
        if bos_id is not None:
            lengths = lengths + 1.0
        scores = scores / (((5.0 + lengths) / 6.0) ** length_penalty)

    order = jnp.argsort(-scores, axis=-1)
    scores = jnp.take_along_axis(scores, order, axis=1)
    seqs = jnp.take_along_axis(seqs, order[..., None], axis=1)
    if bos_id is not None:
        bos = jnp.full((b, k, 1), bos_id, jnp.int32)
        seqs = jnp.concatenate([bos, seqs], axis=-1)
    return seqs, scores
