"""Functional op library (the L2 operator surface, TPU-native).

Importing this package registers every op into the OpInfoMap
(paddle_tpu.core.registry), mirroring the reference's static-registrar
pattern (op_registry.h:199) without global constructors.
"""

from paddle_tpu.ops import (activation, attention, beam_search, crf,
                            detection, elementwise, math, metrics_ops,
                            niche, nn, reduction, sequence, tensor)
from paddle_tpu.ops.beam_search import (beam_init, beam_search_decode,  # noqa: F401
                                        beam_search_step, gather_beams)
from paddle_tpu.ops.attention import (dot_product_attention,  # noqa: F401
                                      flash_attention,
                                      scaled_dot_product_attention)
from paddle_tpu.ops.activation import *  # noqa: F401,F403
from paddle_tpu.ops.elementwise import add, div, max, min, mod, mul as multiply, pow as elementwise_pow, sub  # noqa: F401
from paddle_tpu.ops.math import bmm, dot, fc, matmul, mul  # noqa: F401
from paddle_tpu.ops.nn import (batch_norm, conv2d, conv2d_transpose,  # noqa: F401
                               cross_entropy, depthwise_conv2d, dropout,
                               embedding, interpolate, label_smooth,
                               layer_norm, log_softmax, one_hot, pool2d,
                               sigmoid_cross_entropy_with_logits, softmax,
                               softmax_with_cross_entropy, square_error_cost)
from paddle_tpu.ops.reduction import (logsumexp, mean, reduce_all, reduce_any,  # noqa: F401
                                      reduce_max, reduce_mean, reduce_min,
                                      reduce_prod, reduce_sum)
from paddle_tpu.ops.tensor import (accuracy, argmax, argmin, argsort, assign,  # noqa: F401
                                   cast, concat, expand, fill_constant,
                                   flatten, gather, gather_nd,
                                   reshape, scatter, slice, split, squeeze,
                                   stack, top_k, transpose, unsqueeze, where)
