"""Sequence ops over padded+lengths batches (the LoD world, TPU-native).

Reference mapping: ``operators/sequence_ops/`` (47 files — seq_pool,
seq_expand, seq_pad/unpad, seq_mask, seq_softmax, seq_concat, seq_reverse
over LoD ragged tensors, SURVEY.md §2.3). XLA needs static shapes, so the
ragged representation is (data (B, T, ...), lengths (B,)) — sequence_pad
parity is the representation itself; each op masks by lengths. Segment
variants (segment_sum style) cover the packed-sequence layout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("sequence_mask")
def sequence_mask(lengths, maxlen=None, dtype=jnp.bool_):
    """(B,) lengths -> (B, T) validity mask (sequence_mask_op)."""
    if maxlen is None:
        maxlen = int(jnp.max(lengths))  # requires concrete lengths
    pos = jnp.arange(maxlen)
    return (pos[None, :] < lengths[:, None]).astype(dtype)


@register_op("sequence_pool")
def sequence_pool(x, lengths, pool_type="sum"):
    """Pool (B, T, D) over valid positions (sequence_pool_op:
    sum/average/sqrt/max/last/first)."""
    mask = sequence_mask(lengths, x.shape[1], x.dtype)[..., None]
    if pool_type == "sum":
        return (x * mask).sum(1)
    if pool_type in ("average", "mean"):
        denom = jnp.maximum(lengths[:, None], 1).astype(x.dtype)
        return (x * mask).sum(1) / denom
    if pool_type == "sqrt":
        denom = jnp.sqrt(jnp.maximum(lengths[:, None], 1).astype(x.dtype))
        return (x * mask).sum(1) / denom
    if pool_type == "max":
        neg = jnp.finfo(x.dtype).min
        return jnp.where(mask > 0, x, neg).max(1)
    if pool_type == "last":
        idx = jnp.maximum(lengths - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None].repeat(
            x.shape[-1], -1), axis=1)[:, 0]
    if pool_type == "first":
        return x[:, 0]
    raise ValueError(f"unknown pool_type {pool_type}")


@register_op("sequence_softmax")
def sequence_softmax(x, lengths):
    """Masked softmax over the time dim (sequence_softmax_op)."""
    mask = sequence_mask(lengths, x.shape[1], jnp.bool_)
    neg = jnp.asarray(-1e30, x.dtype)
    z = jnp.where(mask, x, neg)
    p = jax.nn.softmax(z, axis=1)
    return jnp.where(mask, p, 0.0)


@register_op("sequence_reverse")
def sequence_reverse(x, lengths):
    """Reverse each row's valid prefix, keeping padding in place
    (sequence_reverse_op)."""
    t = x.shape[1]
    pos = jnp.arange(t)[None, :]
    src = jnp.where(pos < lengths[:, None], lengths[:, None] - 1 - pos, pos)
    return jnp.take_along_axis(
        x, src[..., None].repeat(x.shape[-1], -1) if x.ndim == 3 else src,
        axis=1)


@register_op("sequence_expand")
def sequence_expand(x, times):
    """Repeat each row i times[i] — static variant requires equal times
    (LoD expand is data-dependent; use repeat for the general host-side
    case). times: python int."""
    return jnp.repeat(x, times, axis=0)


@register_op("sequence_pad")
def sequence_pad(rows, maxlen, pad_value=0.0):
    """Host-side helper: list of (len_i, D) arrays -> (B, maxlen, D),
    lengths. (sequence_pad_op — here padding happens at ingest, matching
    the native feed's ragged slots.)"""
    import numpy as np

    b = len(rows)
    d = np.shape(rows[0])[-1] if np.ndim(rows[0]) > 1 else None
    shape = (b, maxlen, d) if d else (b, maxlen)
    out = np.full(shape, pad_value, dtype=np.asarray(rows[0]).dtype)
    lengths = np.zeros((b,), np.int64)
    for i, r in enumerate(rows):
        r = np.asarray(r)
        n = min(len(r), maxlen)
        out[i, :n] = r[:n]
        lengths[i] = n
    return jnp.asarray(out), jnp.asarray(lengths)


@register_op("sequence_unpad")
def sequence_unpad(x, lengths):
    """(B, T, ...) -> list of valid prefixes (host-side)."""
    import numpy as np

    xs = np.asarray(x)
    ls = np.asarray(lengths)
    return [xs[i, :ls[i]] for i in range(xs.shape[0])]


# -- packed-segment variants (sequence packing for long-context training) --

@register_op("segment_sum")
def segment_sum(data, segment_ids, num_segments):
    return jax.ops.segment_sum(data, segment_ids, num_segments)


@register_op("segment_max")
def segment_max(data, segment_ids, num_segments):
    return jax.ops.segment_max(data, segment_ids, num_segments)


def make_segment_attention_bias(segment_ids, kv_segment_ids=None,
                                dtype=jnp.float32):
    """Packed sequences: (B, Tq) segment ids -> additive (B,1,Tq,Tkv)
    bias blocking cross-segment attention (the packed-batch story for
    Transformer-big variable-length training; ≙ LoD isolation between
    sequences). Pass ``kv_segment_ids`` for cross-attention between two
    packed streams (decoder queries vs encoder keys: a pair shares its
    segment number across streams)."""
    if kv_segment_ids is None:
        kv_segment_ids = segment_ids
    same = segment_ids[:, :, None] == kv_segment_ids[:, None, :]
    return jnp.where(same, 0.0, -1e30).astype(dtype)[:, None, :, :]
