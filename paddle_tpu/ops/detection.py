"""Detection ops (CV): the reference's ``operators/detection/`` surface
(59 files, 15.4k LoC — SURVEY.md §2.3) re-emitted as jittable XLA ops.

Implemented (the load-bearing subset used by the PaddleCV detection
models): box IoU, box coding (encode/decode), prior_box (SSD anchors),
yolo_box (YOLOv3 head decode), multiclass/hard NMS (static-shape, mask
based — XLA-compatible: returns fixed-size top-k with validity mask),
roi_align. Remaining long-tail ops (matrix_nms, density_prior_box, …)
follow the same patterns.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("iou_similarity")
def box_iou(boxes1, boxes2):
    """IoU matrix: boxes (N,4),(M,4) xyxy -> (N,M)."""
    area1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    area2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                               1e-10)


@register_op("box_coder")
def box_encode(boxes, anchors, variances=(0.1, 0.1, 0.2, 0.2)):
    """encode_center_size (box_coder_op): gt xyxy vs anchor xyxy -> deltas."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    bw = boxes[:, 2] - boxes[:, 0]
    bh = boxes[:, 3] - boxes[:, 1]
    bx = boxes[:, 0] + 0.5 * bw
    by = boxes[:, 1] + 0.5 * bh
    v = jnp.asarray(variances)
    return jnp.stack([
        (bx - ax) / aw / v[0], (by - ay) / ah / v[1],
        jnp.log(jnp.maximum(bw / aw, 1e-10)) / v[2],
        jnp.log(jnp.maximum(bh / ah, 1e-10)) / v[3]], axis=-1)


def box_decode(deltas, anchors, variances=(0.1, 0.1, 0.2, 0.2)):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    v = jnp.asarray(variances)
    cx = deltas[:, 0] * v[0] * aw + ax
    cy = deltas[:, 1] * v[1] * ah + ay
    w = jnp.exp(deltas[:, 2] * v[2]) * aw
    h = jnp.exp(deltas[:, 3] * v[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)


@register_op("prior_box")
def prior_box(feature_h, feature_w, image_h, image_w, min_sizes,
              max_sizes=(), aspect_ratios=(1.0,), step=None, offset=0.5,
              clip=True):
    """SSD anchors for one feature map (prior_box_op). Returns (H*W*A, 4)
    normalized xyxy."""
    step_h = step or image_h / feature_h
    step_w = step or image_w / feature_w
    cy = (jnp.arange(feature_h) + offset) * step_h
    cx = (jnp.arange(feature_w) + offset) * step_w
    cx, cy = jnp.meshgrid(cx, cy)  # (H, W)

    # Reference default order (prior_box_op.h:139, min_max_aspect_ratios_
    # order=false): per min_size emit every aspect-ratio box (ar=1 first),
    # THEN that min_size's sqrt(min*max) box — interleaved, not appended
    # after the loop, so anchors line up with reference head channels.
    whs = []
    for i, ms in enumerate(min_sizes):
        whs.append((ms, ms))
        for ar in aspect_ratios:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        if i < len(max_sizes):
            whs.append(((ms * max_sizes[i]) ** 0.5,) * 2)
    whs = jnp.asarray(whs)  # (A, 2)

    centers = jnp.stack([cx, cy], -1).reshape(-1, 1, 2)       # (HW, 1, 2)
    half = whs[None, :, :] / 2.0                              # (1, A, 2)
    boxes = jnp.concatenate([centers - half, centers + half], -1)
    boxes = boxes.reshape(-1, 4) / jnp.asarray(
        [image_w, image_h, image_w, image_h], jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register_op("yolo_box")
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, scale_x_y=1.0, clip_bbox=True):
    """Decode a YOLOv3 head (yolo_box_op). x: (B, A*(5+C), H, W) NCHW like
    the reference; anchors: [(w,h), ...] in pixels. Returns (boxes
    (B, H*W*A, 4) xyxy in image pixels, scores (B, H*W*A, C))."""
    b, _, h, w = x.shape
    a = len(anchors)
    c = class_num
    x = x.reshape(b, a, 5 + c, h, w).transpose(0, 3, 4, 1, 2)  # (B,H,W,A,5+C)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, :, None]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, :, None, None]
    anchors = jnp.asarray(anchors, jnp.float32)  # (A, 2)

    bias = 0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(x[..., 0]) * scale_x_y - bias + grid_x) / w
    cy = (jax.nn.sigmoid(x[..., 1]) * scale_x_y - bias + grid_y) / h
    bw = jnp.exp(x[..., 2]) * anchors[None, None, None, :, 0] \
        / (downsample_ratio * w)
    bh = jnp.exp(x[..., 3]) * anchors[None, None, None, :, 1] \
        / (downsample_ratio * h)
    conf = jax.nn.sigmoid(x[..., 4])
    probs = jax.nn.sigmoid(x[..., 5:]) * conf[..., None]
    probs = jnp.where(conf[..., None] >= conf_thresh, probs, 0.0)

    img_wh = img_size[:, None, ::-1].astype(jnp.float32)       # (B,1,2) w,h
    boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                       cx + bw / 2, cy + bh / 2], -1)
    boxes = boxes.reshape(b, -1, 4) * jnp.tile(img_wh, (1, 1, 2))
    if clip_bbox:
        # yolo_box_op CalcDetectionBox (yolo_box_op.h:48): x1/y1 floor at 0,
        # x2/y2 ceil at img_w-1 / img_h-1.
        boxes = jnp.concatenate([
            jnp.maximum(boxes[..., :2], 0.0),
            jnp.minimum(boxes[..., 2:], img_wh - 1.0)], -1)
    return boxes, probs.reshape(b, -1, c)


@register_op("nms")
def nms(boxes, scores, *, iou_threshold=0.5, score_threshold=0.0,
        max_outputs=100):
    """Static-shape greedy NMS. boxes (N,4), scores (N,). Returns
    (indices (max_outputs,), valid (max_outputs,) bool) — XLA-compatible
    fixed shapes (the reference's multiclass_nms returns a LoD tensor;
    here validity masks carry the dynamic count)."""
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)
    order_scores = jnp.where(scores >= score_threshold, scores, -jnp.inf)

    def body(carry, _):
        avail_scores, = carry
        idx = jnp.argmax(avail_scores)
        best = avail_scores[idx]
        valid = best > -jnp.inf
        # suppress overlapping + the chosen one
        suppress = (iou[idx] >= iou_threshold) | (
            jnp.arange(n) == idx)
        avail_scores = jnp.where(valid & suppress, -jnp.inf, avail_scores)
        return (avail_scores,), (jnp.where(valid, idx, 0), valid)

    _, (idxs, valid) = jax.lax.scan(
        body, (order_scores,), None, length=min(max_outputs, n))
    pad = max_outputs - idxs.shape[0]
    if pad > 0:
        idxs = jnp.concatenate([idxs, jnp.zeros((pad,), idxs.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    return idxs, valid


@register_op("multiclass_nms")
def multiclass_nms(boxes, scores, *, iou_threshold=0.45,
                   score_threshold=0.01, max_per_class=100):
    """Per-class NMS (multiclass_nms_op). boxes (N,4), scores (N,C).
    Returns (cls_ids, indices, valid) each (C*max_per_class,)."""
    c = scores.shape[1]
    f = functools.partial(nms, iou_threshold=iou_threshold,
                          score_threshold=score_threshold,
                          max_outputs=max_per_class)
    idxs, valid = jax.vmap(lambda s: f(boxes, s), in_axes=1)(scores)
    cls_ids = jnp.repeat(jnp.arange(c), max_per_class)
    return cls_ids, idxs.reshape(-1), valid.reshape(-1)


@register_op("box_clip")
def box_clip(boxes, im_shape):
    """Clip xyxy boxes into the image (box_clip_op). boxes (..., 4);
    im_shape (2,) = (h, w) or (..., 2) broadcastable."""
    im_shape = jnp.asarray(im_shape, boxes.dtype)
    h = im_shape[..., 0:1]
    w = im_shape[..., 1:2]
    x1 = jnp.clip(boxes[..., 0:1], 0.0, w - 1)
    y1 = jnp.clip(boxes[..., 1:2], 0.0, h - 1)
    x2 = jnp.clip(boxes[..., 2:3], 0.0, w - 1)
    y2 = jnp.clip(boxes[..., 3:4], 0.0, h - 1)
    return jnp.concatenate([x1, y1, x2, y2], axis=-1)


@register_op("matrix_nms")
def matrix_nms(boxes, scores, *, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0):
    """Matrix NMS (matrix_nms_op, SOLOv2): fully parallel soft-NMS — each
    box's score decays by its worst overlap with any HIGHER-scored box,
    compensated by how suppressed that box itself is. No sequential loop:
    one (K, K) IoU matrix + reductions, the XLA/MXU-friendly NMS.

    boxes (N,4), scores (N,). Returns (indices (keep_top_k,), new_scores,
    valid) — fixed shapes, validity-masked like :func:`nms`.
    """
    n = boxes.shape[0]
    k = min(nms_top_k, n)
    top_scores, order = jax.lax.top_k(
        jnp.where(scores >= score_threshold, scores, -jnp.inf), k)
    cand = boxes[order]                                    # (K, 4)
    iou = box_iou(cand, cand)                              # (K, K)
    # pairwise IoU with strictly higher-scored boxes only (upper triangle)
    higher = jnp.triu(jnp.ones((k, k), bool), 1)           # j < i in score
    iou_h = jnp.where(higher.T, iou, 0.0)                  # (i, j): j higher
    # compensation: how suppressed the suppressor itself is
    comp = iou_h.max(axis=1)                               # per-box
    comp_j = comp[None, :]
    if use_gaussian:
        decay = jnp.exp(-(iou_h ** 2 - comp_j ** 2) / gaussian_sigma)
    else:
        decay = (1.0 - iou_h) / jnp.maximum(1.0 - comp_j, 1e-10)
    decay = jnp.where(iou_h > 0.0, decay, 1.0).min(axis=1)
    new_scores = jnp.where(jnp.isfinite(top_scores),
                           top_scores * decay, -jnp.inf)
    new_scores = jnp.where(new_scores >= post_threshold, new_scores,
                           -jnp.inf)
    kk = min(keep_top_k, k)
    kept_scores, kept = jax.lax.top_k(new_scores, kk)
    idxs = order[kept]
    valid = jnp.isfinite(kept_scores)
    pad = keep_top_k - kk
    if pad > 0:
        idxs = jnp.concatenate([idxs, jnp.zeros((pad,), idxs.dtype)])
        kept_scores = jnp.concatenate(
            [kept_scores, jnp.full((pad,), -jnp.inf)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    return idxs, jnp.where(valid, kept_scores, 0.0), valid


@register_op("density_prior_box")
def density_prior_box(feature_h, feature_w, image_h, image_w, *,
                      fixed_sizes, fixed_ratios=(1.0,), densities=(1,),
                      step=None, offset=0.5, clip=True):
    """Density prior boxes (density_prior_box_op, PyramidBox face
    detection): each (fixed_size, density) pair tiles density^2 shifted
    anchor centers per cell. Returns (H*W*A, 4) normalized xyxy with
    A = sum(d^2) * len(fixed_ratios)."""
    if len(fixed_sizes) != len(densities):
        raise ValueError(
            f"fixed_sizes ({len(fixed_sizes)}) and densities "
            f"({len(densities)}) must pair up one-to-one")
    step_h = step or image_h / feature_h
    step_w = step or image_w / feature_w
    cy0 = (jnp.arange(feature_h) + offset) * step_h
    cx0 = (jnp.arange(feature_w) + offset) * step_w
    cx0, cy0 = jnp.meshgrid(cx0, cy0)            # (H, W)

    rows = []
    # reference (density_prior_box_op.h:96) TRUNCATES the averaged step
    # and the per-density shift to int — match exactly
    step_avg = int((step_h + step_w) * 0.5)
    for size, density in zip(fixed_sizes, densities):
        shift = int(step_avg / density)
        for ratio in fixed_ratios:
            w = size * (ratio ** 0.5)
            h = size / (ratio ** 0.5)
            for di in range(density):
                for dj in range(density):
                    ox = (dj + 0.5) * shift - step_avg / 2.0
                    oy = (di + 0.5) * shift - step_avg / 2.0
                    rows.append((ox, oy, w, h))
    offs = jnp.asarray(rows, jnp.float32)        # (A, 4): ox, oy, w, h

    centers = jnp.stack([cx0, cy0], -1).reshape(-1, 1, 2)   # (HW, 1, 2)
    ctr = centers + offs[None, :, :2]
    half = offs[None, :, 2:] / 2.0
    boxes = jnp.concatenate([ctr - half, ctr + half], -1).reshape(-1, 4)
    boxes = boxes / jnp.asarray([image_w, image_h, image_w, image_h],
                                jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register_op("anchor_generator")
def anchor_generator(feature_h, feature_w, *, anchor_sizes=(64, 128, 256),
                     aspect_ratios=(0.5, 1.0, 2.0), stride=(16.0, 16.0),
                     offset=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    """RPN anchors for one feature map (anchor_generator_op). Unlike
    prior_box (SSD, normalized coords), returns PIXEL-coordinate xyxy
    anchors (H*W*A, 4) plus the broadcast variances (H*W*A, 4)."""
    sh, sw = stride
    cy = (jnp.arange(feature_h, dtype=jnp.float32) + offset) * sh
    cx = (jnp.arange(feature_w, dtype=jnp.float32) + offset) * sw
    cx, cy = jnp.meshgrid(cx, cy)                             # (H, W)

    whs = []
    for size in anchor_sizes:
        area = float(size) ** 2
        for ar in aspect_ratios:
            w = (area / ar) ** 0.5
            whs.append((w, w * ar))
    whs = jnp.asarray(whs, jnp.float32)                       # (A, 2)

    centers = jnp.stack([cx, cy], -1).reshape(-1, 1, 2)       # (HW, 1, 2)
    half = whs[None, :, :] / 2.0
    anchors = jnp.concatenate([centers - half, centers + half],
                              -1).reshape(-1, 4)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (anchors.shape[0], 4))
    return anchors, var


@register_op("roi_pool")
def roi_pool(features, rois, *, output_size=(7, 7), spatial_scale=1.0):
    """ROI max pooling (roi_pool_op — the quantized Fast-RCNN pooling;
    roi_align below is the interpolated successor). features (H, W, C);
    rois (R, 4) xyxy image coords. Returns (R, oh, ow, C)."""
    h, w, c = features.shape
    oh, ow = output_size
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)
    neg = jnp.finfo(features.dtype).min

    def one_roi(roi):
        x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)

        def one_bin(by, bx):
            # quantized bin bounds (floor/ceil like the reference)
            y_lo = y1 + jnp.floor(by * rh / oh)
            y_hi = y1 + jnp.ceil((by + 1) * rh / oh)
            x_lo = x1 + jnp.floor(bx * rw / ow)
            x_hi = x1 + jnp.ceil((bx + 1) * rw / ow)
            in_y = (ys >= y_lo) & (ys < y_hi)
            in_x = (xs >= x_lo) & (xs < x_hi)
            m = in_y[:, None] & in_x[None, :]
            masked = jnp.where(m[..., None], features, neg)
            out = masked.max(axis=(0, 1))
            return jnp.where(m.any(), out, 0.0)               # empty bin -> 0

        by = jnp.arange(oh)
        bx = jnp.arange(ow)
        return jax.vmap(lambda y: jax.vmap(
            lambda x: one_bin(y, x))(bx))(by)                 # (oh, ow, C)

    return jax.vmap(one_roi)(rois)


@register_op("roi_align")
def roi_align(features, rois, *, output_size=(7, 7), spatial_scale=1.0,
              sampling_ratio=2):
    """ROIAlign (roi_align_op). features (H, W, C) single image NHWC slice;
    rois (R, 4) xyxy in image coords. Returns (R, oh, ow, C)."""
    h, w, _ = features.shape
    oh, ow = output_size

    def one_roi(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        # sampling_ratio x sampling_ratio bilinear samples per bin
        sr = sampling_ratio
        ys = y1 + (jnp.arange(oh * sr) + 0.5) * bin_h / sr
        xs = x1 + (jnp.arange(ow * sr) + 0.5) * bin_w / sr

        def bilinear(y, x):
            y = jnp.clip(y, 0.0, h - 1.0)
            x = jnp.clip(x, 0.0, w - 1.0)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(x).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, h - 1)
            x1_ = jnp.minimum(x0 + 1, w - 1)
            wy = y - y0
            wx = x - x0
            return (features[y0, x0] * (1 - wy) * (1 - wx)
                    + features[y0, x1_] * (1 - wy) * wx
                    + features[y1_, x0] * wy * (1 - wx)
                    + features[y1_, x1_] * wy * wx)

        samples = jax.vmap(lambda y: jax.vmap(
            lambda x: bilinear(y, x))(xs))(ys)      # (oh*sr, ow*sr, C)
        samples = samples.reshape(oh, sr, ow, sr, -1)
        return samples.mean(axis=(1, 3))

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# Training-side detection stack: matching, target assignment, losses.
# Reference: operators/detection/{bipartite_match,target_assign,
# mine_hard_examples}_op.cc, ssd_loss composition in
# python/paddle/fluid/layers/detection.py (ssd_loss), yolov3_loss_op.cc,
# sigmoid_focal_loss_op.cc, rpn_target_assign_op.cc,
# generate_proposals_op.cc, distribute_fpn_proposals_op.cc,
# collect_fpn_proposals_op.cc, polygon_box_transform_op.cc.
# TPU design: everything static-shape; ground truths arrive padded with a
# row mask (the LoD analog), dynamic counts ride validity masks, and the
# sequential greedy pieces are lax loops with trip count = padded G (small).
# ---------------------------------------------------------------------------


@register_op("bipartite_match")
def bipartite_match(dist, row_mask=None):
    """Greedy bipartite matching (bipartite_match_op.cc). ``dist`` (G, P):
    similarity of ground-truth rows vs prior columns; ``row_mask`` (G,)
    marks real rows of a padded batch. Iteratively matches the globally
    best (row, col) pair and retires both. Returns (match_indices (P,)
    int32 — matched row per column, -1 if none; match_dist (P,))."""
    g, p = dist.shape
    if row_mask is not None:
        dist = jnp.where(row_mask[:, None], dist, -1.0)

    def body(_, carry):
        d, col_to_row, col_dist = carry
        idx = jnp.argmax(d)
        r, c = idx // p, idx % p
        best = d[r, c]
        ok = best > 0.0
        col_to_row = jnp.where(ok, col_to_row.at[c].set(r.astype(jnp.int32)),
                               col_to_row)
        col_dist = jnp.where(ok, col_dist.at[c].set(best), col_dist)
        d2 = d.at[r, :].set(-1.0)
        d2 = d2.at[:, c].set(-1.0)
        return jnp.where(ok, d2, d), col_to_row, col_dist

    init = (dist, jnp.full((p,), -1, jnp.int32),
            jnp.zeros((p,), dist.dtype))
    _, col_to_row, col_dist = jax.lax.fori_loop(0, g, body, init)
    return col_to_row, col_dist


def match_boxes(iou, row_mask=None, *, match_type="per_prediction",
                overlap_threshold=0.5):
    """SSD matching: bipartite seeds, then (per_prediction) every unmatched
    prior whose best-IoU ground truth exceeds ``overlap_threshold`` also
    matches it (layers/detection.py ssd_loss matching step)."""
    m_idx, m_dist = bipartite_match(iou, row_mask)
    if match_type == "per_prediction":
        masked = iou if row_mask is None else jnp.where(
            row_mask[:, None], iou, -1.0)
        best_row = jnp.argmax(masked, axis=0).astype(jnp.int32)
        best_iou = jnp.max(masked, axis=0)
        extra = (m_idx < 0) & (best_iou >= overlap_threshold)
        m_idx = jnp.where(extra, best_row, m_idx)
        m_dist = jnp.where(extra, best_iou, m_dist)
    return m_idx, m_dist


@register_op("target_assign")
def target_assign(x, match_indices, mismatch_value=0.0):
    """Gather per-prior targets from per-ground-truth rows
    (target_assign_op.cc). ``x`` (G, K) row attributes; ``match_indices``
    (P,) from :func:`bipartite_match`. Returns (out (P, K), out_weight (P,)
    — 1.0 where matched, 0.0 elsewhere; unmatched rows filled with
    ``mismatch_value``)."""
    matched = match_indices >= 0
    out = x[jnp.maximum(match_indices, 0)]
    out = jnp.where(matched[:, None], out,
                    jnp.asarray(mismatch_value, x.dtype))
    return out, matched.astype(jnp.float32)


def _stable_bce(logits, targets):
    """max(x,0) - x*t + log1p(exp(-|x|)) — the overflow-safe sigmoid BCE
    shared by focal and YOLOv3 losses."""
    return (jnp.maximum(logits, 0.0) - logits * targets
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))


def topk_mask(mask, score, limit):
    """Keep at most ``limit`` (dynamic) True entries of ``mask``, the ones
    with the highest ``score`` — the static-shape "dynamic count as a rank
    threshold" idiom shared by hard-negative mining and RPN subsampling."""
    p = score.shape[0]
    order = jnp.argsort(-jnp.where(mask, score, -jnp.inf))
    rank = jnp.zeros((p,), jnp.int32).at[order].set(
        jnp.arange(p, dtype=jnp.int32))
    return mask & (rank < limit)


@register_op("mine_hard_examples")
def mine_hard_examples(neg_loss, match_indices, *, neg_pos_ratio=3.0,
                       sample_size=None):
    """Hard-negative mining, ``max_negative`` mode
    (mine_hard_examples_op.cc): keep the ``neg_pos_ratio * num_pos``
    unmatched priors with the highest candidate loss. The dynamic count is
    carried as a rank threshold (static shapes). Returns bool (P,)."""
    p = neg_loss.shape[0]
    pos = match_indices >= 0
    num_pos = pos.sum()
    cap = jnp.asarray(sample_size, jnp.int32) if sample_size is not None \
        else jnp.asarray(p, jnp.int32)
    num_neg = jnp.minimum((neg_pos_ratio * num_pos).astype(jnp.int32), cap)
    return topk_mask(~pos & jnp.isfinite(neg_loss), neg_loss, num_neg)


@register_op("ssd_loss")
def ssd_loss(loc_pred, conf_pred, anchors, gt_boxes, gt_labels, gt_mask, *,
             background_label=0, overlap_threshold=0.5, neg_pos_ratio=3.0,
             loc_weight=1.0, conf_weight=1.0,
             variances=(0.1, 0.1, 0.2, 0.2)):
    """MultiBox SSD loss (layers/detection.py ssd_loss, composed from the
    same primitive ops as the reference): match -> encode -> smooth-L1 on
    positives + softmax CE on positives and mined hard negatives,
    normalized by the matched count per image.

    loc_pred (B, P, 4) deltas; conf_pred (B, P, C) logits (class 0 =
    background); anchors (P, 4) normalized xyxy; gt_boxes (B, G, 4)
    normalized xyxy (padded); gt_labels (B, G) int in [1, C); gt_mask
    (B, G) bool. Returns scalar mean loss."""
    from paddle_tpu.ops.nn import smooth_l1

    def one(loc_p, conf_p, gt_b, gt_l, gt_m):
        iou = box_iou(gt_b, anchors)                          # (G, P)
        m_idx, _ = match_boxes(iou, gt_m,
                               overlap_threshold=overlap_threshold)
        pos = m_idx >= 0
        tgt_boxes, _ = target_assign(gt_b, m_idx)
        loc_t = box_encode(tgt_boxes, anchors, variances)
        loc_l = (smooth_l1(loc_p, jax.lax.stop_gradient(loc_t)).sum(-1)
                 * pos)                                       # (P,)
        cls_t = jnp.where(pos, gt_l[jnp.maximum(m_idx, 0)],
                          background_label)
        logp = jax.nn.log_softmax(conf_p.astype(jnp.float32), -1)
        ce = -jnp.take_along_axis(logp, cls_t[:, None], -1)[:, 0]
        neg = mine_hard_examples(-logp[:, background_label], m_idx,
                                 neg_pos_ratio=neg_pos_ratio)
        conf_l = ce * (pos | neg)
        n_match = jnp.maximum(pos.sum(), 1)
        return (loc_weight * loc_l.sum()
                + conf_weight * conf_l.sum()) / n_match

    return jax.vmap(one)(loc_pred, conf_pred, gt_boxes, gt_labels,
                         gt_mask).mean()


@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(logits, labels, *, gamma=2.0, alpha=0.25,
                       normalizer=None):
    """Focal loss (sigmoid_focal_loss_op.cc, RetinaNet). ``logits`` (N, C);
    ``labels`` (N,) int in [0, C] where 0 = background and class k maps to
    column k-1 (the reference convention). Returns the per-element (N, C)
    loss, optionally divided by ``normalizer`` (foreground count)."""
    c = logits.shape[1]
    t = (labels[:, None] == jnp.arange(1, c + 1)[None, :]).astype(
        logits.dtype)
    p = jax.nn.sigmoid(logits)
    bce = _stable_bce(logits, t)
    p_t = p * t + (1.0 - p) * (1.0 - t)
    a_t = alpha * t + (1.0 - alpha) * (1.0 - t)
    loss = a_t * (1.0 - p_t) ** gamma * bce
    if normalizer is not None:
        loss = loss / jnp.maximum(normalizer, 1.0)
    return loss


@register_op("yolov3_loss")
def yolov3_loss(x, gt_boxes, gt_labels, gt_mask, *, anchors, anchor_mask,
                class_num, ignore_thresh=0.7, downsample_ratio=32):
    """YOLOv3 loss for one head (yolov3_loss_op.cc). ``x`` (B, A*(5+C), H,
    W) NCHW raw head output, A = len(anchor_mask); ``anchors`` the FULL
    pixel-space anchor list [(w, h), ...]; ``anchor_mask`` the indices this
    head owns; ``gt_boxes`` (B, G, 4) normalized (cx, cy, w, h) in [0, 1]
    (the reference layout); ``gt_labels`` (B, G) int; ``gt_mask`` (B, G).

    Per ground truth: the responsible cell is (floor(cx*W), floor(cy*H));
    the responsible anchor is the best wh-IoU over the FULL anchor set —
    the gt contributes xywh/obj/class terms only if that anchor belongs to
    this head. Objectness negatives are cells whose best predicted-box IoU
    with any gt stays below ``ignore_thresh``. Returns scalar mean loss."""
    b, _, h, w = x.shape
    a = len(anchor_mask)
    c = class_num
    g = gt_boxes.shape[1]
    full = jnp.asarray(anchors, jnp.float32)                  # (Af, 2)
    own = jnp.asarray(anchor_mask, jnp.int32)                 # (A,)
    head_wh = full[own]                                       # (A, 2)
    in_w = w * downsample_ratio
    in_h = h * downsample_ratio

    x = x.reshape(b, a, 5 + c, h, w).transpose(0, 3, 4, 1, 2)  # (B,H,W,A,5+C)

    def wh_iou(wh1, wh2):
        inter = jnp.minimum(wh1[..., 0], wh2[..., 0]) * \
            jnp.minimum(wh1[..., 1], wh2[..., 1])
        return inter / jnp.maximum(
            wh1[..., 0] * wh1[..., 1] + wh2[..., 0] * wh2[..., 1] - inter,
            1e-10)

    def one(head, gt_b, gt_l, gt_m):
        # --- decode predicted boxes (normalized cxcywh) for ignore mask
        grid_x = jnp.arange(w, dtype=jnp.float32)[None, :, None]
        grid_y = jnp.arange(h, dtype=jnp.float32)[:, None, None]
        px = (jax.nn.sigmoid(head[..., 0]) + grid_x) / w
        py = (jax.nn.sigmoid(head[..., 1]) + grid_y) / h
        pw = jnp.exp(jnp.clip(head[..., 2], -10, 10)) * \
            head_wh[None, None, :, 0] / in_w
        ph = jnp.exp(jnp.clip(head[..., 3], -10, 10)) * \
            head_wh[None, None, :, 1] / in_h
        pred = jnp.stack([px - pw / 2, py - ph / 2,
                          px + pw / 2, py + ph / 2], -1)      # (H,W,A,4)
        gt_xyxy = jnp.concatenate([gt_b[:, :2] - gt_b[:, 2:] / 2,
                                   gt_b[:, :2] + gt_b[:, 2:] / 2], -1)
        ious = box_iou(pred.reshape(-1, 4), gt_xyxy)          # (HWA, G)
        ious = jnp.where(gt_m[None, :], ious, 0.0)
        ignore = (ious.max(-1) >= ignore_thresh).reshape(h, w, a)

        # --- per-gt responsible (cell, anchor) targets, scattered
        t_obj = jnp.zeros((h, w, a))
        t_xy = jnp.zeros((h, w, a, 2))
        t_wh = jnp.zeros((h, w, a, 2))
        t_cls = jnp.zeros((h, w, a, c))
        t_scale = jnp.zeros((h, w, a))

        def assign(i, carry):
            t_obj, t_xy, t_wh, t_cls, t_scale = carry
            box = gt_b[i]
            gi = jnp.clip((box[0] * w).astype(jnp.int32), 0, w - 1)
            gj = jnp.clip((box[1] * h).astype(jnp.int32), 0, h - 1)
            gt_wh_pix = box[2:] * jnp.asarray([in_w, in_h], jnp.float32)
            best = jnp.argmax(wh_iou(full, gt_wh_pix[None, :]))
            owned = (own == best)
            ai = jnp.argmax(owned)                            # head slot
            use = gt_m[i] & owned.any() & (box[2] > 0) & (box[3] > 0)
            tx = box[0] * w - gi
            ty = box[1] * h - gj
            twh = jnp.log(jnp.maximum(
                gt_wh_pix / jnp.maximum(full[best], 1e-10), 1e-10))
            scale = 2.0 - box[2] * box[3]
            onehot = jax.nn.one_hot(gt_l[i], c)
            t_obj = jnp.where(use, t_obj.at[gj, gi, ai].set(1.0), t_obj)
            t_xy = jnp.where(use, t_xy.at[gj, gi, ai].set(
                jnp.stack([tx, ty])), t_xy)
            t_wh = jnp.where(use, t_wh.at[gj, gi, ai].set(twh), t_wh)
            t_cls = jnp.where(use, t_cls.at[gj, gi, ai].set(onehot), t_cls)
            t_scale = jnp.where(use, t_scale.at[gj, gi, ai].set(scale),
                                t_scale)
            return t_obj, t_xy, t_wh, t_cls, t_scale

        t_obj, t_xy, t_wh, t_cls, t_scale = jax.lax.fori_loop(
            0, g, assign, (t_obj, t_xy, t_wh, t_cls, t_scale))

        bce = _stable_bce
        pos = t_obj > 0
        sc = t_scale * pos
        loss_xy = (bce(head[..., 0:2], t_xy).sum(-1) * sc).sum()
        loss_wh = (jnp.abs(head[..., 2:4] - t_wh).sum(-1) * sc).sum()
        obj_logit = head[..., 4]
        loss_obj = (bce(obj_logit, 1.0) * pos).sum() + \
            (bce(obj_logit, 0.0) * (~pos & ~ignore)).sum()
        loss_cls = (bce(head[..., 5:], t_cls).sum(-1) * pos).sum()
        return loss_xy + loss_wh + loss_obj + loss_cls

    return jax.vmap(one)(x, gt_boxes, gt_labels, gt_mask).mean()


@register_op("rpn_target_assign")
def rpn_target_assign(anchors, gt_boxes, gt_mask, *, im_shape=None,
                      pos_threshold=0.7, neg_threshold=0.3,
                      batch_size_per_im=256, fg_fraction=0.5,
                      variances=(1.0, 1.0, 1.0, 1.0), key=None):
    """RPN anchor labeling (rpn_target_assign_op.cc): label 1 for anchors
    with IoU >= pos_threshold or each gt's argmax anchor; 0 below
    neg_threshold; -1 (ignored) between. Counts are capped at
    ``fg_fraction * batch_size_per_im`` foregrounds and the remainder
    backgrounds — the reference subsamples randomly; pass ``key`` for that,
    otherwise the hardest (highest/lowest IoU) are kept deterministically.
    Returns (labels (P,) int32, bbox_targets (P, 4), pos_mask, neg_mask)."""
    p = anchors.shape[0]
    inside = None
    if im_shape is not None:
        h, w = im_shape[0], im_shape[1]
        inside = ((anchors[:, 0] >= 0) & (anchors[:, 1] >= 0)
                  & (anchors[:, 2] <= w - 1) & (anchors[:, 3] <= h - 1))
    iou = box_iou(gt_boxes, anchors)                          # (G, P)
    iou = jnp.where(gt_mask[:, None], iou, -1.0)
    if inside is not None:
        # rpn_target_assign_op.cc excludes anchors straddling the image
        # boundary from labeling entirely (they stay -1 / ignored)
        iou = jnp.where(inside[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=0)                         # per anchor
    best_iou = jnp.max(iou, axis=0)
    # each gt's best anchor is always fg (ties broadcast via equality) —
    # but only when the gt overlaps SOMETHING: a zero-IoU gt must not
    # force every anchor positive through the >= 0 comparison
    gt_best = jnp.max(jnp.where(gt_mask[:, None], iou, -jnp.inf), axis=1)
    forced = ((iou >= gt_best[:, None]) & gt_mask[:, None]
              & (gt_best[:, None] > 0)).any(0)
    fg = forced | (best_iou >= pos_threshold)
    # best_iou == -1 (no valid gt at all) is definitionally background:
    # empty images must still contribute negative objectness samples
    bg = (~fg) & (best_iou < neg_threshold)

    max_fg = int(batch_size_per_im * fg_fraction)
    rand = (jax.random.uniform(key, (p,)) if key is not None
            else jnp.zeros((p,)))

    if inside is not None:
        fg = fg & inside
        bg = bg & inside
    fg = topk_mask(fg, best_iou + rand, max_fg)
    n_fg = fg.sum()
    bg = topk_mask(bg, -best_iou + rand, batch_size_per_im - n_fg)

    labels = jnp.where(fg, 1, jnp.where(bg, 0, -1)).astype(jnp.int32)
    tgt = box_encode(gt_boxes[best_gt], anchors, variances)
    tgt = jnp.where(fg[:, None], tgt, 0.0)
    return labels, tgt, fg, bg


@register_op("generate_proposals")
def generate_proposals(scores, deltas, anchors, im_shape, *,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.7, min_size=0.0,
                       variances=(1.0, 1.0, 1.0, 1.0)):
    """RPN proposal generation (generate_proposals_op.cc), one image:
    decode -> clip -> drop tiny -> top-k pre-NMS -> NMS -> top-k post.
    ``scores`` (P,), ``deltas`` (P, 4), ``anchors`` (P, 4) pixel xyxy,
    ``im_shape`` (2,) = (h, w). Returns (rois (post, 4), roi_scores
    (post,), valid (post,) bool) — static shapes."""
    p = scores.shape[0]
    boxes = box_decode(deltas, anchors, variances)
    boxes = box_clip(boxes, im_shape)
    ws = boxes[:, 2] - boxes[:, 0] + 1
    hs = boxes[:, 3] - boxes[:, 1] + 1
    keep = (ws >= min_size) & (hs >= min_size)
    s = jnp.where(keep, scores, -jnp.inf)
    k = min(pre_nms_top_n, p)
    top_s, order = jax.lax.top_k(s, k)
    cand = boxes[order]
    idxs, valid = nms(cand, top_s, iou_threshold=nms_thresh,
                      score_threshold=-jnp.inf,
                      max_outputs=min(post_nms_top_n, k))
    rois = cand[idxs]
    roi_scores = jnp.where(valid, top_s[idxs], -jnp.inf)
    valid = valid & jnp.isfinite(roi_scores)
    pad = post_nms_top_n - idxs.shape[0]
    if pad > 0:
        rois = jnp.concatenate([rois, jnp.zeros((pad, 4))])
        roi_scores = jnp.concatenate(
            [roi_scores, jnp.full((pad,), -jnp.inf)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    # invalid rows keep -inf scores so downstream top-k (e.g.
    # collect_fpn_proposals without valid_list) can never pick padding
    return rois, jnp.where(valid, roi_scores, -jnp.inf), valid


@register_op("distribute_fpn_proposals")
def distribute_fpn_proposals(rois, *, min_level=2, max_level=5,
                             refer_level=4, refer_scale=224):
    """Map RoIs to FPN levels (distribute_fpn_proposals_op.cc):
    level = clip(floor(refer_level + log2(sqrt(area)/refer_scale))).
    The reference splits into per-level LoD tensors; here the split is a
    (L, N) bool mask stack plus the level index per RoI — downstream heads
    run all levels with masked RoIs (static shapes)."""
    ws = jnp.maximum(rois[:, 2] - rois[:, 0], 0.0)
    hs = jnp.maximum(rois[:, 3] - rois[:, 1], 0.0)
    scale = jnp.sqrt(ws * hs)
    lvl = jnp.floor(refer_level + jnp.log2(
        jnp.maximum(scale, 1e-6) / refer_scale))
    lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
    levels = jnp.arange(min_level, max_level + 1)
    masks = lvl[None, :] == levels[:, None]                   # (L, N)
    return lvl, masks


@register_op("collect_fpn_proposals")
def collect_fpn_proposals(rois_list, scores_list, valid_list=None, *,
                          post_nms_top_n=1000):
    """Merge per-level proposals and keep the global top-k by score
    (collect_fpn_proposals_op.cc). Inputs: lists of (Ni, 4) / (Ni,);
    ``valid_list`` carries :func:`generate_proposals`' validity masks.
    Padding is also safe without it: generate_proposals keeps -inf
    scores on invalid rows, which the isfinite check here rejects.
    Returns (rois (k, 4), scores (k,), valid (k,))."""
    rois = jnp.concatenate(rois_list, axis=0)
    scores = jnp.concatenate(scores_list, axis=0)
    if valid_list is not None:
        scores = jnp.where(jnp.concatenate(valid_list, axis=0),
                           scores, -jnp.inf)
    k = min(post_nms_top_n, scores.shape[0])
    top_s, order = jax.lax.top_k(scores, k)
    out_r = rois[order]
    valid = jnp.isfinite(top_s)
    pad = post_nms_top_n - k
    if pad > 0:
        out_r = jnp.concatenate([out_r, jnp.zeros((pad, 4))])
        top_s = jnp.concatenate([top_s, jnp.full((pad,), -jnp.inf)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    # invalid rows keep -inf (same convention as generate_proposals)
    return out_r, top_s, valid


@register_op("polygon_box_transform")
def polygon_box_transform(x):
    """EAST quad-offset to absolute coords (polygon_box_transform_op.cc):
    input (B, 8, H, W) predicted offsets on a 4x-downsampled grid; output
    channel 2k   (x offsets): 4*w_index - in,
    channel 2k+1 (y offsets): 4*h_index - in."""
    b, c, h, w = x.shape
    xi = jnp.arange(w, dtype=x.dtype)[None, None, None, :] * 4.0
    yi = jnp.arange(h, dtype=x.dtype)[None, None, :, None] * 4.0
    is_x = (jnp.arange(c) % 2 == 0)[None, :, None, None]
    return jnp.where(is_x, xi - x, yi - x)


@register_op("retinanet_detection_output")
def retinanet_detection_output(boxes_list, scores_list, anchors_list,
                               im_shape, *, score_threshold=0.05,
                               nms_top_k=1000, keep_top_k=100,
                               nms_threshold=0.5,
                               variances=(1.0, 1.0, 1.0, 1.0)):
    """RetinaNet decode + multiclass NMS across FPN levels
    (retinanet_detection_output_op.cc), one image. ``boxes_list``: per-level
    (Pi, 4) deltas; ``scores_list``: per-level (Pi, C) sigmoid scores;
    ``anchors_list``: per-level (Pi, 4). Returns (boxes (K, 4), cls (K,),
    scores (K,), valid (K,)) with K = keep_top_k."""
    decoded = [box_clip(box_decode(d, a, variances), im_shape)
               for d, a in zip(boxes_list, anchors_list)]
    boxes = jnp.concatenate(decoded, axis=0)
    scores = jnp.concatenate(scores_list, axis=0)             # (P, C)
    # pre-NMS top-k by best class score (the reference filters per level
    # before NMS): bounds the NxN IoU matrix at nms_top_k, not P
    k = min(nms_top_k, scores.shape[0])
    _, sel = jax.lax.top_k(scores.max(axis=1), k)
    boxes = boxes[sel]
    scores = scores[sel]
    per = max(1, keep_top_k)
    cls_ids, idxs, valid = multiclass_nms(
        boxes, scores, iou_threshold=nms_threshold,
        score_threshold=score_threshold, max_per_class=per)
    sel_scores = jnp.where(
        valid, scores[idxs, cls_ids], -jnp.inf)
    k = min(keep_top_k, sel_scores.shape[0])
    top_s, order = jax.lax.top_k(sel_scores, k)
    out_valid = jnp.isfinite(top_s)
    return (boxes[idxs[order]], cls_ids[order],
            jnp.where(out_valid, top_s, 0.0), out_valid)


@register_op("detection_output")
def detection_output(loc, conf, anchors, *, score_threshold=0.01,
                     nms_threshold=0.45, nms_top_k=400, keep_top_k=200,
                     variances=(0.1, 0.1, 0.2, 0.2),
                     background_label=0):
    """layers.detection_output (SSD post-process): decode + per-class NMS
    + global top-k. ``loc`` (B, P, 4) deltas; ``conf`` (B, P, C) logits.
    Returns per image (boxes (K, 4), cls (K,), scores (K,), valid)."""

    def one(loc_i, conf_i):
        boxes = box_decode(loc_i, anchors, variances)
        probs = jax.nn.softmax(conf_i.astype(jnp.float32), -1)
        fg = jnp.concatenate([probs[:, :background_label],
                              probs[:, background_label + 1:]], -1)
        # per-class cap is nms_top_k (reference semantics) — NOT
        # keep_top_k split across classes, which would starve crowded
        # single-class scenes; the global keep_top_k cut comes after
        per = max(1, min(nms_top_k, boxes.shape[0]))
        cls_ids, idxs, valid = multiclass_nms(
            boxes, fg, iou_threshold=nms_threshold,
            score_threshold=score_threshold, max_per_class=per)
        sel = jnp.where(valid, fg[idxs, cls_ids], -jnp.inf)
        k = min(keep_top_k, sel.shape[0])
        top_s, order = jax.lax.top_k(sel, k)
        ok = jnp.isfinite(top_s)
        cls = cls_ids[order]
        cls = jnp.where(cls >= background_label, cls + 1, cls)
        return (boxes[idxs[order]], cls, jnp.where(ok, top_s, 0.0), ok)

    return jax.vmap(one)(loc, conf)


def multiclass_nms2(boxes, scores, *, iou_threshold=0.45,
                    score_threshold=0.01, max_per_class=100):
    """multiclass_nms2_op: multiclass_nms that ALSO returns the input-box
    indices (the reference's second output)."""
    cls_ids, idxs, valid = multiclass_nms(
        boxes, scores, iou_threshold=iou_threshold,
        score_threshold=score_threshold, max_per_class=max_per_class)
    return cls_ids, idxs, valid, idxs


@register_op("box_decoder_and_assign")
def box_decoder_and_assign(prior_box, deltas, scores, *,
                           variances=(0.1, 0.1, 0.2, 0.2),
                           box_clip_value=4.135):
    """box_decoder_and_assign_op (Cascade R-CNN): decode per-class box
    deltas (P, C*4) and pick each prior's best-scoring class box.
    Returns (decoded (P, C, 4), assigned (P, 4))."""
    p, c4 = deltas.shape
    c = c4 // 4
    d = deltas.reshape(p, c, 4)
    d = d.at[:, :, 2:].set(jnp.clip(d[:, :, 2:], -box_clip_value,
                                    box_clip_value))
    decoded = jax.vmap(lambda dc: box_decode(dc, prior_box, variances),
                       in_axes=1, out_axes=1)(d)
    best = jnp.argmax(scores[:, :c], axis=-1)
    assigned = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, -1), 1)[:, 0]
    return decoded, assigned


@register_op("retinanet_target_assign")
def retinanet_target_assign(anchors, gt_boxes, gt_labels, gt_mask, *,
                            positive_overlap=0.5, negative_overlap=0.4,
                            variances=(1.0, 1.0, 1.0, 1.0)):
    """retinanet_target_assign_op: anchor labeling for focal-loss heads —
    labels: gt class (>=1) above positive_overlap or per-gt argmax, 0
    below negative_overlap, -1 between (ignored). Returns (cls_targets
    (P,), bbox_targets (P, 4), fg_mask, fg_num)."""
    iou = box_iou(gt_boxes, anchors)
    iou = jnp.where(gt_mask[:, None], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=0)
    best_iou = jnp.max(iou, axis=0)
    gt_best = jnp.max(jnp.where(gt_mask[:, None], iou, -jnp.inf), axis=1)
    forced = ((iou >= gt_best[:, None]) & gt_mask[:, None]
              & (gt_best[:, None] > 0)).any(0)
    fg = forced | (best_iou >= positive_overlap)
    bg = (~fg) & (best_iou < negative_overlap)
    cls = jnp.where(fg, gt_labels[best_gt],
                    jnp.where(bg, 0, -1)).astype(jnp.int32)
    tgt = box_encode(gt_boxes[best_gt], anchors, variances)
    tgt = jnp.where(fg[:, None], tgt, 0.0)
    return cls, tgt, fg, fg.sum()


def _bilinear_sample(img, ys, xs):
    """img (H, W, C); ys/xs float grids (any shape); zero outside."""
    h, w, _ = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def gather(yi, xi):
        inb = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        v = img[jnp.clip(yi, 0, h - 1), jnp.clip(xi, 0, w - 1)]
        return jnp.where(inb[..., None], v, 0.0)

    yi0 = y0.astype(jnp.int32)
    xi0 = x0.astype(jnp.int32)
    return (gather(yi0, xi0) * ((1 - wy) * (1 - wx))[..., None]
            + gather(yi0, xi0 + 1) * ((1 - wy) * wx)[..., None]
            + gather(yi0 + 1, xi0) * (wy * (1 - wx))[..., None]
            + gather(yi0 + 1, xi0 + 1) * (wy * wx)[..., None])


@register_op("psroi_pool")
def psroi_pool(features, rois, *, output_size=7, spatial_scale=1.0,
               output_channels=None):
    """Position-sensitive RoI pooling (psroi_pool_op, R-FCN): input
    channels are k*k groups of D; bin (i, j) average-pools ONLY its own
    group. features (H, W, k*k*D); rois (R, 4) xyxy image coords.
    Returns (R, k, k, D)."""
    k = output_size
    h, w, c = features.shape
    d = output_channels or c // (k * k)
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)

    def one(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)

        def bin_ij(i, j):
            y_lo = y1 + i * rh / k
            y_hi = y1 + (i + 1) * rh / k
            x_lo = x1 + j * rw / k
            x_hi = x1 + (j + 1) * rw / k
            m = ((ys[:, None] >= y_lo) & (ys[:, None] < y_hi)
                 & (xs[None, :] >= x_lo) & (xs[None, :] < x_hi))
            grp = jax.lax.dynamic_slice_in_dim(
                features, (i * k + j) * d, d, axis=2)
            s = (grp * m[..., None]).sum((0, 1))
            return s / jnp.maximum(m.sum(), 1.0)

        ii = jnp.arange(k)
        return jax.vmap(lambda i: jax.vmap(
            lambda j: bin_ij(i, j))(ii))(ii)      # (k, k, D)

    return jax.vmap(one)(rois)


@register_op("prroi_pool")
def prroi_pool(features, rois, *, output_size=(7, 7), spatial_scale=1.0,
               samples_per_bin=4):
    """Precise RoI pooling (prroi_pool_op): continuous average of the
    bilinear-interpolated feature over each bin. The reference evaluates
    the exact integral; here the integral is approximated with a dense
    ``samples_per_bin`` x ``samples_per_bin`` bilinear grid (converges to
    the exact value, fully differentiable incl. w.r.t. roi coords)."""
    oh, ow = output_size
    sp = samples_per_bin

    def one(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        bw = (x2 - x1) / ow
        bh = (y2 - y1) / oh
        ys = y1 + (jnp.arange(oh * sp) + 0.5) * bh / sp
        xs = x1 + (jnp.arange(ow * sp) + 0.5) * bw / sp
        grid = _bilinear_sample(features, ys[:, None] *
                                jnp.ones_like(xs)[None, :],
                                jnp.ones_like(ys)[:, None] * xs[None, :])
        return grid.reshape(oh, sp, ow, sp, -1).mean((1, 3))

    return jax.vmap(one)(rois)


@register_op("deformable_conv")
def deformable_conv(x, offset, weight, *, stride=1, padding=0,
                    mask=None):
    """Deformable conv v1/v2 (deformable_conv_op): each kernel tap samples
    the input at its grid position + a learned (dy, dx) offset, bilinear-
    interpolated; v2 additionally modulates each tap by ``mask``.
    x (B, H, W, Cin); offset (B, Ho, Wo, 2*kh*kw) [dy, dx per tap];
    weight (kh, kw, Cin, Cout); mask (B, Ho, Wo, kh*kw) or None.
    Single group, NHWC (TPU layout; the reference is NCHW)."""
    kh, kw, cin, cout = weight.shape
    s = stride if isinstance(stride, tuple) else (stride, stride)
    p = padding if isinstance(padding, tuple) else (padding, padding)
    b, h, w, _ = x.shape
    ho = (h + 2 * p[0] - kh) // s[0] + 1
    wo = (w + 2 * p[1] - kw) // s[1] + 1
    base_y = jnp.arange(ho) * s[0] - p[0]
    base_x = jnp.arange(wo) * s[1] - p[1]

    def one(img, off, msk):
        taps = []
        for i in range(kh):
            for j in range(kw):
                t = i * kw + j
                dy = off[..., 2 * t]
                dx = off[..., 2 * t + 1]
                ys = base_y[:, None] + i + dy                  # (Ho, Wo)
                xs = base_x[None, :] + j + dx
                v = _bilinear_sample(img, ys, xs)              # (Ho,Wo,Cin)
                if msk is not None:
                    v = v * msk[..., t][..., None]
                taps.append(v @ weight[i, j])                  # (Ho,Wo,Cout)
        return sum(taps)

    if mask is None:
        return jax.vmap(lambda im, of: one(im, of, None))(x, offset)
    return jax.vmap(one)(x, offset, mask)


@register_op("generate_proposal_labels")
def generate_proposal_labels(rois, roi_valid, gt_boxes, gt_labels,
                             gt_mask, *, batch_size_per_im=64,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             variances=(0.1, 0.1, 0.2, 0.2), key=None,
                             return_matches=False):
    """RCNN second-stage target sampling (generate_proposal_labels_op),
    one image: label each proposal by max-IoU gt, subsample to
    ``batch_size_per_im`` with ``fg_fraction`` foregrounds (deterministic
    hardest-first unless ``key`` supplies random tie-break like the
    reference), emit classification + regression targets. Returns
    (labels (P,) int32 [-1 = not sampled], bbox_targets (P, 4),
    fg_mask, bg_mask) — plus the matched gt index per proposal when
    ``return_matches`` (what generate_mask_labels consumes)."""
    p = rois.shape[0]
    iou = box_iou(gt_boxes, rois)
    iou = jnp.where(gt_mask[:, None] & roi_valid[None, :], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=0)
    best_iou = jnp.max(iou, axis=0)
    fg = best_iou >= fg_thresh
    bg = (~fg) & (best_iou < bg_thresh_hi) & (best_iou >= bg_thresh_lo) \
        & roi_valid
    rand = (jax.random.uniform(key, (p,)) if key is not None
            else jnp.zeros((p,)))
    max_fg = int(batch_size_per_im * fg_fraction)
    fg = topk_mask(fg, best_iou + rand, max_fg)
    bg = topk_mask(bg, -best_iou + rand,
                   batch_size_per_im - fg.sum())
    labels = jnp.where(fg, gt_labels[best_gt],
                       jnp.where(bg, 0, -1)).astype(jnp.int32)
    tgt = box_encode(gt_boxes[best_gt], rois, variances)
    tgt = jnp.where(fg[:, None], tgt, 0.0)
    if return_matches:
        return labels, tgt, fg, bg, best_gt
    return labels, tgt, fg, bg


@register_op("roi_perspective_transform")
def roi_perspective_transform(features, rois, *, output_size=(8, 8),
                              spatial_scale=1.0):
    """roi_perspective_transform_op (EAST OCR): rectify quadrilateral
    RoIs into fixed (oh, ow) patches via a per-RoI homography + bilinear
    sampling. ``features`` (H, W, C); ``rois`` (R, 8) quad corners
    (x1,y1,...,x4,y4) in clockwise order starting top-left, image
    coords. Differentiable w.r.t. features AND roi corners."""
    oh, ow = output_size

    def homography(quad):
        """Solve the 8-dof projective map sending the output rect's
        corners (0,0),(ow-1,0),(ow-1,oh-1),(0,oh-1) to the quad."""
        src = jnp.asarray([[0.0, 0.0], [ow - 1.0, 0.0],
                           [ow - 1.0, oh - 1.0], [0.0, oh - 1.0]])
        dst = quad.reshape(4, 2)
        rows = []
        rhs = []
        for k in range(4):
            sx, sy = src[k, 0], src[k, 1]
            dx, dy = dst[k, 0], dst[k, 1]
            rows.append(jnp.stack([sx, sy, 1.0, 0.0, 0.0, 0.0,
                                   -sx * dx, -sy * dx]))
            rows.append(jnp.stack([0.0, 0.0, 0.0, sx, sy, 1.0,
                                   -sx * dy, -sy * dy]))
            rhs.extend([dx, dy])
        A = jnp.stack(rows)
        b = jnp.stack(rhs)
        # Tikhonov guard: predicted quads can degenerate (collinear /
        # repeated corners) making A singular — a NaN here would poison
        # the whole loss; the epsilon is invisible for valid quads
        A = A + 1e-6 * jnp.eye(8)
        h = jnp.linalg.solve(A, b)
        return jnp.concatenate([h, jnp.ones((1,))]).reshape(3, 3)

    gy, gx = jnp.meshgrid(jnp.arange(oh, dtype=jnp.float32),
                          jnp.arange(ow, dtype=jnp.float32),
                          indexing="ij")
    ones = jnp.ones_like(gx)
    grid = jnp.stack([gx, gy, ones], axis=-1)         # (oh, ow, 3)

    def one(quad):
        H = homography(quad * spatial_scale)
        mapped = grid @ H.T                            # (oh, ow, 3)
        xs = mapped[..., 0] / jnp.maximum(jnp.abs(mapped[..., 2]),
                                          1e-8) * jnp.sign(mapped[..., 2])
        ys = mapped[..., 1] / jnp.maximum(jnp.abs(mapped[..., 2]),
                                          1e-8) * jnp.sign(mapped[..., 2])
        return _bilinear_sample(features, ys, xs)

    return jax.vmap(one)(rois)


@register_op("generate_mask_labels")
def generate_mask_labels(rois, match_gt, fg_mask, gt_masks, *,
                         resolution=14, im_size):
    """Mask-RCNN mask targets (generate_mask_labels_op.cc): for each
    foreground RoI, crop its matched ground-truth instance mask to the
    RoI window and resample to (resolution, resolution), thresholded to
    {0, 1}. The reference rasterizes COCO polygons then crops; here the
    gt arrives as binary masks (G, Hm, Wm) at image scale (the
    rasterization lives in the data pipeline).

    rois (R, 4) pixel xyxy; match_gt (R,) gt index per roi; fg_mask (R,)
    marks rois that get mask supervision. Returns (targets (R, res, res)
    float 0/1 — zero rows for non-fg, weights (R,))."""
    _, mh, mw = gt_masks.shape
    if mh != mw:
        # roi_align has one spatial_scale; anisotropic rasters would
        # sample the x axis wrongly — rescale rois per-axis instead
        raise ValueError(
            f"gt_masks must be square rasters, got {(mh, mw)}; "
            "resample masks (or store at image aspect) upstream")
    scale = mh / im_size

    def one(roi, gi, fg):
        m = gt_masks[gi][:, :, None].astype(jnp.float32)   # (Hm, Wm, 1)
        patch = roi_align(m, roi[None],
                          output_size=(resolution, resolution),
                          spatial_scale=scale)[0, :, :, 0]
        return jnp.where(fg, (patch >= 0.5).astype(jnp.float32),
                         jnp.zeros_like(patch))

    targets = jax.vmap(one)(rois, jnp.maximum(match_gt, 0), fg_mask)
    return targets, fg_mask.astype(jnp.float32)


@register_op("deformable_roi_pooling")
def deformable_roi_pooling(features, rois, offsets=None, *,
                           output_size=(7, 7), spatial_scale=1.0,
                           gamma=0.1):
    """Deformable RoI pooling (deformable_roi_pooling_op, Deformable
    ConvNets): RoIAlign where each output bin's sampling center shifts by
    a learned normalized offset, scaled by ``gamma`` and the RoI size.
    ``features`` (H, W, C); ``rois`` (R, 4) xyxy; ``offsets``
    (R, oh, ow, 2) [dy, dx] normalized (None = plain aligned pooling).
    Differentiable w.r.t. features, rois AND offsets."""
    oh, ow = output_size

    def one(roi, off):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bw = rw / ow
        bh = rh / oh
        cy = y1 + (jnp.arange(oh) + 0.5) * bh                 # (oh,)
        cx = x1 + (jnp.arange(ow) + 0.5) * bw                 # (ow,)
        gy = jnp.broadcast_to(cy[:, None], (oh, ow))
        gx = jnp.broadcast_to(cx[None, :], (oh, ow))
        if off is not None:
            gy = gy + gamma * rh * off[..., 0]
            gx = gx + gamma * rw * off[..., 1]
        return _bilinear_sample(features, gy, gx)             # (oh,ow,C)

    if offsets is None:
        return jax.vmap(lambda r: one(r, None))(rois)
    return jax.vmap(one)(rois, offsets)
