"""Detection ops (CV): the reference's ``operators/detection/`` surface
(59 files, 15.4k LoC — SURVEY.md §2.3) re-emitted as jittable XLA ops.

Implemented (the load-bearing subset used by the PaddleCV detection
models): box IoU, box coding (encode/decode), prior_box (SSD anchors),
yolo_box (YOLOv3 head decode), multiclass/hard NMS (static-shape, mask
based — XLA-compatible: returns fixed-size top-k with validity mask),
roi_align. Remaining long-tail ops (matrix_nms, density_prior_box, …)
follow the same patterns.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.registry import register_op


@register_op("iou_similarity")
def box_iou(boxes1, boxes2):
    """IoU matrix: boxes (N,4),(M,4) xyxy -> (N,M)."""
    area1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    area2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    lt = jnp.maximum(boxes1[:, None, :2], boxes2[None, :, :2])
    rb = jnp.minimum(boxes1[:, None, 2:], boxes2[None, :, 2:])
    wh = jnp.clip(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area1[:, None] + area2[None, :] - inter,
                               1e-10)


@register_op("box_coder")
def box_encode(boxes, anchors, variances=(0.1, 0.1, 0.2, 0.2)):
    """encode_center_size (box_coder_op): gt xyxy vs anchor xyxy -> deltas."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    bw = boxes[:, 2] - boxes[:, 0]
    bh = boxes[:, 3] - boxes[:, 1]
    bx = boxes[:, 0] + 0.5 * bw
    by = boxes[:, 1] + 0.5 * bh
    v = jnp.asarray(variances)
    return jnp.stack([
        (bx - ax) / aw / v[0], (by - ay) / ah / v[1],
        jnp.log(jnp.maximum(bw / aw, 1e-10)) / v[2],
        jnp.log(jnp.maximum(bh / ah, 1e-10)) / v[3]], axis=-1)


def box_decode(deltas, anchors, variances=(0.1, 0.1, 0.2, 0.2)):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = anchors[:, 0] + 0.5 * aw
    ay = anchors[:, 1] + 0.5 * ah
    v = jnp.asarray(variances)
    cx = deltas[:, 0] * v[0] * aw + ax
    cy = deltas[:, 1] * v[1] * ah + ay
    w = jnp.exp(deltas[:, 2] * v[2]) * aw
    h = jnp.exp(deltas[:, 3] * v[3]) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)


@register_op("prior_box")
def prior_box(feature_h, feature_w, image_h, image_w, min_sizes,
              max_sizes=(), aspect_ratios=(1.0,), step=None, offset=0.5,
              clip=True):
    """SSD anchors for one feature map (prior_box_op). Returns (H*W*A, 4)
    normalized xyxy."""
    step_h = step or image_h / feature_h
    step_w = step or image_w / feature_w
    cy = (jnp.arange(feature_h) + offset) * step_h
    cx = (jnp.arange(feature_w) + offset) * step_w
    cx, cy = jnp.meshgrid(cx, cy)  # (H, W)

    whs = []
    for ms in min_sizes:
        whs.append((ms, ms))
        for ar in aspect_ratios:
            if abs(ar - 1.0) < 1e-6:
                continue
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
    for ms, Ms in zip(min_sizes, max_sizes):
        whs.append(((ms * Ms) ** 0.5,) * 2)
    whs = jnp.asarray(whs)  # (A, 2)

    centers = jnp.stack([cx, cy], -1).reshape(-1, 1, 2)       # (HW, 1, 2)
    half = whs[None, :, :] / 2.0                              # (1, A, 2)
    boxes = jnp.concatenate([centers - half, centers + half], -1)
    boxes = boxes.reshape(-1, 4) / jnp.asarray(
        [image_w, image_h, image_w, image_h], jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register_op("yolo_box")
def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, scale_x_y=1.0):
    """Decode a YOLOv3 head (yolo_box_op). x: (B, A*(5+C), H, W) NCHW like
    the reference; anchors: [(w,h), ...] in pixels. Returns (boxes
    (B, H*W*A, 4) xyxy in image pixels, scores (B, H*W*A, C))."""
    b, _, h, w = x.shape
    a = len(anchors)
    c = class_num
    x = x.reshape(b, a, 5 + c, h, w).transpose(0, 3, 4, 1, 2)  # (B,H,W,A,5+C)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, :, None]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, :, None, None]
    anchors = jnp.asarray(anchors, jnp.float32)  # (A, 2)

    bias = 0.5 * (scale_x_y - 1.0)
    cx = (jax.nn.sigmoid(x[..., 0]) * scale_x_y - bias + grid_x) / w
    cy = (jax.nn.sigmoid(x[..., 1]) * scale_x_y - bias + grid_y) / h
    bw = jnp.exp(x[..., 2]) * anchors[None, None, None, :, 0] \
        / (downsample_ratio * w)
    bh = jnp.exp(x[..., 3]) * anchors[None, None, None, :, 1] \
        / (downsample_ratio * h)
    conf = jax.nn.sigmoid(x[..., 4])
    probs = jax.nn.sigmoid(x[..., 5:]) * conf[..., None]
    probs = jnp.where(conf[..., None] >= conf_thresh, probs, 0.0)

    img_wh = img_size[:, None, ::-1].astype(jnp.float32)       # (B,1,2) w,h
    boxes = jnp.stack([cx - bw / 2, cy - bh / 2,
                       cx + bw / 2, cy + bh / 2], -1)
    boxes = boxes.reshape(b, -1, 4) * jnp.tile(img_wh, (1, 1, 2))
    return boxes, probs.reshape(b, -1, c)


@register_op("nms")
def nms(boxes, scores, *, iou_threshold=0.5, score_threshold=0.0,
        max_outputs=100):
    """Static-shape greedy NMS. boxes (N,4), scores (N,). Returns
    (indices (max_outputs,), valid (max_outputs,) bool) — XLA-compatible
    fixed shapes (the reference's multiclass_nms returns a LoD tensor;
    here validity masks carry the dynamic count)."""
    n = boxes.shape[0]
    iou = box_iou(boxes, boxes)
    order_scores = jnp.where(scores >= score_threshold, scores, -jnp.inf)

    def body(carry, _):
        avail_scores, = carry
        idx = jnp.argmax(avail_scores)
        best = avail_scores[idx]
        valid = best > -jnp.inf
        # suppress overlapping + the chosen one
        suppress = (iou[idx] >= iou_threshold) | (
            jnp.arange(n) == idx)
        avail_scores = jnp.where(valid & suppress, -jnp.inf, avail_scores)
        return (avail_scores,), (jnp.where(valid, idx, 0), valid)

    _, (idxs, valid) = jax.lax.scan(
        body, (order_scores,), None, length=min(max_outputs, n))
    pad = max_outputs - idxs.shape[0]
    if pad > 0:
        idxs = jnp.concatenate([idxs, jnp.zeros((pad,), idxs.dtype)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    return idxs, valid


@register_op("multiclass_nms")
def multiclass_nms(boxes, scores, *, iou_threshold=0.45,
                   score_threshold=0.01, max_per_class=100):
    """Per-class NMS (multiclass_nms_op). boxes (N,4), scores (N,C).
    Returns (cls_ids, indices, valid) each (C*max_per_class,)."""
    c = scores.shape[1]
    f = functools.partial(nms, iou_threshold=iou_threshold,
                          score_threshold=score_threshold,
                          max_outputs=max_per_class)
    idxs, valid = jax.vmap(lambda s: f(boxes, s), in_axes=1)(scores)
    cls_ids = jnp.repeat(jnp.arange(c), max_per_class)
    return cls_ids, idxs.reshape(-1), valid.reshape(-1)


@register_op("box_clip")
def box_clip(boxes, im_shape):
    """Clip xyxy boxes into the image (box_clip_op). boxes (..., 4);
    im_shape (2,) = (h, w) or (..., 2) broadcastable."""
    im_shape = jnp.asarray(im_shape, boxes.dtype)
    h = im_shape[..., 0:1]
    w = im_shape[..., 1:2]
    x1 = jnp.clip(boxes[..., 0:1], 0.0, w - 1)
    y1 = jnp.clip(boxes[..., 1:2], 0.0, h - 1)
    x2 = jnp.clip(boxes[..., 2:3], 0.0, w - 1)
    y2 = jnp.clip(boxes[..., 3:4], 0.0, h - 1)
    return jnp.concatenate([x1, y1, x2, y2], axis=-1)


@register_op("matrix_nms")
def matrix_nms(boxes, scores, *, score_threshold=0.05, post_threshold=0.0,
               nms_top_k=400, keep_top_k=100, use_gaussian=False,
               gaussian_sigma=2.0):
    """Matrix NMS (matrix_nms_op, SOLOv2): fully parallel soft-NMS — each
    box's score decays by its worst overlap with any HIGHER-scored box,
    compensated by how suppressed that box itself is. No sequential loop:
    one (K, K) IoU matrix + reductions, the XLA/MXU-friendly NMS.

    boxes (N,4), scores (N,). Returns (indices (keep_top_k,), new_scores,
    valid) — fixed shapes, validity-masked like :func:`nms`.
    """
    n = boxes.shape[0]
    k = min(nms_top_k, n)
    top_scores, order = jax.lax.top_k(
        jnp.where(scores >= score_threshold, scores, -jnp.inf), k)
    cand = boxes[order]                                    # (K, 4)
    iou = box_iou(cand, cand)                              # (K, K)
    # pairwise IoU with strictly higher-scored boxes only (upper triangle)
    higher = jnp.triu(jnp.ones((k, k), bool), 1)           # j < i in score
    iou_h = jnp.where(higher.T, iou, 0.0)                  # (i, j): j higher
    # compensation: how suppressed the suppressor itself is
    comp = iou_h.max(axis=1)                               # per-box
    comp_j = comp[None, :]
    if use_gaussian:
        decay = jnp.exp(-(iou_h ** 2 - comp_j ** 2) / gaussian_sigma)
    else:
        decay = (1.0 - iou_h) / jnp.maximum(1.0 - comp_j, 1e-10)
    decay = jnp.where(iou_h > 0.0, decay, 1.0).min(axis=1)
    new_scores = jnp.where(jnp.isfinite(top_scores),
                           top_scores * decay, -jnp.inf)
    new_scores = jnp.where(new_scores >= post_threshold, new_scores,
                           -jnp.inf)
    kk = min(keep_top_k, k)
    kept_scores, kept = jax.lax.top_k(new_scores, kk)
    idxs = order[kept]
    valid = jnp.isfinite(kept_scores)
    pad = keep_top_k - kk
    if pad > 0:
        idxs = jnp.concatenate([idxs, jnp.zeros((pad,), idxs.dtype)])
        kept_scores = jnp.concatenate(
            [kept_scores, jnp.full((pad,), -jnp.inf)])
        valid = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    return idxs, jnp.where(valid, kept_scores, 0.0), valid


@register_op("density_prior_box")
def density_prior_box(feature_h, feature_w, image_h, image_w, *,
                      fixed_sizes, fixed_ratios=(1.0,), densities=(1,),
                      step=None, offset=0.5, clip=True):
    """Density prior boxes (density_prior_box_op, PyramidBox face
    detection): each (fixed_size, density) pair tiles density^2 shifted
    anchor centers per cell. Returns (H*W*A, 4) normalized xyxy with
    A = sum(d^2) * len(fixed_ratios)."""
    if len(fixed_sizes) != len(densities):
        raise ValueError(
            f"fixed_sizes ({len(fixed_sizes)}) and densities "
            f"({len(densities)}) must pair up one-to-one")
    step_h = step or image_h / feature_h
    step_w = step or image_w / feature_w
    cy0 = (jnp.arange(feature_h) + offset) * step_h
    cx0 = (jnp.arange(feature_w) + offset) * step_w
    cx0, cy0 = jnp.meshgrid(cx0, cy0)            # (H, W)

    rows = []
    # reference (density_prior_box_op.h:96) TRUNCATES the averaged step
    # and the per-density shift to int — match exactly
    step_avg = int((step_h + step_w) * 0.5)
    for size, density in zip(fixed_sizes, densities):
        shift = int(step_avg / density)
        for ratio in fixed_ratios:
            w = size * (ratio ** 0.5)
            h = size / (ratio ** 0.5)
            for di in range(density):
                for dj in range(density):
                    ox = (dj + 0.5) * shift - step_avg / 2.0
                    oy = (di + 0.5) * shift - step_avg / 2.0
                    rows.append((ox, oy, w, h))
    offs = jnp.asarray(rows, jnp.float32)        # (A, 4): ox, oy, w, h

    centers = jnp.stack([cx0, cy0], -1).reshape(-1, 1, 2)   # (HW, 1, 2)
    ctr = centers + offs[None, :, :2]
    half = offs[None, :, 2:] / 2.0
    boxes = jnp.concatenate([ctr - half, ctr + half], -1).reshape(-1, 4)
    boxes = boxes / jnp.asarray([image_w, image_h, image_w, image_h],
                                jnp.float32)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


@register_op("anchor_generator")
def anchor_generator(feature_h, feature_w, *, anchor_sizes=(64, 128, 256),
                     aspect_ratios=(0.5, 1.0, 2.0), stride=(16.0, 16.0),
                     offset=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    """RPN anchors for one feature map (anchor_generator_op). Unlike
    prior_box (SSD, normalized coords), returns PIXEL-coordinate xyxy
    anchors (H*W*A, 4) plus the broadcast variances (H*W*A, 4)."""
    sh, sw = stride
    cy = (jnp.arange(feature_h, dtype=jnp.float32) + offset) * sh
    cx = (jnp.arange(feature_w, dtype=jnp.float32) + offset) * sw
    cx, cy = jnp.meshgrid(cx, cy)                             # (H, W)

    whs = []
    for size in anchor_sizes:
        area = float(size) ** 2
        for ar in aspect_ratios:
            w = (area / ar) ** 0.5
            whs.append((w, w * ar))
    whs = jnp.asarray(whs, jnp.float32)                       # (A, 2)

    centers = jnp.stack([cx, cy], -1).reshape(-1, 1, 2)       # (HW, 1, 2)
    half = whs[None, :, :] / 2.0
    anchors = jnp.concatenate([centers - half, centers + half],
                              -1).reshape(-1, 4)
    var = jnp.broadcast_to(jnp.asarray(variances, jnp.float32),
                           (anchors.shape[0], 4))
    return anchors, var


@register_op("roi_pool")
def roi_pool(features, rois, *, output_size=(7, 7), spatial_scale=1.0):
    """ROI max pooling (roi_pool_op — the quantized Fast-RCNN pooling;
    roi_align below is the interpolated successor). features (H, W, C);
    rois (R, 4) xyxy image coords. Returns (R, oh, ow, C)."""
    h, w, c = features.shape
    oh, ow = output_size
    ys = jnp.arange(h, dtype=jnp.float32)
    xs = jnp.arange(w, dtype=jnp.float32)
    neg = jnp.finfo(features.dtype).min

    def one_roi(roi):
        x1, y1, x2, y2 = jnp.round(roi * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)

        def one_bin(by, bx):
            # quantized bin bounds (floor/ceil like the reference)
            y_lo = y1 + jnp.floor(by * rh / oh)
            y_hi = y1 + jnp.ceil((by + 1) * rh / oh)
            x_lo = x1 + jnp.floor(bx * rw / ow)
            x_hi = x1 + jnp.ceil((bx + 1) * rw / ow)
            in_y = (ys >= y_lo) & (ys < y_hi)
            in_x = (xs >= x_lo) & (xs < x_hi)
            m = in_y[:, None] & in_x[None, :]
            masked = jnp.where(m[..., None], features, neg)
            out = masked.max(axis=(0, 1))
            return jnp.where(m.any(), out, 0.0)               # empty bin -> 0

        by = jnp.arange(oh)
        bx = jnp.arange(ow)
        return jax.vmap(lambda y: jax.vmap(
            lambda x: one_bin(y, x))(bx))(by)                 # (oh, ow, C)

    return jax.vmap(one_roi)(rois)


@register_op("roi_align")
def roi_align(features, rois, *, output_size=(7, 7), spatial_scale=1.0,
              sampling_ratio=2):
    """ROIAlign (roi_align_op). features (H, W, C) single image NHWC slice;
    rois (R, 4) xyxy in image coords. Returns (R, oh, ow, C)."""
    h, w, _ = features.shape
    oh, ow = output_size

    def one_roi(roi):
        x1, y1, x2, y2 = roi * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / ow
        bin_h = rh / oh
        # sampling_ratio x sampling_ratio bilinear samples per bin
        sr = sampling_ratio
        ys = y1 + (jnp.arange(oh * sr) + 0.5) * bin_h / sr
        xs = x1 + (jnp.arange(ow * sr) + 0.5) * bin_w / sr

        def bilinear(y, x):
            y = jnp.clip(y, 0.0, h - 1.0)
            x = jnp.clip(x, 0.0, w - 1.0)
            y0 = jnp.floor(y).astype(jnp.int32)
            x0 = jnp.floor(x).astype(jnp.int32)
            y1_ = jnp.minimum(y0 + 1, h - 1)
            x1_ = jnp.minimum(x0 + 1, w - 1)
            wy = y - y0
            wx = x - x0
            return (features[y0, x0] * (1 - wy) * (1 - wx)
                    + features[y0, x1_] * (1 - wy) * wx
                    + features[y1_, x0] * wy * (1 - wx)
                    + features[y1_, x1_] * wy * wx)

        samples = jax.vmap(lambda y: jax.vmap(
            lambda x: bilinear(y, x))(xs))(ys)      # (oh*sr, ow*sr, C)
        samples = samples.reshape(oh, sr, ow, sr, -1)
        return samples.mean(axis=(1, 3))

    return jax.vmap(one_roi)(rois)
