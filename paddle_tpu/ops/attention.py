"""Attention ops: XLA-composed SDPA + Pallas flash-attention TPU kernel.

Reference mapping: the reference has no fused attention — attention exists
only as composed ops (mul/matmul + softmax + dropout) inside models and the
``operators/fused/`` kernel fusions (SURVEY.md §2.3, §5.7). On TPU the hot
path is a Pallas flash-attention kernel (online softmax, O(S) memory, MXU
tiled) — the analog of the reference's ``fused/`` op family, designed for
the MXU rather than translated.

Layout convention: (batch, num_heads, seq, head_dim) — "BHSD".

Dispatch: :func:`dot_product_attention` picks the Pallas kernel on TPU and
the XLA-composed path elsewhere (CPU tests run the kernel in interpret
mode). The Pallas forward carries a custom_vjp whose backward recomputes
attention with the XLA path — correct grads, flash-speed forward; a full
Pallas backward is a perf follow-up.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas backend; present in jax>=0.4 installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free
                 # for fully-masked rows (padded queries)


# ---------------------------------------------------------------------------
# XLA-composed reference path
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(q, k, v, *, bias=None, causal=False,
                                 scale: Optional[float] = None,
                                 dropout_rate: float = 0.0,
                                 dropout_key=None):
    """Composed attention in fp32 softmax. q,k,v: (B, H, S, D).

    ``bias`` is additive, broadcastable to (B, H, Sq, Sk) (use NEG_INF for
    masked positions). ``causal`` adds a lower-triangular mask.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row + (sk - sq), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def make_padding_bias(pad_mask, dtype=jnp.float32):
    """(B, Sk) bool valid-mask -> additive bias (B, 1, 1, Sk)."""
    return jnp.where(pad_mask, 0.0, NEG_INF).astype(dtype)[:, None, None, :]


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref,
                      m_scr, l_scr, acc_scr, *,
                      scale, causal, block_q, block_k, seq_q, seq_k):
    """Grid (BH, nq, nk); online-softmax accumulation over kv blocks.

    Scratch: m (bq,128) running max, l (bq,128) running denom (values
    broadcast across lanes), acc (bq, D) fp32 accumulator.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32)           # (bq, D)
        k = k_ref[0].astype(jnp.float32)           # (bk, D)
        # zero padded kv rows (pallas pads out-of-bounds blocks with
        # garbage/NaN; 0*NaN would poison the p@v contraction)
        kv_valid = (ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < seq_k
        k = jnp.where(kv_valid, k, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            s = jnp.where(col <= row + (seq_k - seq_q), s, NEG_INF)
        # mask out padding blocks past the true seq end (grid is padded up)
        s = jnp.where(col < seq_k, s, NEG_INF)

        m_prev = m_scr[...]                        # (bq, 128)
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_next = jnp.maximum(m_prev, m_cur)        # broadcast over lanes
        alpha = jnp.exp(m_prev - m_next)           # (bq, 128)
        p = jnp.exp(s - m_next[:, :1])             # (bq, bk)
        l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        l_scr[...] = l_next
        v = jnp.where(kv_valid, v_ref[0].astype(jnp.float32), 0.0)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, D)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + pv

    if causal:
        # skip kv blocks fully above the diagonal
        below = ki * block_k <= qi * block_q + (block_q - 1) + (seq_k - seq_q)
        pl.when(below)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)  # fully-masked rows -> 0
        o_ref[0] = (acc_scr[...] / denom).astype(o_ref.dtype)


def _flash_fwd(q, k, v, bias, *, scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(sk, bk)
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)

    in_specs = [
        pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
        pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
        pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        # key-only bias (B,1,1,Sk) or (1,1,1,Sk): broadcast rows over bq
        bias = jnp.broadcast_to(bias, (b, h, sq, sk)) \
            if bias.shape[2] not in (1,) else bias
        if bias.shape[2] == 1:
            br = jnp.broadcast_to(bias, (b, h, 1, sk)).reshape(bh, 1, sk)
            br = jnp.broadcast_to(br[:, 0:1, :], (bh, 8, sk))  # sublane pad
            in_specs.append(
                pl.BlockSpec((1, 8, bk), lambda g, i, j: (g, 0, j)))
            # kernel reads bias_ref[0] of shape (8, bk); slice row 0
            args.append(br)
            bias_mode = "key"
        else:
            br = bias.reshape(bh, sq, sk)
            in_specs.append(
                pl.BlockSpec((1, bq, bk), lambda g, i, j: (g, i, j)))
            args.append(br)
            bias_mode = "full"
    else:
        bias_mode = None

    kernel = functools.partial(
        _flash_kernel_dispatch, bias_mode=bias_mode, scale=scale,
        causal=causal, block_q=bq, block_k=bk, seq_q=sq, seq_k=sk)

    scratch = [
        pltpu.VMEM((bq, 128), jnp.float32),
        pltpu.VMEM((bq, 128), jnp.float32),
        pltpu.VMEM((bq, d), jnp.float32),
    ] if pltpu is not None else [
        pl.ANY  # pragma: no cover
    ]
    grid = (bh, nq, nk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if (pltpu is not None and not interpret) else None,
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, sq, d)


def _flash_kernel_dispatch(*refs, bias_mode, **kw):
    if bias_mode is None:
        q_ref, k_ref, v_ref, o_ref, m, l, acc = refs
        _flash_fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, m, l, acc, **kw)
    elif bias_mode == "key":
        q_ref, k_ref, v_ref, b_ref, o_ref, m, l, acc = refs
        _flash_fwd_kernel(q_ref, k_ref, v_ref, _KeyBias(b_ref), o_ref,
                          m, l, acc, **kw)
    else:
        q_ref, k_ref, v_ref, b_ref, o_ref, m, l, acc = refs
        _flash_fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, m, l, acc, **kw)


class _KeyBias:
    """Adapts a (1, 8, bk) key-bias block to the (bq, bk) read the kernel
    does: row 0 broadcast over queries."""

    def __init__(self, ref):
        self._ref = ref

    def __getitem__(self, idx):
        return self._ref[0][0:1, :]  # (1, bk), broadcasts against (bq, bk)

    def astype(self, dt):  # pragma: no cover - not used
        raise TypeError


# ---------------------------------------------------------------------------
# public flash_attention with custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, causal=False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False):
    """Flash attention (Pallas fwd). q,k,v: (B,H,S,D); bias additive,
    broadcastable to (B,H,Sq,Sk). Backward recomputes via the XLA path."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if bias is not None and bias.ndim < 4:  # accept broadcastable ranks
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    return _flash_fwd(q, k, v, bias, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


def _flash_vjp_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    out = flash_attention(q, k, v, bias, causal, scale, block_q, block_k,
                          interpret)
    return out, (q, k, v, bias)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, bias = res

    def ref(q, k, v, bias):
        return scaled_dot_product_attention(q, k, v, bias=bias, causal=causal,
                                            scale=scale)

    _, vjp = jax.vjp(ref, q, k, v, bias)
    return vjp(g)


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except RuntimeError:  # pragma: no cover
        return False


def dot_product_attention(q, k, v, *, bias=None, causal=False,
                          scale=None, dropout_rate=0.0, dropout_key=None,
                          impl: str = "auto"):
    """Attention entry point used by nn layers.

    impl: "auto" (flash on TPU, xla elsewhere), "flash", "xla",
    "flash_interpret" (tests).
    """
    if impl == "auto":
        impl = "flash" if (_on_tpu() and dropout_rate == 0.0) else "xla"
    if impl == "xla" or dropout_rate > 0.0:
        return scaled_dot_product_attention(
            q, k, v, bias=bias, causal=causal, scale=scale,
            dropout_rate=dropout_rate, dropout_key=dropout_key)
    interpret = impl == "flash_interpret"
    return flash_attention(q, k, v, bias, causal, scale, 512, 512, interpret)
