"""Attention ops: XLA-composed SDPA + Pallas flash-attention TPU kernel.

Reference mapping: the reference has no fused attention — attention exists
only as composed ops (mul/matmul + softmax + dropout) inside models and the
``operators/fused/`` kernel fusions (SURVEY.md §2.3, §5.7). On TPU the hot
path is a Pallas flash-attention kernel (online softmax, O(S) memory, MXU
tiled) — the analog of the reference's ``fused/`` op family, designed for
the MXU rather than translated.

Layout convention: (batch, num_heads, seq, head_dim) — "BHSD".

Dispatch: :func:`dot_product_attention` picks the Pallas kernel on TPU and
the XLA-composed path elsewhere (CPU tests run the kernel in interpret
mode). Both forward and backward are Pallas kernels (FlashAttention-2
style: the backward recomputes p from the forward's logsumexp in two
kernels, dkv and dq); full (Sq,Sk) biases fall back to the XLA backward so
trainable position biases get gradients.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # TPU-specific pallas backend; present in jax>=0.4 installs
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None


NEG_INF = -1e30  # large-negative instead of -inf: keeps softmax NaN-free
                 # for fully-masked rows (padded queries)


# ---------------------------------------------------------------------------
# XLA-composed reference path
# ---------------------------------------------------------------------------

def scaled_dot_product_attention(q, k, v, *, bias=None, causal=False,
                                 scale: Optional[float] = None,
                                 dropout_rate: float = 0.0,
                                 dropout_key=None):
    """Composed attention in fp32 softmax. q,k,v: (B, H, S, D).

    ``bias`` is additive, broadcastable to (B, H, Sq, Sk) (use NEG_INF for
    masked positions). ``causal`` adds a lower-triangular mask.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row + (sk - sq), s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # fully-masked rows (every key at NEG_INF): emit 0, not the uniform mean
    # of v — keeps this path consistent with the Pallas flash kernel
    alive = jnp.max(s, axis=-1, keepdims=True) > NEG_INF / 2
    p = jnp.where(alive, p, 0.0)
    if dropout_rate > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def make_padding_bias(pad_mask, dtype=jnp.float32):
    """(B, Sk) bool valid-mask -> additive bias (B, 1, 1, Sk)."""
    return jnp.where(pad_mask, 0.0, NEG_INF).astype(dtype)[:, None, None, :]


# ---------------------------------------------------------------------------
# lax fallback with flash-kernel semantics (the shared-harness fallback)
# ---------------------------------------------------------------------------

def _masked_scores(q, k, bias, *, scale, causal):
    """fp32 score block with the SAME masking the Pallas kernel applies."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias.astype(s.dtype)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        row = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where(col <= row + (sk - sq), s, NEG_INF)
    return s


def _lax_flash_fwd(q, k, v, bias=None, *, scale=None, causal=False,
                   return_lse=False):
    """XLA-composed forward with the flash kernel's exact conventions:
    fully-masked rows emit 0 (not a uniform mean of v) and, with
    ``return_lse``, a ~NEG_INF logsumexp — so ring attention's
    streaming logaddexp merge works identically on the fallback path.
    This is the registered lax fallback of the ``flash_attention``
    kernel (:mod:`paddle_tpu.kernels`)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if bias is not None and bias.ndim < 4:
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    s = _masked_scores(q, k, bias, scale=scale, causal=causal)
    m = jnp.max(s, axis=-1)                         # (B,H,Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    denom = jnp.where(l == 0.0, 1.0, l)
    alive = m > NEG_INF / 2
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    out = jnp.where(alive[..., None], out / denom[..., None], 0.0)
    out = out.astype(q.dtype)
    if return_lse:
        return out, m + jnp.log(denom)              # dead rows: ~NEG_INF
    return out


def _lax_flash_block_bwd(q, k, v, bias, out, lse, g, *, scale, causal):
    """XLA-composed FlashAttention-2 block backward against a GLOBAL
    logsumexp: recompute p = exp(s - lse), then ds = p(dp - delta)scale.
    Mirrors :func:`_flash_bwd`'s two Pallas kernels, so ring attention's
    backward merge is backend-independent (grads accumulate across ring
    blocks against the merged forward's lse on either path)."""
    s = _masked_scores(q, k, bias, scale=scale, causal=causal)
    p = jnp.exp(s - lse[..., None])
    # fully-masked rows: lse ~ NEG_INF would turn exp into garbage ones
    p = jnp.where(lse[..., None] <= NEG_INF / 2, 0.0, p)
    g32 = g.astype(jnp.float32)
    delta = jnp.sum(g32 * out.astype(jnp.float32), axis=-1)   # (B,H,Sq)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32))
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Pallas flash-attention forward kernel
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref,
                      m_scr, l_scr, acc_scr, *,
                      scale, causal, block_q, block_k, seq_q, seq_k):
    """Grid (BH, nq, nk); online-softmax accumulation over kv blocks.

    Scratch: m (bq,128) running max, l (bq,128) running denom (values
    broadcast across lanes), acc (bq, D) fp32 accumulator.
    """
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _body():
        q = q_ref[0].astype(jnp.float32)           # (bq, D)
        k = k_ref[0].astype(jnp.float32)           # (bk, D)
        # zero padded kv rows (pallas pads out-of-bounds blocks with
        # garbage/NaN; 0*NaN would poison the p@v contraction)
        kv_valid = (ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < seq_k
        k = jnp.where(kv_valid, k, 0.0)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        row = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        col = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if causal:
            s = jnp.where(col <= row + (seq_k - seq_q), s, NEG_INF)
        # mask out padding blocks past the true seq end (grid is padded up)
        s = jnp.where(col < seq_k, s, NEG_INF)

        m_prev = m_scr[...]                        # (bq, 128)
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (bq, 1)
        m_next = jnp.maximum(m_prev, m_cur)        # broadcast over lanes
        alpha = jnp.exp(m_prev - m_next)           # (bq, 128)
        p = jnp.exp(s - m_next[:, :1])             # (bq, bk)
        l_next = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        m_scr[...] = m_next
        l_scr[...] = l_next
        v = jnp.where(kv_valid, v_ref[0].astype(jnp.float32), 0.0)
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # (bq, D)
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + pv

    if causal:
        # skip kv blocks fully above the diagonal
        below = ki * block_k <= qi * block_q + (block_q - 1) + (seq_k - seq_q)
        pl.when(below)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finish():
        denom = l_scr[...][:, :1]
        denom = jnp.where(denom == 0.0, 1.0, denom)
        # fully-masked rows (every key at NEG_INF bias): m never rises above
        # ~NEG_INF, p=exp(s-m)=1 and the naive result would be a uniform mean
        # of v. Zero them so the forward matches the backward, which drops
        # those rows' cotangents via the same lse <= NEG_INF/2 test.
        alive = m_scr[...][:, :1] > NEG_INF / 2
        o_ref[0] = jnp.where(alive, acc_scr[...] / denom, 0.0).astype(
            o_ref.dtype)
        if lse_ref is not None:  # logsumexp row stats for the backward
            lse_ref[0, 0] = (m_scr[...][:, 0] + jnp.log(denom[:, 0]))


def _prep_bias(bias, b, h, sq, sk):
    """Normalize bias into (mode, array, BlockSpec-args). Key-only biases
    (Sq dim == 1) get a sublane-padded (bh, 8, sk) layout."""
    bh = b * h
    bias = jnp.broadcast_to(bias, (b, h, sq, sk)) \
        if bias.shape[2] not in (1,) else bias
    if bias.shape[2] == 1:
        br = jnp.broadcast_to(bias, (b, h, 1, sk)).reshape(bh, 1, sk)
        br = jnp.broadcast_to(br[:, 0:1, :], (bh, 8, sk))
        return "key", br
    return "full", bias.reshape(bh, sq, sk)


def _flash_fwd(q, k, v, bias, *, scale, causal, block_q, block_k, interpret,
               return_lse=False):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(sk, bk)
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)

    in_specs = [
        pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0)),
        pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
        pl.BlockSpec((1, bk, d), lambda g, i, j: (g, j, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        bias_mode, br = _prep_bias(bias, b, h, sq, sk)
        if bias_mode == "key":
            in_specs.append(
                pl.BlockSpec((1, 8, bk), lambda g, i, j: (g, 0, j)))
        else:
            in_specs.append(
                pl.BlockSpec((1, bq, bk), lambda g, i, j: (g, i, j)))
        args.append(br)
    else:
        bias_mode = None

    kernel = functools.partial(
        _flash_kernel_dispatch, bias_mode=bias_mode, with_lse=return_lse,
        scale=scale, causal=causal, block_q=bq, block_k=bk,
        seq_q=sq, seq_k=sk)

    scratch = [
        pltpu.VMEM((bq, 128), jnp.float32),
        pltpu.VMEM((bq, 128), jnp.float32),
        pltpu.VMEM((bq, d), jnp.float32),
    ]
    out_specs = pl.BlockSpec((1, bq, d), lambda g, i, j: (g, i, 0))
    out_shape = jax.ShapeDtypeStruct((bh, sq, d), q.dtype)
    if return_lse:
        # (bh, 1, sq) layout: TPU needs the sublane dim to equal the full
        # array dim when it is not a multiple of 8
        out_specs = [out_specs,
                     pl.BlockSpec((1, 1, bq), lambda g, i, j: (g, 0, i))]
        out_shape = [out_shape,
                     jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32)]
    grid = (bh, nq, nk)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if (pltpu is not None and not interpret) else None,
        interpret=interpret,
    )(*args)
    if return_lse:
        o, lse = out
        return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)
    return out.reshape(b, h, sq, d)




def _flash_kernel_dispatch(*refs, bias_mode, with_lse, **kw):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    b_ref = None
    if bias_mode is not None:
        b_ref = refs[i]
        i += 1
        if bias_mode == "key":
            b_ref = _KeyBias(b_ref)
    o_ref = refs[i]
    i += 1
    lse_ref = refs[i] if with_lse else None
    if with_lse:
        i += 1
    m, l, acc = refs[i:]
    _flash_fwd_kernel(q_ref, k_ref, v_ref, b_ref, o_ref, lse_ref,
                      m, l, acc, **kw)


class _KeyBias:
    """Adapts a (1, 8, bk) key-bias block to the (bq, bk) read the kernel
    does: row 0 broadcast over queries."""

    def __init__(self, ref):
        self._ref = ref

    def __getitem__(self, idx):
        return self._ref[0][0:1, :]  # (1, bk), broadcasts against (bq, bk)

    def astype(self, dt):  # pragma: no cover - not used
        raise TypeError


# ---------------------------------------------------------------------------
# Pallas flash-attention backward (FlashAttention-2 style two-kernel split)
# ---------------------------------------------------------------------------

def _recompute_p(q, k, bias_blk, lse, ki, qi, *, scale, causal,
                 block_q, block_k, seq_q, seq_k):
    """Recompute the probability block p = exp(s - lse) with the SAME
    masking as the forward (so p matches bit-for-bit up to fp assoc)."""
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
    if bias_blk is not None:
        s = s + bias_blk
    row = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    col = ki * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    if causal:
        s = jnp.where(col <= row + (seq_k - seq_q), s, NEG_INF)
    s = jnp.where(col < seq_k, s, NEG_INF)
    p = jnp.exp(s - lse[:, None])
    # zero padded q rows (their lse/do are garbage)
    p = jnp.where(row < seq_q, p, 0.0)
    # fully-masked rows: lse sits at ~NEG_INF (log-denominator cancelled by
    # fp rounding), so exp(s - lse) would come out 1 per column instead of
    # 1/seq_k — inflating dk/dv for every key by seq_k. Zero such rows.
    p = jnp.where(lse[:, None] <= NEG_INF / 2, 0.0, p)
    return p


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          bias_ref, dk_ref, dv_ref,
                          dk_scr, dv_scr, *,
                          scale, causal, block_q, block_k, seq_q, seq_k):
    """Grid (BH, nk, nq): for a fixed kv block, stream q blocks and
    accumulate dk = sum ds^T q, dv = sum p^T do."""
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _body():
        row_valid = (qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)) < seq_q
        kv_valid = (ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < seq_k
        q = jnp.where(row_valid, q_ref[0].astype(jnp.float32), 0.0)
        k = jnp.where(kv_valid, k_ref[0].astype(jnp.float32), 0.0)
        v = jnp.where(kv_valid, v_ref[0].astype(jnp.float32), 0.0)
        do = jnp.where(row_valid, do_ref[0].astype(jnp.float32), 0.0)
        lse = jnp.where(row_valid[:, 0], lse_ref[0, 0], 0.0)
        delta = jnp.where(row_valid[:, 0], delta_ref[0, 0], 0.0)
        bias_blk = (bias_ref[0].astype(jnp.float32)
                    if bias_ref is not None else None)

        p = _recompute_p(q, k, bias_blk, lse, ki, qi, scale=scale,
                         causal=causal, block_q=block_q, block_k=block_k,
                         seq_q=seq_q, seq_k=seq_k)
        # dv += p^T do   (contract over q rows)
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do v^T ; ds = p * (dp - delta) * scale
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        # dk += ds^T q
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        below = ki * block_k <= qi * block_q + (block_q - 1) + (seq_k - seq_q)
        pl.when(below)(_body)
    else:
        _body()

    @pl.when(qi == nq - 1)
    def _finish():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         bias_ref, dq_ref, dq_scr, *,
                         scale, causal, block_q, block_k, seq_q, seq_k):
    """Grid (BH, nq, nk): for a fixed q block, stream kv blocks and
    accumulate dq = sum ds k."""
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _body():
        row_valid = (qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, 1), 0)) < seq_q
        kv_valid = (ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_k, 1), 0)) < seq_k
        q = jnp.where(row_valid, q_ref[0].astype(jnp.float32), 0.0)
        k = jnp.where(kv_valid, k_ref[0].astype(jnp.float32), 0.0)
        v = jnp.where(kv_valid, v_ref[0].astype(jnp.float32), 0.0)
        do = jnp.where(row_valid, do_ref[0].astype(jnp.float32), 0.0)
        lse = jnp.where(row_valid[:, 0], lse_ref[0, 0], 0.0)
        delta = jnp.where(row_valid[:, 0], delta_ref[0, 0], 0.0)
        bias_blk = (bias_ref[0].astype(jnp.float32)
                    if bias_ref is not None else None)

        p = _recompute_p(q, k, bias_blk, lse, ki, qi, scale=scale,
                         causal=causal, block_q=block_q, block_k=block_k,
                         seq_q=seq_q, seq_k=seq_k)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        below = ki * block_k <= qi * block_q + (block_q - 1) + (seq_k - seq_q)
        pl.when(below)(_body)
    else:
        _body()

    @pl.when(ki == nk - 1)
    def _finish():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _flash_bwd(q, k, v, bias, out, lse, g, *, scale, causal,
               block_q, block_k, interpret):
    """Pallas backward: returns (dq, dk, dv). Bias grads are not computed
    here (callers with trainable biases use the XLA path)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    nq = pl.cdiv(sq, bq)
    nk = pl.cdiv(sk, bk)
    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    dor = g.reshape(bh, sq, d)
    lser = lse.reshape(bh, 1, sq)
    # delta = rowsum(do * o) — cheap elementwise, let XLA fuse it
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, sq)

    bias_mode = None
    bias_args = []
    if bias is not None:
        bias_mode, br = _prep_bias(bias, b, h, sq, sk)
        bias_args = [br]

    def bias_spec(for_dkv):
        if bias_mode == "key":
            return [pl.BlockSpec((1, 8, bk),
                                 (lambda g_, i, j: (g_, 0, i)) if for_dkv
                                 else (lambda g_, i, j: (g_, 0, j)))]
        if bias_mode == "full":
            return [pl.BlockSpec((1, bq, bk),
                                 (lambda g_, i, j: (g_, j, i)) if for_dkv
                                 else (lambda g_, i, j: (g_, i, j)))]
        return []

    common = dict(scale=scale, causal=causal, block_q=bq, block_k=bk,
                  seq_q=sq, seq_k=sk)
    cparams = pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
    ) if (pltpu is not None and not interpret) else None

    # dk/dv: grid (bh, nk, nq) — i = kv block, j = q block
    dkv_kernel = functools.partial(
        _bwd_dispatch, which="dkv", has_bias=bias_mode is not None,
        mode=bias_mode, **common)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g_, i, j: (g_, j, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda g_, i, j: (g_, i, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda g_, i, j: (g_, i, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda g_, i, j: (g_, j, 0)),   # do
            pl.BlockSpec((1, 1, bq), lambda g_, i, j: (g_, 0, j)),   # lse
            pl.BlockSpec((1, 1, bq), lambda g_, i, j: (g_, 0, j)),   # delta
            *bias_spec(for_dkv=True),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda g_, i, j: (g_, i, 0)),
            pl.BlockSpec((1, bk, d), lambda g_, i, j: (g_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), q.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), q.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, d), jnp.float32),
            pltpu.VMEM((bk, d), jnp.float32),
        ],
        compiler_params=cparams,
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta, *bias_args)

    dq_kernel = functools.partial(
        _bwd_dispatch, which="dq", has_bias=bias_mode is not None,
        mode=bias_mode, **common)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda g_, i, j: (g_, i, 0)),   # q
            pl.BlockSpec((1, bk, d), lambda g_, i, j: (g_, j, 0)),   # k
            pl.BlockSpec((1, bk, d), lambda g_, i, j: (g_, j, 0)),   # v
            pl.BlockSpec((1, bq, d), lambda g_, i, j: (g_, i, 0)),   # do
            pl.BlockSpec((1, 1, bq), lambda g_, i, j: (g_, 0, i)),   # lse
            pl.BlockSpec((1, 1, bq), lambda g_, i, j: (g_, 0, i)),   # delta
            *bias_spec(for_dkv=False),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda g_, i, j: (g_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=cparams,
        interpret=interpret,
    )(qr, kr, vr, dor, lser, delta, *bias_args)

    shape4 = (b, h, sq, d)
    return (dq.reshape(shape4), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


def _bwd_dispatch(*refs, which, has_bias, mode, **kw):
    refs = list(refs)
    ins, rest = refs[:6], refs[6:]
    if has_bias:
        b_ref, rest = rest[0], rest[1:]
        if mode == "key":
            b_ref = _KeyBias(b_ref)
    else:
        b_ref = None
    if which == "dkv":
        dk_ref, dv_ref, dk_scr, dv_scr = rest
        _flash_bwd_dkv_kernel(*ins, b_ref, dk_ref, dv_ref, dk_scr, dv_scr,
                              **kw)
    else:
        dq_ref, dq_scr = rest
        _flash_bwd_dq_kernel(*ins, b_ref, dq_ref, dq_scr, **kw)


# ---------------------------------------------------------------------------
# public flash_attention with custom_vjp
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, causal=False,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False):
    """Flash attention (Pallas fwd + bwd). q,k,v: (B,H,S,D); bias additive,
    broadcastable to (B,H,Sq,Sk).

    Backward: FlashAttention-2-style Pallas kernels (dkv + dq, recomputing
    p from the forward's logsumexp). Key-padding biases (Sq dim == 1) are
    treated as constants (zero cotangent); full (Sq,Sk) biases take the
    XLA recompute path so trainable relative-position biases get grads."""
    if pltpu is None:
        raise RuntimeError("Pallas TPU backend unavailable in this jax "
                           "install; use impl='xla'")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if bias is not None and bias.ndim < 4:  # accept broadcastable ranks
        bias = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    return _flash_fwd(q, k, v, bias, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


def _flash_vjp_fwd(q, k, v, bias, causal, scale, block_q, block_k, interpret):
    if pltpu is None:
        raise RuntimeError("Pallas TPU backend unavailable in this jax "
                           "install; use impl='xla'")
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    bias4 = bias
    if bias is not None and bias.ndim < 4:
        bias4 = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    out, lse = _flash_fwd(q, k, v, bias4, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k,
                          interpret=interpret, return_lse=True)
    # save the ORIGINAL bias so its cotangent matches the caller's shape
    return out, (q, k, v, bias, out, lse)


def _flash_vjp_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, bias, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    sq_dim = (bias.shape[-2] if bias is not None and bias.ndim >= 2 else 1)
    if bias is not None and sq_dim != 1:
        # a full (.., Sq, Sk) bias may be trainable (relative-position
        # biases): take the XLA recompute path, which yields its grad in
        # the caller's original bias shape
        def ref(q, k, v, bias):
            return scaled_dot_product_attention(q, k, v, bias=bias,
                                                causal=causal, scale=scale)

        _, vjp = jax.vjp(ref, q, k, v, bias)
        return vjp(g)
    bias4 = bias
    if bias is not None and bias.ndim < 4:
        bias4 = bias.reshape((1,) * (4 - bias.ndim) + bias.shape)
    dq, dk, dv = _flash_bwd(q, k, v, bias4, out, lse, g, scale=scale,
                            causal=causal, block_q=block_q, block_k=block_k,
                            interpret=interpret)
    dbias = jnp.zeros_like(bias) if bias is not None else None
    return dq, dk, dv, dbias


flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------

def _on_tpu() -> bool:
    from paddle_tpu.kernels import harness
    return harness.on_tpu()


def dot_product_attention(q, k, v, *, bias=None, causal=False,
                          scale=None, dropout_rate=0.0, dropout_key=None,
                          impl: str = "auto"):
    """Attention entry point used by nn layers.

    impl: "auto" (flash on TPU, xla elsewhere), "flash", "xla",
    "flash_interpret" (tests). The flash impls dispatch through the
    shared kernel registry (:mod:`paddle_tpu.kernels`): block sizes
    resolve from the autotuner cache at trace time.
    """
    if impl == "auto":
        impl = "flash" if (pltpu is not None and _on_tpu()
                           and dropout_rate == 0.0) else "xla"
    if impl == "xla" or dropout_rate > 0.0:
        return scaled_dot_product_attention(
            q, k, v, bias=bias, causal=causal, scale=scale,
            dropout_rate=dropout_rate, dropout_key=dropout_key)
    from paddle_tpu import kernels
    return kernels.dispatch(
        "flash_attention", q, k, v, bias,
        impl="pallas_interpret" if impl == "flash_interpret" else "pallas",
        causal=causal, scale=scale)


# ---------------------------------------------------------------------------
# kernel-registry entry (paddle_tpu.kernels)
# ---------------------------------------------------------------------------

def _flash_kernel_pallas(q, k, v, bias=None, *, block_sizes, interpret,
                         causal=False, scale=None):
    return flash_attention(q, k, v, bias, causal, scale,
                           block_sizes.get("block_q", 512),
                           block_sizes.get("block_k", 512), interpret)


def _flash_kernel_lax(q, k, v, bias=None, *, causal=False, scale=None):
    return _lax_flash_fwd(q, k, v, bias, scale=scale, causal=causal)


def _flash_kernel_reference(q, k, v, bias=None, *, causal=False,
                            scale=None):
    return scaled_dot_product_attention(q, k, v, bias=bias, causal=causal,
                                        scale=scale)


def _flash_sample_inputs(seed):
    b, h, s, d = ((1, 2, 64, 32), (2, 2, 128, 64), (1, 4, 320, 64))[
        seed % 3]
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    return ((jax.random.normal(kq, (b, h, s, d), jnp.float32),
             jax.random.normal(kk, (b, h, s, d), jnp.float32),
             jax.random.normal(kv, (b, h, s, d), jnp.float32)),
            {"causal": True})


def _flash_tune_signature(args, kwargs):
    q, k = args[0], args[1]
    b, h, sq, d = q.shape
    return (("bh", b * h), ("q", sq), ("k", k.shape[2]), ("d", d))


def _flash_vmem_estimate(args, kwargs, blocks):
    d = args[0].shape[-1]
    bq = blocks.get("block_q", 512)
    bk = blocks.get("block_k", 512)
    # fp32 working set: q + acc, k + v, s + p, m/l lane scratch
    return 4 * (2 * bq * d + 2 * bk * d + 2 * bq * bk + 2 * bq * 128)


def _register_flash_kernel():
    from paddle_tpu import kernels
    kernels.register(kernels.KernelSpec(
        name="flash_attention",
        contract=kernels.KernelContract(
            version=1,
            arg_layouts={"q": "(B,H,Sq,D)", "k": "(B,H,Sk,D)",
                         "v": "(B,H,Sk,D)",
                         "bias": "(B,H,Sq,Sk) additive, optional"},
            out_layout="(B,H,Sq,D)",
            grid="(B*H, cdiv(Sq,block_q), cdiv(Sk,block_k)) "
                 "kv-arbitrary online softmax",
            block_candidates={"block_q": (512, 256, 128),
                              "block_k": (512, 256, 128)},
            atol=2e-5, rtol=2e-5),
        pallas_fn=_flash_kernel_pallas,
        lax_fn=_flash_kernel_lax,
        reference_fn=_flash_kernel_reference,
        sample_inputs=_flash_sample_inputs,
        pallas_sites=("paddle_tpu.ops.attention:_flash_fwd",
                      "paddle_tpu.ops.attention:_flash_bwd"),
        tune_signature=_flash_tune_signature,
        vmem_estimate=_flash_vmem_estimate))


_register_flash_kernel()
