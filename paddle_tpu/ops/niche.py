"""The last of the reference's op surface: eight niche root ops.

Reference kernels (all CPU-only or CPU+CUDA in the reference):
- ``operators/sample_logits_op.cc`` + ``math/sample_prob.h`` (sampled
  softmax preparation)
- ``operators/unpool_op.cc`` + ``math/unpooling.cc`` (max-unpool by index)
- ``operators/spp_op.cc`` (spatial pyramid pooling)
- ``operators/conv_shift_op.cc`` (NTM circular correlation)
- ``operators/tree_conv_op.cc`` + ``math/tree2col.cc`` (tree-based conv)
- ``operators/var_conv_2d_op.cc`` (variable-size conv over LoD images)
- ``operators/modified_huber_loss_op.cc``
- ``operators/sequence_ops/sequence_topk_avg_pooling_op.cc``

TPU-native design notes: every op here is static-shape (padded + masked
where the reference used LoD), jittable except :func:`tree_conv`'s patch
construction, which is data-dependent graph traversal done host-side in
numpy (the reference kernel is likewise CPU-only; the differentiable
contraction runs in XLA).
"""

from __future__ import annotations

import math as _pymath

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


# ---------------------------------------------------------------------------
# modified_huber_loss
# ---------------------------------------------------------------------------

@register_op("modified_huber_loss",
             reference=lambda x, y: np.where(
                 x * (2 * y - 1) < -1, -4 * x * (2 * y - 1),
                 np.where(x * (2 * y - 1) < 1,
                          (1 - x * (2 * y - 1)) ** 2, 0.0)))
def modified_huber_loss(x, y):
    """modified_huber_loss_op.h:41: with a = x * (2y - 1),
    loss = -4a if a < -1; (1-a)^2 if -1 <= a < 1; 0 otherwise.
    ``y`` must be {0, 1}. Autodiff reproduces the hand-written grad
    kernel (both branches differentiate the same piecewise form)."""
    a = x * (2.0 * y - 1.0)
    return jnp.where(a < -1.0, -4.0 * a,
                     jnp.where(a < 1.0, (1.0 - a) ** 2, 0.0))


# ---------------------------------------------------------------------------
# unpool (max-unpool-2d)
# ---------------------------------------------------------------------------

@register_op("unpool")
def unpool(x, indices, output_size):
    """Max-unpooling (unpool_op.cc / math/unpooling.cc:21): scatter each
    input value to its recorded argmax position. ``x``/``indices``
    (N, C, h, w) NCHW, ``indices`` flat positions into the unpooled
    (H, W) plane; ``output_size`` (H, W). Positions not hit stay 0."""
    n, c, h, w = x.shape
    oh, ow = output_size
    flat_x = x.reshape(n, c, h * w)
    flat_i = indices.reshape(n, c, h * w).astype(jnp.int32)
    out = jnp.zeros((n, c, oh * ow), x.dtype)
    out = jax.vmap(jax.vmap(lambda o, i, v: o.at[i].set(v)))(
        out, flat_i, flat_x)
    return out.reshape(n, c, oh, ow)


# ---------------------------------------------------------------------------
# spp (spatial pyramid pooling)
# ---------------------------------------------------------------------------

def _pool_level(x, ksize, stride, pad, pooling_type):
    """One pyramid level: NCHW window-reduce with the reference's
    exclusive-average semantics (pad cells don't count in the divisor)."""
    kh, kw = ksize
    sh, sw = stride
    ph, pw = pad
    dims = (1, 1, kh, kw)
    strides = (1, 1, sh, sw)
    padding = ((0, 0), (0, 0), (ph, ph), (pw, pw))
    if pooling_type == "max":
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, dims, strides, padding)
    s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, padding)
    ones = jnp.ones(x.shape[2:], x.dtype)
    cnt = jax.lax.reduce_window(
        ones, 0.0, jax.lax.add, (kh, kw), (sh, sw),
        ((ph, ph), (pw, pw)))
    return s / cnt[None, None]


@register_op("spp")
def spp(x, pyramid_height, pooling_type="max"):
    """Spatial pyramid pooling (spp_op.h:28): level p pools into
    2^p x 2^p bins with kernel ceil(dim/bins), pad
    (kernel*bins - dim + 1)//2, stride = kernel; levels are flattened
    and concatenated -> (N, C * sum_p 4^p)."""
    n, c, h, w = x.shape
    outs = []
    for p in range(pyramid_height):
        bins = 2 ** p
        kh = _pymath.ceil(h / bins)
        kw = _pymath.ceil(w / bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        lvl = _pool_level(x, (kh, kw), (kh, kw), (ph, pw), pooling_type)
        outs.append(lvl.reshape(n, c * bins * bins))
    return jnp.concatenate(outs, axis=1)


# ---------------------------------------------------------------------------
# conv_shift (circular correlation)
# ---------------------------------------------------------------------------

def _conv_shift_ref(x, y):
    b, m = x.shape
    _, n = y.shape
    half = (n - 1) // 2
    out = np.zeros_like(x)
    for i in range(m):
        for j in range(-half, half + 1):
            out[:, i] += x[:, (i + j) % m] * y[:, j + half]
    return out


@register_op("conv_shift", reference=_conv_shift_ref)
def conv_shift(x, y):
    """Circular correlation (conv_shift_op.cc:101, NTM attention shift):
    Out[i] = sum_{j=-(N-1)/2}^{(N-1)/2} X[(i+j) mod M] * Y[j + (N-1)/2].
    ``x`` (B, M), ``y`` (B, N) with N odd, N <= M."""
    m = x.shape[1]
    n = y.shape[1]
    if n % 2 != 1:
        raise ValueError(f"conv_shift filter width must be odd, got {n}")
    if n > m:
        raise ValueError(f"conv_shift filter width {n} exceeds data "
                         f"width {m}")
    half = (n - 1) // 2
    # gather matrix of circular indices: idx[j, i] = (i + j - half) mod M
    idx = (jnp.arange(m)[None, :] + jnp.arange(n)[:, None] - half) % m
    gathered = x[:, idx]                       # (B, N, M)
    return jnp.einsum("bnm,bn->bm", gathered, y)


# ---------------------------------------------------------------------------
# tree_conv
# ---------------------------------------------------------------------------

def _tree_patch_weights(edges, num_nodes, max_depth):
    """Host-side tree2col (math/tree2col.cc:82): DFS patch per root with
    continuous-binary-tree weights eta_t/l/r. Returns (P, N, 3) float32
    where row p holds node weights for root p+1 (1-based nodes)."""
    # directed parent->child adjacency (Tree2ColUtil::construct_tree
    # inserts only the (parent, child) edge), so the DFS from each root
    # visits descendants only and pclen is the parent's child count
    tr = [[] for _ in range(num_nodes + 1)]
    for a, b in np.asarray(edges).reshape(-1, 2):
        a, b = int(a), int(b)
        if a == 0 or b == 0:
            continue  # padded edge rows (construct_tree stops at any
            # zero endpoint — node ids are 1-based)
        tr[a].append(b)

    weights = np.zeros((num_nodes, num_nodes, 3), np.float32)

    def eta(index, pclen, depth):
        eta_t = (max_depth - depth) / max_depth
        if pclen == 1:
            tmp = 0.5
        else:
            tmp = (index - 1.0) / (pclen - 1.0)
        eta_l = (1.0 - eta_t) * tmp
        eta_r = (1.0 - eta_t) * (1.0 - eta_l)
        return eta_l, eta_r, eta_t

    for root in range(1, num_nodes + 1):
        # iterative DFS mirroring Tree2ColUtil::construct_patch
        visited = {root}
        stack = [(root, 1, 1, 0)]
        patch = [(root, 1, 1, 0)]
        while stack:
            node, _, _, depth = stack[-1]
            advanced = False
            children = tr[node]
            for i, v in enumerate(children):
                if v not in visited and depth + 1 < max_depth:
                    visited.add(v)
                    stack.append((v, i, len(children), depth + 1))
                    patch.append((v, i + 1, len(children), depth + 1))
                    advanced = True
            if not advanced:
                stack.pop()
        for node, index, pclen, depth in patch:
            el, er, et = eta(index, pclen, depth)
            weights[root - 1, node - 1, 0] += el
            weights[root - 1, node - 1, 1] += er
            weights[root - 1, node - 1, 2] += et
    return weights


@register_op("tree_conv")
def tree_conv(nodes_vector, edge_set, filter, max_depth=2):
    """Tree-based convolution (tree_conv_op.h:27, arXiv:1409.5718):
    ``nodes_vector`` (B, N, F); ``edge_set`` (B, E, 2) int 1-based
    (0,0 rows = padding); ``filter`` (F, 3, out_size, num_filters).
    Returns (B, N, out_size, num_filters).

    The DFS patch construction is data-dependent -> runs host-side in
    numpy (the reference kernel is CPU-only for the same reason); the
    contraction is XLA and differentiable wrt nodes_vector and filter."""
    b, n, f = nodes_vector.shape
    ws = np.stack([
        _tree_patch_weights(np.asarray(edge_set[i]), n, max_depth)
        for i in range(b)])                          # (B, N, N, 3)
    ws = jnp.asarray(ws)
    # patch[b, p, f, c] = sum_v ws[b, p, v, c] * nodes[b, v, f]
    patch = jnp.einsum("bpvc,bvf->bpfc", ws, nodes_vector)
    return jnp.einsum("bpfc,fcom->bpom", patch, filter)


# ---------------------------------------------------------------------------
# var_conv_2d
# ---------------------------------------------------------------------------

@register_op("var_conv_2d")
def var_conv_2d(x, row_lens, col_lens, w, *, input_channel, output_channel,
                kernel_h=3, kernel_w=3, stride_h=1, stride_w=1):
    """Variable-size conv (var_conv_2d_op.cc:121). The reference packs
    each sample's (h_i, w_i) image in a LoD tensor; here samples ride a
    padded canvas ``x`` (B, C, Hmax, Wmax) with ``row_lens``/``col_lens``
    (B,) giving true sizes. Kernel centers sit on a stride grid with
    half-kernel zero borders (out-of-bounds taps read 0, exactly the
    reference's im2col), output (B, OC, ceil(Hmax/sh), ceil(Wmax/sw))
    masked to each sample's ceil(h_i/sh) x ceil(w_i/sw) region.

    ``w`` is the reference layout (OC, C*kh*kw)."""
    bsz, c, hm, wm = x.shape
    if c != input_channel:
        raise ValueError(f"x has {c} channels, expected {input_channel}")
    half_h, half_w = kernel_h // 2, kernel_w // 2
    out_h = (hm - 1) // stride_h + 1
    out_w = (wm - 1) // stride_w + 1

    # zero beyond each sample's true extent (reference reads 0 there)
    rmask = jnp.arange(hm)[None, :] < row_lens[:, None]       # (B, Hm)
    cmask = jnp.arange(wm)[None, :] < col_lens[:, None]       # (B, Wm)
    x = x * (rmask[:, None, :, None] & cmask[:, None, None, :])

    # pad so window i starts at i*stride - half_kernel
    pad_h_hi = max(0, (out_h - 1) * stride_h - half_h + kernel_h - hm)
    pad_w_hi = max(0, (out_w - 1) * stride_w - half_w + kernel_w - wm)
    kernel = w.reshape(output_channel, input_channel, kernel_h, kernel_w)
    out = jax.lax.conv_general_dilated(
        x, kernel, (stride_h, stride_w),
        ((half_h, pad_h_hi), (half_w, pad_w_hi)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))

    # mask outputs beyond each sample's top grid
    to_h = jnp.where(row_lens > 0, (row_lens - 1) // stride_h + 1, 0)
    to_w = jnp.where(col_lens > 0, (col_lens - 1) // stride_w + 1, 0)
    omask = ((jnp.arange(out_h)[None, :] < to_h[:, None])[:, None, :, None]
             & (jnp.arange(out_w)[None, :] < to_w[:, None])[:, None, None, :])
    return out * omask


# ---------------------------------------------------------------------------
# sample_logits
# ---------------------------------------------------------------------------

def _tolerable(v):
    """TolerableValue (sample_logits_op.h): clamp inf/nan like the
    reference does before/after the logQ subtraction."""
    big = jnp.asarray(1e20, v.dtype)
    v = jnp.where(jnp.isnan(v), jnp.zeros_like(v), v)
    return jnp.clip(v, -big, big)


@register_op("sample_logits", has_grad=False)
def sample_logits(logits, labels, num_samples, rng=None, *,
                  remove_accidental_hits=True, customized_samples=None,
                  customized_probabilities=None):
    """Sampled-softmax preparation (sample_logits_op.h:148): returns
    (samples (B, T+S), probabilities (B, T+S), sampled_logits (B, T+S),
    sampled_labels (B, T) = arange(T)).

    Negatives follow the log-uniform class distribution
    Q(c) = log((c+2)/(c+1)) / log(range+1) (math/sampler.cc:56), drawn
    with replacement and SHARED across the batch exactly like the
    reference: SampleWithProb's sampling loop writes each drawn v into
    every row (sample_prob.h:78-92), and the CUDA kernel copies row 0's
    columns to all rows (sample_prob.cu:86). Q is scaled by num_samples
    (the reference's
    num_tries==num_samples branch of adjust_prob, sample_prob.h:30 —
    its uniquifying retry loop is host-side control flow; here the
    with-replacement closed form keeps the op jittable). Pass
    ``customized_samples``/``customized_probabilities`` to reproduce the
    reference bit-for-bit (use_customized_samples=true path).

    sampled_logits = gather(logits, samples) - log(Q), with accidental
    hits (a negative equal to one of the row's true labels) pushed down
    by 1e20 when ``remove_accidental_hits``."""
    b, num_classes = logits.shape
    num_true = labels.shape[1]
    log_range = jnp.log(jnp.asarray(num_classes + 1.0, logits.dtype))

    def q(v):
        v = v.astype(logits.dtype)
        return jnp.log((v + 2.0) / (v + 1.0)) / log_range

    if customized_samples is not None:
        if customized_probabilities is None:
            raise ValueError("customized_samples requires "
                             "customized_probabilities (the reference's "
                             "use_customized_samples path takes both)")
        samples = customized_samples
        probabilities = customized_probabilities
    else:
        if rng is None:
            raise ValueError("sample_logits needs a PRNG key when not "
                             "given customized_samples")
        u = jax.random.uniform(rng, (num_samples,), logits.dtype)
        # inverse-transform log-uniform (sampler.cc:44); one shared draw
        # broadcast to every row, matching sample_prob.h:78-92
        neg = (jnp.exp(u * log_range) - 1.0).astype(jnp.int32) % num_classes
        samples = jnp.concatenate(
            [labels, jnp.broadcast_to(neg[None, :], (b, num_samples))], 1)
        # adjust_prob, num_tries == num_samples branch (scales all columns)
        probabilities = q(samples) * num_samples

    sampled_logits = jnp.take_along_axis(logits, samples, axis=1)
    if remove_accidental_hits:
        negs = samples[:, num_true:]                     # (B, S)
        hit = (negs[:, :, None] == labels[:, None, :]).any(-1)
        sampled_logits = jnp.concatenate(
            [sampled_logits[:, :num_true],
             sampled_logits[:, num_true:] - 1e20 * hit], 1)
    sampled_logits = _tolerable(
        sampled_logits - _tolerable(jnp.log(probabilities)))
    sampled_labels = jnp.broadcast_to(jnp.arange(num_true)[None, :],
                                      (b, num_true))
    return samples, probabilities, sampled_logits, sampled_labels


# ---------------------------------------------------------------------------
# sequence_topk_avg_pooling
# ---------------------------------------------------------------------------

@register_op("sequence_topk_avg_pooling")
def sequence_topk_avg_pooling(x, row_lens, col_lens, *, topks):
    """sequence_topk_avg_pooling_op.h:64: per (sample, row, channel),
    take the top-k values over the row's valid columns and emit their
    average for each k in ``topks`` — dividing by k even when fewer than
    k columns are valid (the reference saturates the running sum).

    Dense layout: ``x`` (B, C, Rmax, Cmax) with ``row_lens``/``col_lens``
    (B,) valid extents; returns (B, Rmax, C, len(topks)) with rows past
    ``row_lens`` zeroed (the reference's LoD output only materializes
    valid rows)."""
    b, c, rm, cm = x.shape
    topks = tuple(int(k) for k in topks)
    max_k = max(topks)
    if max_k > cm:
        raise ValueError(f"topks={topks} exceed column capacity {cm}")
    colmask = jnp.arange(cm)[None, :] < col_lens[:, None]     # (B, Cm)
    neg = jnp.asarray(-jnp.inf, x.dtype)
    masked = jnp.where(colmask[:, None, None, :], x, neg)
    top = jax.lax.top_k(masked, max_k)[0]                     # (B,C,Rm,K)
    # saturating prefix sum: invalid slots contribute 0
    contrib = jnp.where(jnp.isfinite(top), top, 0.0)
    csum = jnp.cumsum(contrib, axis=-1)
    ks = jnp.asarray(topks) - 1
    avg = csum[..., ks] / jnp.asarray(topks, x.dtype)         # (B,C,Rm,k)
    rowmask = jnp.arange(rm)[None, :] < row_lens[:, None]     # (B, Rm)
    avg = avg * rowmask[:, None, :, None]
    return jnp.transpose(avg, (0, 2, 1, 3))                   # (B,Rm,C,k)
