"""Tensor manipulation ops (reference: fluid's concat/split/reshape/transpose/
gather/scatter/top_k/argsort/cast/fill/assign op families in
``paddle/fluid/operators/``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op
from paddle_tpu.core.dtypes import convert_dtype


@register_op("concat", reference=lambda xs, axis=0: np.concatenate(xs, axis))
def concat(xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


@register_op("split")
def split(x, num_or_sections, axis=0):
    """fluid split_op: int -> equal parts; list -> section sizes."""
    if isinstance(num_or_sections, int):
        return jnp.split(x, num_or_sections, axis=axis)
    bounds = np.cumsum(num_or_sections)[:-1].tolist()
    return jnp.split(x, bounds, axis=axis)


@register_op("stack", reference=lambda xs, axis=0: np.stack(xs, axis))
def stack(xs, axis=0):
    return jnp.stack(xs, axis=axis)


@register_op("unstack", has_grad=True)
def unstack(x, axis=0):
    return [jnp.squeeze(s, axis) for s in jnp.split(x, x.shape[axis], axis)]


@register_op("reshape", reference=lambda x, shape: np.reshape(x, shape))
def reshape(x, shape):
    return jnp.reshape(x, shape)


@register_op("squeeze", reference=lambda x, axes=None: np.squeeze(x, tuple(axes) if axes else None))
def squeeze(x, axes=None):
    return jnp.squeeze(x, tuple(axes) if axes else None)


@register_op("unsqueeze", reference=lambda x, axes: np.expand_dims(x, tuple(axes) if isinstance(axes, (list, tuple)) else axes))
def unsqueeze(x, axes):
    return jnp.expand_dims(x, tuple(axes) if isinstance(axes, (list, tuple)) else axes)


@register_op("flatten")
def flatten(x, axis=1):
    """fluid flatten_op: collapse dims before/after ``axis`` into a matrix."""
    lead = int(np.prod(x.shape[:axis])) if axis > 0 else 1
    return x.reshape(lead, -1)


@register_op("transpose", reference=lambda x, perm: np.transpose(x, perm))
def transpose(x, perm):
    return jnp.transpose(x, perm)


import builtins


@register_op("slice")
def slice(x, axes, starts, ends):  # noqa: A001 - fluid op name
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = builtins.slice(s, e)
    return x[tuple(idx)]


@register_op("strided_slice")
def strided_slice(x, axes, starts, ends, strides):
    idx = [builtins.slice(None)] * x.ndim
    for ax, s, e, st in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(s, e, st)
    return x[tuple(idx)]


@register_op("gather", reference=lambda x, index: np.take(x, index, 0))
def gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


@register_op("gather_nd")
def gather_nd(x, index):
    return x[tuple(jnp.moveaxis(index, -1, 0))]


@register_op("scatter")
def scatter(x, index, updates, overwrite=True):
    """fluid scatter_op: write rows of ``updates`` at ``index``."""
    if overwrite:
        return x.at[index].set(updates)
    return x.at[index].add(updates)


@register_op("scatter_nd_add")
def scatter_nd_add(x, index, updates):
    return x.at[tuple(jnp.moveaxis(index, -1, 0))].add(updates)


@register_op("top_k", has_grad=False)
def top_k(x, k):
    return jax.lax.top_k(x, k)


@register_op("argsort", has_grad=False,
             reference=lambda x, axis=-1: (np.sort(x, axis), np.argsort(x, axis, kind="stable")))
def argsort(x, axis=-1):
    idx = jnp.argsort(x, axis=axis, stable=True)
    return jnp.take_along_axis(x, idx, axis=axis), idx


@register_op("argmax", has_grad=False, reference=lambda x, axis=-1: np.argmax(x, axis))
def argmax(x, axis=-1):
    return jnp.argmax(x, axis=axis)


@register_op("argmin", has_grad=False, reference=lambda x, axis=-1: np.argmin(x, axis))
def argmin(x, axis=-1):
    return jnp.argmin(x, axis=axis)


@register_op("cast", reference=lambda x, dtype: np.asarray(x).astype(dtype))
def cast(x, dtype):
    return x.astype(convert_dtype(dtype))


@register_op("fill_constant", has_grad=False)
def fill_constant(shape, dtype, value):
    return jnp.full(shape, value, dtype=convert_dtype(dtype))


@register_op("zeros_like", has_grad=False, reference=np.zeros_like)
def zeros_like(x):
    return jnp.zeros_like(x)


@register_op("ones_like", has_grad=False, reference=np.ones_like)
def ones_like(x):
    return jnp.ones_like(x)


@register_op("assign", reference=np.asarray)
def assign(x):
    return jnp.asarray(x)


@register_op("expand", reference=lambda x, times: np.tile(x, times))
def expand(x, expand_times):
    return jnp.tile(x, expand_times)


@register_op("expand_as")
def expand_as(x, target):
    return jnp.broadcast_to(x, target.shape)


@register_op("tile", reference=np.tile)
def tile(x, reps):
    return jnp.tile(x, reps)


@register_op("where", reference=np.where)
def where(cond, x, y):
    return jnp.where(cond, x, y)


@register_op("masked_select", has_grad=False)
def masked_select(x, mask, size=None):
    """Static-shape variant: requires ``size`` (XLA has no dynamic output
    shapes); pads with zeros. fluid's masked_select is dynamic."""
    if size is None:
        raise ValueError("TPU masked_select needs a static `size`")
    idx = jnp.nonzero(mask.reshape(-1), size=size, fill_value=0)[0]
    return x.reshape(-1)[idx]


@register_op("range", has_grad=False, reference=lambda s, e, st: np.arange(s, e, st))
def arange(start, end, step=1, dtype=jnp.int32):
    return jnp.arange(start, end, step, dtype=convert_dtype(dtype))


@register_op("linspace", has_grad=False)
def linspace(start, stop, num, dtype=jnp.float32):
    return jnp.linspace(start, stop, num, dtype=convert_dtype(dtype))


@register_op("shape", has_grad=False)
def shape(x):
    return jnp.asarray(x.shape, dtype=jnp.int32)


@register_op("eye", has_grad=False)
def eye(num_rows, num_cols=None, dtype=jnp.float32):
    return jnp.eye(num_rows, num_cols, dtype=convert_dtype(dtype))


@register_op("diag", has_grad=False)
def diag(x):
    return jnp.diag(x)


@register_op("flip", reference=lambda x, axis: np.flip(x, axis))
def flip(x, axis):
    return jnp.flip(x, axis)


@register_op("roll")
def roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis)


@register_op("clip_by_norm")
def clip_by_norm(x, max_norm):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    return jnp.where(norm > max_norm, x * (max_norm / norm), x)


@register_op("isfinite", has_grad=False, reference=np.isfinite)
def isfinite(x):
    return jnp.isfinite(x)


@register_op("isnan", has_grad=False, reference=np.isnan)
def isnan(x):
    return jnp.isnan(x)


@register_op("increment")
def increment(x, value=1.0):
    return x + value


@register_op("accuracy", has_grad=False)
def accuracy(logits_or_topk, label, k=1):
    """fluid accuracy_op (operators/metrics/accuracy_op)."""
    _, pred = jax.lax.top_k(logits_or_topk, k)
    lbl = label.reshape(-1, 1)
    correct = jnp.any(pred == lbl, axis=1)
    return jnp.mean(correct.astype(jnp.float32))
