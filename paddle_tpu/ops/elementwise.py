"""Broadcasted elementwise binary ops.

Reference: ``paddle/fluid/operators/elementwise/`` (34 files, hand-rolled
broadcast engine in ``elementwise_op_function.h``). On TPU the entire
broadcast machinery is XLA's — these are thin registrations so the op
surface, OpTest coverage, and ``axis``-style broadcasting parity exist.

Fluid's ``axis`` attribute aligns y's dims starting at ``axis`` of x
(e.g. x:[N,C,H,W], y:[C], axis=1). We reproduce that by reshaping y.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.registry import register_op


def _align(x, y, axis):
    """Expand y to x's rank with fluid's axis semantics."""
    if axis == -1 or x.ndim == y.ndim:
        return y
    trailing = x.ndim - axis - y.ndim
    if trailing < 0:
        raise ValueError(f"bad axis {axis} for shapes {x.shape}, {y.shape}")
    return y.reshape(y.shape + (1,) * trailing)


def _np_align(x, y, axis):
    x, y = np.asarray(x), np.asarray(y)
    if axis == -1 or x.ndim == y.ndim:
        return y
    return y.reshape(y.shape + (1,) * (x.ndim - axis - y.ndim))


def _make(name, fn, np_fn):
    def ref(x, y, axis=-1):
        return np_fn(x, _np_align(x, y, axis))

    @register_op(f"elementwise_{name}", reference=ref)
    def op(x, y, axis=-1):
        return fn(x, _align(x, jnp.asarray(y), axis))

    op.__name__ = f"elementwise_{name}"
    op.__doc__ = f"Broadcasted elementwise {name} (fluid elementwise_{name}_op)."
    return op


add = _make("add", jnp.add, np.add)
sub = _make("sub", jnp.subtract, np.subtract)
mul = _make("mul", jnp.multiply, np.multiply)
div = _make("div", jnp.divide, np.divide)
floordiv = _make("floordiv", jnp.floor_divide, np.floor_divide)
mod = _make("mod", jnp.mod, np.mod)
max = _make("max", jnp.maximum, np.maximum)
min = _make("min", jnp.minimum, np.minimum)
pow = _make("pow", jnp.power, np.power)
