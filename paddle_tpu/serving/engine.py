"""ServingEngine: continuous-batching GPT inference over a paged KV cache.

The serving loop is ONE jit-compiled fixed-shape decode step — every
slot advances a BLOCK of ``decode_block`` tokens per call (an on-device
``fori_loop``, amortizing the host round-trip), attending over its own
pages via ``decode_attention.ragged_paged_decode_attention`` — plus a
fixed-shape chunked-prefill step that feeds prompts into freed slots.
All shapes are static: ``num_slots``, the prefill chunk, and a pow2-
bucketed block-table gather width that tracks the LIVE high-water mark
(so decode work follows live tokens, not slot capacity, even on the lax
fallback). The cache pages are **donated** into both steps, and
:meth:`ServingEngine.warmup` precompiles every bucket, so steady-state
serving triggers zero recompiles and zero cache copies — a
:class:`~paddle_tpu.observability.RecompileDetector` wired to the step
proves it.

Decode work per block is O(live tokens) — a slot holding a 16-token
sequence reads 1 page while its neighbour reads 16 — versus the dense
``generate(use_cache=True)`` loop's O(batch × max_len) padded attention.

Metrics (observability registry): ``serving_requests_total``,
``serving_tokens_total``, ``serving_prefill_tokens_total``,
``serving_steps_total``, ``serving_ttft_seconds``,
``serving_queue_wait_seconds``, ``serving_slot_occupancy``,
``serving_page_utilization``, plus ``serving_decode_recompiles_total``
via the detector.
"""

from __future__ import annotations

import time
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.serving import decode_attention as DA
from paddle_tpu.serving.paged_cache import PagedCacheConfig, PagedKVCache
from paddle_tpu.serving.scheduler import ContinuousBatchingScheduler


class ServingEngine:
    """Continuous-batching front end over a ``models.gpt.GPT``.

    ``submit()`` enqueues a request, ``step()`` advances every live slot
    one token (admitting queued requests into freed slots first), and
    ``generate_many()`` drives the loop to completion. Decoding is
    greedy — the deterministic serving mode the paged-vs-dense parity
    tests pin down.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_tokens_per_slot: Optional[int] = None,
                 prefill_chunk: int = 32, decode_block: int = 8,
                 attn_impl: str = "auto", cache_dtype=None,
                 registry=None):
        cfg = model.cfg
        if cfg.pipeline or cfg.stacked_layers:
            raise ValueError(
                "ServingEngine needs the LayerList GPT layout; convert "
                "stacked/pipeline checkpoints for serving first")
        self.model = model
        self.params = params
        self.attn_impl = attn_impl
        self.prefill_chunk = int(prefill_chunk)
        self.decode_block = max(int(decode_block), 1)
        if max_tokens_per_slot is None:
            max_tokens_per_slot = cfg.max_position
        max_pages_per_slot = -(-max_tokens_per_slot // page_size)
        if num_pages is None:
            # enough for every slot full, +1 null page — callers can size
            # DOWN to bet on early EOS (that is the paging win)
            num_pages = num_slots * max_pages_per_slot + 1
        # like generate(cache_dtype=...): a bf16 page pool halves KV
        # gather traffic (softmax still runs fp32 inside the kernel)
        dtype = cache_dtype or params["wte"]["weight"].dtype
        self.cache = PagedKVCache(PagedCacheConfig(
            num_layers=cfg.num_layers, num_heads=cfg.num_heads,
            head_dim=cfg.hidden_size // cfg.num_heads,
            num_slots=num_slots, page_size=page_size, num_pages=num_pages,
            max_pages_per_slot=max_pages_per_slot, dtype=dtype))
        self.scheduler = ContinuousBatchingScheduler(
            num_slots, can_admit=self._can_admit)

        from paddle_tpu import observability as obs
        self._reg = registry or obs.default()
        self.recompile_detector = obs.RecompileDetector(
            "serving_decode", warmup=1, registry=self._reg)

        self.decode_step = jax.jit(self._decode_step_impl,
                                   donate_argnums=(1,))
        self.prefill_step = jax.jit(self._prefill_chunk_impl,
                                    donate_argnums=(1,))
        # finished-request store for result(); pop-on-read + bounded, so
        # a server that only consumes step()'s return dict still cannot
        # grow host memory with the total requests ever served
        self._results: "OrderedDict[int, np.ndarray]" = OrderedDict()
        self._results_cap = max(64, 16 * num_slots)

    # -- request surface --------------------------------------------------

    def _can_admit(self, req) -> bool:
        return self.cache.can_reserve(req.total_tokens)

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_id: Optional[int] = None) -> int:
        total = len(np.asarray(prompt).reshape(-1)) + max_new_tokens
        limit = min(self.cache.config.max_tokens_per_slot,
                    self.model.cfg.max_position)
        if total > limit:
            raise ValueError(f"request needs {total} tokens > per-slot "
                             f"limit {limit}")
        if self.cache.config.pages_for(total) > self.cache.config.num_pages - 1:
            raise ValueError("request exceeds the whole page pool")
        rid = self.scheduler.submit(prompt, max_new_tokens, eos_id)
        self._reg.counter("serving_requests_total",
                          "requests submitted to the engine").inc()
        return rid

    def result(self, rid: int) -> Optional[np.ndarray]:
        """Generated tokens for a finished request (None while running
        or already consumed). Pop-on-read, and the store keeps only the
        most recent finishers (``step()``'s return dict is the primary
        delivery path) — consume results promptly."""
        return self._results.pop(rid, None)

    # -- engine loop ------------------------------------------------------

    def step(self) -> Dict[int, np.ndarray]:
        """One engine iteration: admit+prefill into free slots, advance
        every decoding slot one token, evict finished sequences. Returns
        ``{rid: generated tokens}`` for requests that finished now."""
        finished: Dict[int, np.ndarray] = {}
        while True:  # admissions can cascade as early-EOS slots free up
            # pages are reserved inside the admit callback, so each
            # can_admit check sees the pool net of earlier admissions
            # in the same call (no over-commit on a down-sized pool)
            admitted = self.scheduler.admit(
                on_admit=lambda slot, req: self.cache.reserve(
                    slot, req.total_tokens))
            if not admitted:
                break
            for slot in admitted:
                self._prefill_slot(slot)
            finished.update(self._evict())

        dslots = self.scheduler.decode_slots()
        if dslots:
            # occupancy/utilization of the batch the decode step
            # actually runs with (recorded before eviction, which
            # empties finished slots' lengths)
            self._reg.gauge("serving_slot_occupancy",
                            "fraction of decode slots live").set(
                                len(dslots) / self.scheduler.num_slots)
            self._reg.gauge("serving_page_utilization",
                            "live tokens / page-pool capacity").set(
                                self.cache.utilization())
            n = self.decode_block
            s_tot = self.scheduler.num_slots
            tokens = np.zeros((s_tot,), np.int32)
            for i in dslots:
                tokens[i] = self.scheduler.slots[i].generated[-1]
            w = self._gather_width(dslots)
            t0 = time.monotonic()
            out, self.cache.pages = self.decode_step(
                self.params, self.cache.pages,
                jnp.asarray(self.cache.block_tables[:, :w]),
                jnp.asarray(self.cache.lengths), jnp.asarray(tokens))
            out = np.asarray(out)                    # (S, decode_block)
            self._reg.histogram(
                "serving_decode_step_seconds",
                "wall time per decode block (sync included)").observe(
                    time.monotonic() - t0)
            kept = 0
            for i in dslots:
                st = self.scheduler.slots[i]
                req = st.request
                budget = req.max_new_tokens - len(st.generated)
                for j in range(min(n, budget)):
                    tok = int(out[i, j])
                    st.generated.append(tok)
                    kept += 1
                    if req.eos_id is not None and tok == req.eos_id:
                        break
                if not st.finished():
                    # device advanced this slot the full block
                    self.cache.lengths[i] += n
            self._reg.counter("serving_tokens_total",
                              "decode tokens produced").inc(kept)
            self._reg.counter("serving_steps_total").inc()
            self.recompile_detector.check()
            finished.update(self._evict())

        return finished

    def generate_many(self, prompts: Sequence, max_new_tokens: int = 32,
                      eos_id: Optional[int] = None,
                      max_steps: Optional[int] = None) -> List[np.ndarray]:
        """Submit ``prompts`` and run the loop until all finish; returns
        each request's generated tokens in submission order."""
        rids = [self.submit(p, max_new_tokens, eos_id) for p in prompts]
        collected: Dict[int, np.ndarray] = {}
        steps = 0
        while not self.scheduler.idle():
            collected.update(self.step())
            steps += 1
            if max_steps is not None and steps > max_steps:
                raise RuntimeError(f"no convergence in {max_steps} steps")
        for r in rids:          # consumed here; drop from the store
            self._results.pop(r, None)
        return [collected[r] for r in rids]

    def _evict(self) -> Dict[int, np.ndarray]:
        out = {}
        for slot, st in self.scheduler.evict_finished().items():
            self.cache.free_slot(slot)
            toks = np.asarray(st.generated, np.int32)
            self._results[st.request.rid] = toks
            out[st.request.rid] = toks
        while len(self._results) > self._results_cap:
            self._results.popitem(last=False)   # oldest unconsumed
        return out

    # -- prefill ----------------------------------------------------------

    def _prefill_slot(self, slot: int):
        """Feed an admitted slot's prompt through the chunked prefill
        step (its pages were already reserved at admission)."""
        st = self.scheduler.slots[slot]
        req = st.request
        self._reg.histogram(
            "serving_queue_wait_seconds",
            "submit -> slot admission wait").observe(
                max(st.admitted_at - req.submitted_at, 0.0))
        prompt = req.prompt
        c = self.prefill_chunk
        bt_row = jnp.asarray(self.cache.block_tables[slot])
        nxt = None
        t0 = time.monotonic()
        for lo in range(0, prompt.shape[0], c):
            chunk = prompt[lo:lo + c]
            n_valid = chunk.shape[0]
            if n_valid < c:
                chunk = np.pad(chunk, (0, c - n_valid))
            nxt, self.cache.pages = self.prefill_step(
                self.params, self.cache.pages, bt_row,
                jnp.asarray(lo, jnp.int32), jnp.asarray(chunk),
                jnp.asarray(n_valid, jnp.int32))
            self.cache.lengths[slot] += n_valid
            st.prefilled += n_valid
        st.generated.append(int(nxt))
        st.first_token_at = time.monotonic()
        self._reg.histogram(
            "serving_prefill_seconds",
            "wall time prefilling one request (all chunks)").observe(
                st.first_token_at - t0)
        self._reg.histogram("serving_ttft_seconds",
                            "submit -> first token latency").observe(
                                st.first_token_at - req.submitted_at)
        self._reg.counter("serving_prefill_tokens_total").inc(
            int(prompt.shape[0]))
        self._reg.counter("serving_tokens_total").inc()

    def _gather_width(self, dslots) -> int:
        """Pow2 page count covering every active slot through one decode
        block — the lax gather (and the Pallas grid) then scale with the
        LIVE high-water mark, not full slot capacity. Pow2 bucketing
        keeps the set of compiled shapes log-sized; :meth:`warmup`
        precompiles them all."""
        c = self.cache.config
        max_len = max(int(self.cache.lengths[i]) for i in dslots)
        need = c.pages_for(max_len + self.decode_block)
        w = 1
        while w < need:
            w *= 2
        return min(w, c.max_pages_per_slot)

    def warmup(self):
        """Compile every decode gather-width bucket and the prefill
        chunk up front (all against the null page — no live state is
        touched), so a serving process takes its compiles at startup and
        the steady-state loop stays at ZERO recompiles."""
        c = self.cache.config
        s_tot = self.scheduler.num_slots
        widths, w = [], 1
        while w < c.max_pages_per_slot:
            widths.append(w)
            w *= 2
        widths.append(c.max_pages_per_slot)
        zeros = jnp.zeros((s_tot,), jnp.int32)
        for w in sorted(set(widths)):
            _, self.cache.pages = self.decode_step(
                self.params, self.cache.pages,
                jnp.zeros((s_tot, w), jnp.int32), zeros, zeros)
        _, self.cache.pages = self.prefill_step(
            self.params, self.cache.pages,
            jnp.zeros((c.max_pages_per_slot,), jnp.int32),
            jnp.asarray(0, jnp.int32),
            jnp.zeros((self.prefill_chunk,), jnp.int32),
            jnp.asarray(1, jnp.int32))

    # -- jitted step bodies ----------------------------------------------

    def _decode_step_impl(self, params, pages, block_tables, lengths,
                          tokens):
        """Fixed-shape batched decode of ONE BLOCK of ``decode_block``
        tokens per slot: each inner iteration enters every slot's
        current token at position ``lengths[s]``, lands its K/V in the
        slot's current page, and attends ragged-paged over live pages
        only — one host round-trip per block instead of per token.
        Inactive slots (length 0) and post-EOS/post-cap lanes write to
        the null page / past their reservation and produce discarded
        garbage (the host keeps only in-budget, pre-EOS tokens).
        Returns (tokens (S, decode_block), pages)."""
        model, cfg = self.model, self.model.cfg
        ps = self.cache.config.page_size
        s_tot = tokens.shape[0]
        w = block_tables.shape[1]
        slot_ids = jnp.arange(s_tot)

        def one_token(pages, lengths, tokens):
            pos = jnp.minimum(lengths, cfg.max_position - 1)
            x = (model.wte(params["wte"], tokens[:, None])
                 + model.wpe(params["wpe"], pos[:, None]))      # (S,1,D)
            page_idx = block_tables[slot_ids,
                                    jnp.minimum(lengths // ps, w - 1)]
            off = lengths % ps
            new_pages = []
            for i, block in enumerate(model.blocks):
                bp = params["blocks"][str(i)]
                h = block.ln1(bp["ln1"], x)
                q, k, v = block.attn.qkv_heads(bp["attn"], h)   # (S,H,1,Dh)
                kp, vp = pages[i]
                kp = kp.at[page_idx, off].set(
                    k[:, :, 0, :].astype(kp.dtype))
                vp = vp.at[page_idx, off].set(
                    v[:, :, 0, :].astype(vp.dtype))
                att = DA.ragged_paged_decode_attention(
                    q[:, :, 0, :], kp, vp, block_tables, lengths + 1,
                    impl=self.attn_impl)                        # (S,H,Dh)
                x = x + block.attn.proj_out(bp["attn"],
                                            att[:, :, None, :])
                x = x + block.mlp(bp["mlp"], block.ln2(bp["ln2"], x))
                new_pages.append((kp, vp))
            x = model.ln_f(params["ln_f"], x)
            logits = jnp.einsum("bd,vd->bv", x[:, 0],
                                params["wte"]["weight"])
            return new_pages, jnp.argmax(logits, -1).astype(jnp.int32)

        out = jnp.zeros((s_tot, self.decode_block), jnp.int32)

        def body(j, carry):
            pages, lengths, tokens, out = carry
            pages, nxt = one_token(pages, lengths, tokens)
            return pages, lengths + 1, nxt, out.at[:, j].set(nxt)

        pages, _, _, out = jax.lax.fori_loop(
            0, self.decode_block, body, (pages, lengths, tokens, out))
        return out, pages

    def _prefill_chunk_impl(self, params, pages, bt_row, start, tokens,
                            n_valid):
        """Fixed-shape chunked prefill for ONE slot: ``tokens`` (C,) at
        positions ``start..start+C-1`` (first ``n_valid`` real, rest
        pad). Writes the chunk's K/V into the slot's pages and attends
        causally over everything cached so far. Returns (greedy next
        token after the chunk's last valid position, pages)."""
        model, cfg = self.model, self.model.cfg
        ps = self.cache.config.page_size
        mp = self.cache.config.max_pages_per_slot
        c = tokens.shape[0]
        positions = start + jnp.arange(c, dtype=jnp.int32)
        pos_e = jnp.minimum(positions, cfg.max_position - 1)
        x = (model.wte(params["wte"], tokens[None, :])
             + model.wpe(params["wpe"], pos_e[None, :]))        # (1,C,D)
        valid = jnp.arange(c) < n_valid
        page_idx = jnp.where(
            valid, bt_row[jnp.minimum(positions // ps, mp - 1)], 0)
        off = positions % ps
        new_pages = []
        for i, block in enumerate(model.blocks):
            bp = params["blocks"][str(i)]
            h = block.ln1(bp["ln1"], x)
            q, k, v = block.attn.qkv_heads(bp["attn"], h)       # (1,H,C,Dh)
            kp, vp = pages[i]
            k_tok = k[0].transpose(1, 0, 2)                     # (C,H,Dh)
            v_tok = v[0].transpose(1, 0, 2)
            kp = kp.at[page_idx, off].set(k_tok.astype(kp.dtype))
            vp = vp.at[page_idx, off].set(v_tok.astype(vp.dtype))
            att = DA.paged_prefill_attention(
                q[0].transpose(1, 0, 2), kp, vp, bt_row, positions)
            x = x + block.attn.proj_out(bp["attn"],
                                        att.transpose(1, 0, 2)[None])
            x = x + block.mlp(bp["mlp"], block.ln2(bp["ln2"], x))
            new_pages.append((kp, vp))
        x = model.ln_f(params["ln_f"], x)
        last = jax.lax.dynamic_index_in_dim(
            x[0], jnp.maximum(n_valid - 1, 0), axis=0, keepdims=False)
        logits = last @ params["wte"]["weight"].T
        return jnp.argmax(logits).astype(jnp.int32), new_pages
